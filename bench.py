"""Benchmark: device-native ES generation throughput on the flagship config.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extras": {...}}

Metric: env-steps/sec/chip (BASELINE.json primary metric) for a full ES
generation — noise-table perturbation, vmapped policy rollouts, centered
ranks, psum'd rank-weighted update — on Pendulum (never terminates, so every
scanned step is a real env step; no done-mask inflation) with a 64x64 MLP,
population 4096, horizon 200: ~819k env steps per generation.

extras: a Humanoid-sized-policy point (SyntheticEnv obs 376 → 256×256 → 17,
the __graft_entry__ flagship shape), a pop-10240 point, and a
physics-on-chip locomotion point (Cheetah2D — never terminates, so its
step counts carry the same honesty property; its MFU counts policy-forward
FLOPs only, not the physics).  "mfu" is policy-forward FLOPs against the
platform roofline: on TPU the fixed v5e bf16 peak (197 TFLOP/s)
regardless of config dtype — one fixed denominator keeps cross-dtype A/B
numbers comparable — and off-TPU this host's MEASURED GEMM ceiling
(obs/profile/roofline.py), tagged ``mfu_basis: cpu_calibrated`` so a
fraction of a loaded host's real capability is never read against
accelerator silicon.  Per-phase achieved rates and the compile ledger
ride each row (``phases`` / ``compile``).  When the TPU path fails the
headline falls back to CPU — decided by the typed device probe
(doctor.check_device: alive-or-wedged in seconds with a no-device /
init-hang / compile-hang / exec-hang reason, recorded in
extras["device_probe"]) rather than discovered by a 480s stage timeout —
and the extras carry the same scaling points measured on the CPU mesh,
each tagged ``cpu_relative: true`` — comparable to each other and to
bench_ab_cpu.jsonl, never to TPU numbers.

vs_baseline: ratio against a reference-style estorch loop measured live on
this host — per-member Python loop, torch CPU MLP forward per step,
gymnasium Pendulum env.step — the architecture SURVEY.md §3.2/§3.3 documents
(single process; the reference scales it by n_proc workers, so divide by
core count for a per-core figure if comparing to the 720-core runs).

Stage protocol (each stage is a child process so a tunnel wedge in one
measurement cannot take down the bench — round-1 lesson):
    bench.py --stage-one '<json cfg>'   measure one config, print one JSON
                                        (add --cpu to force the CPU mesh —
                                        harness validation / relative mode
                                        numbers when the chip is absent)
    bench.py --stage-ab                 run the curated A/B subset (see
                                        AB_MATRIX; not a full cross — e.g.
                                        streamed is f32-only by design),
                                        one JSON line per config as it lands
    bench.py --obs-ab                   telemetry-overhead A/B: spans on vs
                                        off on the headline config (the <2%
                                        observability acceptance gate)
    bench.py --chaos [--selfcheck]      recovery-overhead A/B: a host
                                        process-worker run with a 1-worker-
                                        kill-per-20-generations chaos plan
                                        vs the same run clean — measures
                                        what respawn+retry cost, and proves
                                        participation stays full under
                                        faults.  --selfcheck shrinks it to
                                        the run_lint.sh gate: nonzero exit
                                        when recovery did not actually
                                        recover
    bench.py --async-ab [--selfcheck]   barrier-vs-async scheduler A/B
                                        (estorch_tpu/algo/scheduler.py,
                                        docs/async.md): the same tiny
                                        host run under an identical
                                        deterministic straggler plan,
                                        once through ES.train's barrier
                                        loop and once through the event-
                                        driven fold scheduler — medians
                                        + a noise band learned from
                                        interleaved repeats (obs
                                        regress), gating the >=1.25x
                                        throughput win, step ≈
                                        max(eval, update) from the
                                        per-phase spans, and the zero-
                                        silent-drop fold accounting
    bench.py --regress [BASELINE.json]  perf gate (estorch_tpu/obs/export/
                                        regress.py): measure the headline
                                        config `--repeats` times (fresh
                                        stage children), compare the
                                        median against the committed
                                        BENCH_*.json baseline with a
                                        noise band learned from the
                                        repeats; exit 1 on regression.
                                        Defaults to the newest BENCH_r*
                                        file (add --cpu off-chip — only
                                        gate against a baseline measured
                                        on the same platform)
    bench.py --serve [--selfcheck]      serving A/B (estorch_tpu/serve,
                                        docs/serving.md): export a trained
                                        pendulum bundle, serve it, drive
                                        closed-loop load — dynamic batching
                                        vs the same server at max_batch=1.
                                        Gates bit-exact responses, clean
                                        SIGTERM drain, recompiles ≤
                                        n_buckets; the full form also gates
                                        the ≥3x batching win on a big
                                        (memory-bound) policy.  --selfcheck
                                        shrinks the policy to the
                                        run_lint.sh functional gate
    bench.py                            headline + extras, the driver entry

Every stage child writes a heartbeat file (ESTORCH_OBS_HEARTBEAT →
estorch_tpu/obs/recorder.py): a stage timeout reports the child's last
phase + generation + heartbeat age instead of guessing at a tunnel wedge.
"""

import contextlib
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np


def _load_repo_module(name, *relpath):
    """Load a repo module by FILE, without the package __init__.

    The loaded modules are jax-free, but `import estorch_tpu...`
    executes the package init, which imports jax — and importing jax in
    THIS process would touch the possibly-wedged device runtime before
    the stage protocol's subprocess+timeout isolation can protect us
    (the round-1 lesson the whole stage design exists for).  A direct
    file load keeps one implementation of each protocol while keeping
    the bench driver accelerator-free."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        *relpath)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


obs_recorder = _load_repo_module("_estorch_obs_recorder",
                                 "estorch_tpu", "obs", "recorder.py")
HEARTBEAT_ENV = obs_recorder.HEARTBEAT_ENV
describe_heartbeat = obs_recorder.describe_heartbeat
read_heartbeat = obs_recorder.read_heartbeat


def _load_obs_regress():
    """estorch_tpu/obs/export/regress.py, same jax-free contract."""
    return _load_repo_module("_estorch_obs_regress",
                             "estorch_tpu", "obs", "export", "regress.py")


def _load_doctor():
    """estorch_tpu/doctor.py by file: check_device (the typed staged
    probe the platform decision reads) is stdlib-only — the whole module
    imports jax-free, same contract as the recorder/regress loads."""
    return _load_repo_module("_estorch_doctor", "estorch_tpu", "doctor.py")


# ---------------------------------------------------------------------
# crash-durable scratch: per-driver-pid workdir + stale-artifact sweep
# ---------------------------------------------------------------------

_BENCH_TMP_ROOT = os.path.join(tempfile.gettempdir(), "estorch_bench")


def _bench_workdir() -> str:
    """Per-process scratch dir for crash-durable buffers (the buffered
    fallback stderr, stage heartbeats).  Kept when this process dies a
    fatal-signal death (the diagnostics must survive the crash), removed
    on clean driver exit, and swept by :func:`_sweep_stale_bench_dirs`
    on the NEXT driver run once the owning pid is gone — so crashed runs
    cannot accumulate in the temp dir forever."""
    d = os.path.join(_BENCH_TMP_ROOT, str(os.getpid()))
    os.makedirs(d, exist_ok=True)
    return d


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, someone else's
    return True


def _sweep_stale_bench_dirs() -> None:
    """Remove bench scratch left by CRASHED prior runs: per-pid workdirs
    whose owner is gone, plus the legacy flat-file buffers
    (``bench_stderr_<pid>.log`` / ``bench_hb_<pid>_*.json``) older
    drivers wrote straight into the temp dir."""
    import glob
    import re as _re
    import shutil

    if os.path.isdir(_BENCH_TMP_ROOT):
        for name in os.listdir(_BENCH_TMP_ROOT):
            path = os.path.join(_BENCH_TMP_ROOT, name)
            try:
                pid = int(name)
            except ValueError:
                continue  # not ours to judge
            if not _pid_alive(pid):
                shutil.rmtree(path, ignore_errors=True)
    tmp = tempfile.gettempdir()
    for pattern in ("bench_stderr_*.log", "bench_hb_*.json"):
        for path in glob.glob(os.path.join(tmp, pattern)):
            m = _re.search(r"_(\d+)", os.path.basename(path))
            if m and not _pid_alive(int(m.group(1))):
                try:
                    os.remove(path)
                except OSError:
                    pass


def _cleanup_bench_workdir() -> None:
    """Clean-exit removal of this process's scratch dir (a crash skips
    this by construction — that is the point of the buffers)."""
    import shutil

    shutil.rmtree(os.path.join(_BENCH_TMP_ROOT, str(os.getpid())),
                  ignore_errors=True)

# The XLA:CPU persistent-cache loader logs an E-level machine-feature dump
# even for same-machine pseudo-feature mismatches (+prefer-no-scatter etc.,
# utils/backend.py::enable_compilation_cache docstring).  It dominated the
# committed BENCH_r03.json tail looking like a SIGILL hazard; these markers
# identify its lines so the recorded artifact leads with signal.
_XLA_NOISE_MARKERS = (
    "XLA:CPU AOT result",
    "machine features",
    "Machine type used for XLA:CPU compilation",
)


def _clean_stderr(text: str) -> str:
    """Drop the known-noisy XLA:CPU AOT feature-mismatch dump lines."""
    return "\n".join(
        ln for ln in text.splitlines()
        if not any(m in ln for m in _XLA_NOISE_MARKERS)
    )


@contextlib.contextmanager
def _filtered_stderr():
    """Buffer OUR process's fd-2 for the duration and re-emit it with the
    XLA noise dropped.  The in-process CPU fallback's cache loader writes
    the feature dump from C++ logging — sys.stderr interception can't see
    it, only an fd-level redirect can.  The buffer is a NAMED on-disk file
    announced up front: a fatal signal mid-fallback (abort/SIGKILL — the
    finally never runs) leaves the full unfiltered diagnostics at that
    path instead of destroying them with an anonymous tempfile.  It lives
    under the per-pid bench workdir (cleaned on a clean exit, swept as
    stale by the next driver run once this pid dies) so crashed runs
    don't accumulate loose logs in the temp dir."""
    path = os.path.join(_bench_workdir(), "fallback_stderr.log")
    print(f"bench: cpu-fallback stderr buffered at {path} (kept on crash)",
          file=sys.stderr)
    sys.stderr.flush()
    buf = open(path, "w+b")
    saved = os.dup(2)
    os.dup2(buf.fileno(), 2)
    try:
        yield
    finally:
        sys.stderr.flush()
        os.dup2(saved, 2)
        os.close(saved)
        buf.seek(0)
        text = buf.read().decode(errors="replace")
        buf.close()
        os.unlink(path)
        cleaned = _clean_stderr(text)
        if cleaned.strip():
            sys.stderr.write(cleaned + ("" if cleaned.endswith("\n") else "\n"))
            sys.stderr.flush()

SMALL = {"env": "pendulum", "hidden": [64, 64], "population": 4096,
         "horizon": 200}
BIG = {"env": "synthetic", "hidden": [256, 256], "population": 4096,
       "horizon": 200}
POP10K = {"env": "synthetic", "hidden": [256, 256], "population": 10240,
          "horizon": 200, "eval_chunk": 1024}  # bound materialized member
# weights: whole-shard at 10240x166k floats would gamble with 16 GB HBM
LOCO = {"env": "cheetah2d", "hidden": [64, 64], "population": 1024,
        "horizon": 200}  # physics-on-chip point (cheetah2d_device recipe)
LOCO10K = {"env": "humanoid2d", "hidden": [256, 256], "population": 10240,
           "horizon": 100, "eval_chunk": 1024}  # config-3 scale with
# physics: the humanoid2d_pop10k recipe's shape at horizon 100 (a bench
# row, not a training run — scan length and alive-step fraction differ)


def _env_and_policy(cfg):
    from estorch_tpu.envs import (Cheetah2D, Humanoid2D, Pendulum,
                                  SyntheticEnv)

    if cfg["env"] == "pendulum":
        env = Pendulum()
        pk = {"action_dim": 1, "hidden": tuple(cfg["hidden"]),
              "discrete": False, "action_scale": 2.0}
    elif cfg["env"] in ("cheetah2d", "humanoid2d"):
        # device-native physics INSIDE the generation program; the cheetah
        # never terminates, so every scanned step is a real env step (same
        # honesty property the Pendulum headline relies on).  The humanoid
        # terminates on falls — its steps/s reflects the done-mask like a
        # real training run
        env = Cheetah2D() if cfg["env"] == "cheetah2d" else Humanoid2D()
        pk = {"action_dim": env.action_dim, "hidden": tuple(cfg["hidden"]),
              "discrete": False, "action_scale": 1.0}
    else:
        env = SyntheticEnv()
        pk = {"action_dim": env.action_dim, "hidden": tuple(cfg["hidden"]),
              "discrete": False, "action_scale": 1.0}
    return env, pk


def policy_flops_per_member_step(cfg):
    """2·Σ(m·n) over the MLP's matmuls — the MXU work per member env-step."""
    env, _ = _env_and_policy(cfg)
    dims = [env.obs_dim, *cfg["hidden"], env.action_dim]
    return 2 * sum(a * b for a, b in zip(dims[:-1], dims[1:]))


def measure_one(cfg, force_cpu=False):
    """Run one config; returns dict(rate, platform, mfu, ...)."""
    if force_cpu:
        from estorch_tpu.utils import force_cpu_backend

        force_cpu_backend(8)
    # stages are fresh subprocesses: persist XLA executables so repeated
    # configs (headline rerun, A/B retries after a wedge) skip the 20-40s
    # compile; compile time never counts toward the metric either way
    from estorch_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy

    env, pk = _env_and_policy(cfg)
    on_tpu = not force_cpu and jax.devices()[0].platform == "tpu"
    # the param-sharded engine (estorch_tpu/parallel/sharded.py,
    # docs/sharding.md) is f32-only; replicated rows keep the platform
    # default
    shard = bool(cfg.get("shard"))
    dtype = cfg.get("dtype",
                    "float32" if shard
                    else ("bfloat16" if on_tpu else "float32"))
    shard_kwargs = {}
    if shard:
        shard_kwargs = dict(
            shard_params=True,
            model_shards=cfg.get("model_shards"),
            noise_mode=cfg.get("noise_mode", "auto"),
        )
    es = ES(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=cfg["population"],
        sigma=0.05,
        policy_kwargs=pk,
        agent_kwargs={"env": env, "horizon": cfg["horizon"]},
        optimizer_kwargs={"learning_rate": 1e-2},
        eval_chunk=cfg.get("eval_chunk", 0),
        compute_dtype=dtype,
        decomposed=cfg.get("decomposed", False),
        noise_kernel=cfg.get("noise_kernel", False),
        streamed=cfg.get("streamed", False),
        low_rank=cfg.get("low_rank", 0),
        obs_norm=cfg.get("obs_norm", False),
        # default None: spans on, heartbeat picked up from the env var the
        # stage parent set.  The --obs-ab rows pass an explicit bool to
        # measure the spans' own overhead
        telemetry=cfg.get("telemetry"),
        **shard_kwargs,
    )
    gens = cfg.get("gens", 5)
    es.train(1, verbose=False)  # warm-up generation (compile + AOT sanity)
    t0 = time.perf_counter()
    es.train(gens, verbose=False)
    dt = time.perf_counter() - t0
    steps = sum(r["env_steps"] for r in es.history[-gens:])
    n_chips = es.mesh.devices.size
    rate = steps / dt / n_chips
    platform = es.mesh.devices.flat[0].platform

    # memory evidence rides along with every point: device peak HBM (TPU
    # PJRT memory_stats; absent on the CPU backend) and host peak RSS —
    # the noise-table/chunking sizing claims need numbers, not prose
    peak_hbm = None
    if platform == "tpu":
        stats = es.mesh.devices.flat[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        peak_hbm = round(peak / 2**30, 3) if peak else None
    import resource

    # ru_maxrss is KiB on Linux but bytes on macOS
    rss_div = 2**30 if sys.platform == "darwin" else 2**20
    peak_rss = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / rss_div, 3
    )

    # MFU is no longer null off-chip: on TPU it keeps the fixed v5e bf16
    # denominator (cross-dtype comparability); on CPU the denominator is
    # this host's MEASURED GEMM peak (obs/profile/roofline.py), tagged
    # cpu_calibrated so nobody reads it against accelerator silicon
    from estorch_tpu.obs.profile import platform_roofline, profile_records

    # MFU numerator comes from the run's OWN cost model when one was
    # built (shard-aware since the sharded engine landed: noise mode,
    # low-rank forward term, per-device attribution ride along); the
    # static helper is the fallback for telemetry-off rows
    cost_model = getattr(es.obs, "cost_model", None) or {}
    flops_per_step = (cost_model.get("flops_per_env_step")
                      or policy_flops_per_member_step(cfg))
    if platform == "tpu":
        roof = platform_roofline("tpu")
        mfu = rate * flops_per_step / roof["peak_flops_per_s"]
        mfu_basis = roof["basis"]
    else:
        # cpu gets the measured host ceiling; any OTHER platform gets
        # None-peaks (platform_roofline refuses to hand a gpu the host
        # CPU's GEMM rate as a denominator) and mfu stays null there
        roof = platform_roofline(platform)
        peak = roof.get("peak_flops_per_s")
        # whole-host utilization: total steps/s (not per-chip — the CPU
        # "chips" are virtual devices time-slicing this host) against
        # the host's measured ceiling
        mfu = (rate * n_chips * flops_per_step / peak) if peak else None
        mfu_basis = roof.get("basis") if peak else None

    # per-phase attribution of the measured generations (obs/profile/):
    # seconds share + achieved FLOP/s per phase, the compile ledger, and
    # the analytic-vs-XLA cross-check ride the bench row
    phases = None
    compile_block = None
    try:
        # full history, not just the timed window: the compile ledger
        # flushed into the warm-up generation's record, and the warm-up's
        # spans are as representative as the timed ones for attribution
        prof = profile_records(es.history, roof,
                               cost_model=es.obs.cost_model)
        phases = {
            name: {k: (round(v, 8) if isinstance(v, float) else v)
                   for k, v in row.items()
                   if k in ("share", "seconds", "flops_per_s", "mfu",
                            "arith_intensity", "bound")}
            for name, row in (prof.get("phases") or {}).items()
        }
        compile_block = prof.get("compile")
    except Exception as e:  # noqa: BLE001 — attribution must not kill a row
        print(f"bench: phase attribution failed: {e!r}", file=sys.stderr)
    out = {
        "rate": rate,
        "platform": platform,
        "dtype": dtype,
        "mfu": round(mfu, 8) if mfu is not None else None,
        "mfu_basis": mfu_basis,
        "phases": phases,
        "compile": compile_block,
        "peak_hbm_gb": peak_hbm,
        "peak_rss_gb": peak_rss,
        "cfg": cfg,
    }
    hist_out = cfg.get("history_out")
    if hist_out:
        # per-generation records for the baseline-capture path: exactly
        # the keys the regress phase/tail gates consume, written
        # atomically like every other artifact.  history_skip drops the
        # leading warm-up/compile records — a committed TAIL baseline
        # whose p99 is a compile spike would wave real steady-state
        # regressions through (p99 of ~35 samples is the max sample)
        skip = max(0, int(cfg.get("history_skip", 1)))
        keep = ("generation", "env_steps", "env_steps_per_sec",
                "wall_time_s", "phases", "reward_mean")
        tmp = hist_out + ".tmp"
        with open(tmp, "w") as f:
            for rec in es.history[skip:]:
                f.write(json.dumps({k: rec[k] for k in keep if k in rec},
                                   default=float) + "\n")
        os.replace(tmp, hist_out)
    if shard:
        # peak-memory extras: XLA's per-device argument/output/temp bytes
        # for the compiled (sharded, donated) generation program — with
        # sharded inputs those ARE shard sizes (compile ledger contract)
        out["shard"] = {
            "noise_mode": es.engine.noise_mode,
            "mesh": {"pop": es.engine.pop_shards,
                     "model": es.engine.model_shards},
            "per_device_peak_bytes": es.engine.memory_facts().get(
                "peak_bytes"),
            "mfu_from_cost_model": bool(
                cost_model.get("flops_per_env_step")),
        }
    return out


def measure_reference_style_baseline(budget_s=6.0) -> float:
    """Single-process estorch-style loop: torch MLP + gymnasium Pendulum."""
    import gymnasium as gym
    import torch

    policy = torch.nn.Sequential(
        torch.nn.Linear(3, 64), torch.nn.Tanh(),
        torch.nn.Linear(64, 64), torch.nn.Tanh(),
        torch.nn.Linear(64, 1), torch.nn.Tanh(),
    )
    env = gym.make("Pendulum-v1")
    obs, _ = env.reset(seed=0)
    steps = 0
    t0 = time.perf_counter()
    with torch.no_grad():
        while time.perf_counter() - t0 < budget_s:
            for _ in range(200):
                a = policy(torch.from_numpy(np.asarray(obs, dtype=np.float32)))
                obs, r, term, trunc, _ = env.step(a.numpy() * 2.0)
                steps += 1
                if term or trunc:
                    obs, _ = env.reset()
    env.close()
    return steps / (time.perf_counter() - t0)


def run_stage_detailed(cfg, timeout_s=480, force_cpu=False):
    """One config in a child with a hard timeout — the tunnel can wedge at
    init OR mid-run, and bench must still emit its JSON line.  Always
    returns a row dict with a "rate" key (None on failure, plus "error" /
    "stderr_tail" saying why) — the machine-readable form the on-chip A/B
    artifact records, so a wedged row's diagnosis survives in the artifact
    instead of only on a long-gone stderr.

    Every stage child runs with a heartbeat file (obs/recorder.py
    protocol): on timeout the failure line carries the child's last
    phase + generation + heartbeat age instead of a guess — "wedged in
    phase=device at gen 0, silent for 470s" vs "slow but beating"."""
    hb_path = os.path.join(
        _bench_workdir(),
        f"hb_{abs(hash(json.dumps(cfg, sort_keys=True))) % 10**8}.json",
    )
    try:
        argv = [sys.executable, __file__, "--stage-one", json.dumps(cfg)]
        if force_cpu:
            argv.append("--cpu")
        try:
            r = subprocess.run(
                argv, timeout=timeout_s, capture_output=True, text=True,
                env={**os.environ, HEARTBEAT_ENV: hb_path},
            )
        except subprocess.TimeoutExpired:
            row = {"rate": None, "cfg": cfg,
                   "error": (f"timeout after {timeout_s}s "
                             f"({describe_heartbeat(hb_path)})")}
            hb = read_heartbeat(hb_path)
            if hb is not None:
                row["heartbeat"] = hb
            return row
    finally:
        try:
            os.remove(hb_path)
        except OSError:
            pass
    try:
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        out = json.loads(last)
        float(out["rate"]), str(out["platform"]), str(out["dtype"])
        _ = out["mfu"]  # may be null off-TPU, but the key must exist
        _ = out["peak_hbm_gb"], out["peak_rss_gb"]  # memory evidence keys
        if r.returncode != 0:
            # the measurement completed and printed its result, then the
            # child died in teardown (the flaky tunnel does this) — keep
            # the row, annotated, instead of burning a compile-sized
            # re-run in the next scarce window
            out["exit_code"] = r.returncode
        return out
    except (IndexError, KeyError, TypeError, ValueError):
        if r.returncode != 0:
            return {"rate": None, "cfg": cfg,
                    "error": f"stage exited {r.returncode}",
                    "stderr_tail": _clean_stderr(r.stderr)[-800:]}
        return {"rate": None, "cfg": cfg, "error": "unparseable",
                "stdout_tail": r.stdout[-500:],
                "stderr_tail": _clean_stderr(r.stderr)[-800:]}


def run_stage(cfg, timeout_s=480, force_cpu=False):
    """run_stage_detailed, collapsed to the dict-or-None contract the
    headline path uses; failure diagnostics go to OUR stderr (the JSON-line
    contract owns stdout only)."""
    out = run_stage_detailed(cfg, timeout_s=timeout_s, force_cpu=force_cpu)
    if out.get("rate") is None:
        print(f"bench: stage failed cfg={cfg}: {out.get('error')}\n"
              f"{out.get('stdout_tail', '')}\n{out.get('stderr_tail', '')}",
              file=sys.stderr)
        return None
    return out


AB_MATRIX = [
    # (label, base-config, overrides)
    ("small/standard/f32", SMALL, {"dtype": "float32"}),
    ("small/standard/bf16", SMALL, {"dtype": "bfloat16"}),
    ("small/decomposed/f32", SMALL, {"dtype": "float32", "decomposed": True}),
    ("small/decomposed/bf16", SMALL, {"dtype": "bfloat16", "decomposed": True}),
    ("small/decomposed/bf16+nk", SMALL,
     {"dtype": "bfloat16", "decomposed": True, "noise_kernel": True}),
    ("small/streamed/f32", SMALL, {"dtype": "float32", "streamed": True}),
    ("small/streamed/f32+nk", SMALL,
     {"dtype": "float32", "streamed": True, "noise_kernel": True}),
    ("big/standard/bf16", BIG, {"dtype": "bfloat16"}),
    ("big/decomposed/bf16", BIG, {"dtype": "bfloat16", "decomposed": True}),
    ("big/streamed/f32", BIG, {"dtype": "float32", "streamed": True}),
    ("big/lowrank1/bf16", BIG, {"dtype": "bfloat16", "low_rank": 1}),
    ("big/lowrank4/bf16", BIG, {"dtype": "bfloat16", "low_rank": 4}),
    ("pop10k/decomposed/bf16", POP10K,
     {"dtype": "bfloat16", "decomposed": True, "gens": 3}),
    ("pop10k/lowrank1/bf16", POP10K,
     {"dtype": "bfloat16", "low_rank": 1, "gens": 3}),
    ("loco/standard/bf16", LOCO, {"dtype": "bfloat16", "gens": 3}),
    ("loco/standard/f32", LOCO, {"dtype": "float32", "gens": 3}),
    ("loco10k/lowrank1/bf16", LOCO10K,
     {"dtype": "bfloat16", "low_rank": 1, "gens": 3}),
    # the north-star composition (round 4): running obs normalization ON
    # TOP of the rank-1 noise representation — measures what the per-step
    # normalize + per-generation center probe cost at config-3 scale.
    # Shares LOCO10K with the row above so the pair can never diverge.
    ("loco10k/lowrank1+obsnorm/bf16", LOCO10K,
     {"dtype": "bfloat16", "low_rank": 1, "obs_norm": True, "gens": 3}),
]


def stage_ab(force_cpu=False):
    force_cpu = _probe_or_force_cpu(force_cpu)
    seen = {}
    for label, base, over in AB_MATRIX:
        cfg = {**base, **over}
        label_spec = None
        if force_cpu:
            # CPU can't run emulated bf16 at bench sizes in sane time, and
            # relative mode comparisons only make sense at one dtype there —
            # rows that coerce to an already-measured cfg alias its result.
            # The label must say what was MEASURED (f32), not what the
            # matrix row specs for on-chip runs; label_spec keeps the
            # original for joining against future TPU rows
            cfg = {**cfg, "dtype": "float32", "gens": 2}
            if "bf16" in label:
                label_spec, label = label, label.replace("bf16", "f32")
        key = json.dumps(cfg, sort_keys=True)
        if key in seen:
            if label_spec is None and label == seen[key]:
                # an explicit row coerced to a cfg already measured under
                # the SAME label — a second line with an identical label
                # (and self-referential alias_of) would be ambiguous for
                # consumers that join by label; skip it
                continue
            # keep the alias line keyed by the ORIGINAL spec label (e.g.
            # the bf16 row whose cfg coerced onto an f32 measurement):
            # labels stay unique and future TPU rows still join on it
            line = {"label": label_spec or label, "alias_of": seen[key],
                    "cfg": cfg}
            print(json.dumps(line), flush=True)
            continue
        seen[key] = label
        res = run_stage(cfg, timeout_s=1200 if force_cpu else 600,
                        force_cpu=force_cpu)
        line = {"label": label, **(res or {"rate": None, "cfg": cfg})}
        if label_spec:
            line["label_spec"] = label_spec
        print(json.dumps(line), flush=True)


def stage_obs_ab(force_cpu=False, gens=3, repeats=3):
    """Telemetry overhead A/B: the SAME config with default-on spans vs
    telemetry disabled — the <2% observability acceptance gate.

    The ON arm includes everything the hub records by default: spans,
    counters, AND the streaming histograms (obs/hist.py — per-phase
    duration distributions observed on every span exit), so this A/B is
    also the histogram-on vs histogram-off overhead gate; a disabled
    hub swallows observes through NullHistograms the same way it
    swallows counter writes.

    This host's single-run rates swing far more than 2% (shared-core
    load; the round-4 contamination lesson), so one pair of stages
    cannot resolve a 2% effect: ``repeats`` INTERLEAVED on/off pairs are
    measured (ABAB..., so slow drift hits both arms equally) and the
    verdict compares the per-arm MEDIANS.  Per-run rows land as JSON
    lines for the artifact; the ``obs/overhead`` line carries the
    medians + the verdict."""
    force_cpu = _probe_or_force_cpu(force_cpu)
    rates = {"spans_on": [], "spans_off": []}
    for rep in range(repeats):
        for label, tel in (("spans_on", True), ("spans_off", False)):
            cfg = {**SMALL, "gens": gens, "telemetry": tel}
            if force_cpu:
                cfg["dtype"] = "float32"
            r = run_stage(cfg, timeout_s=1200 if force_cpu else 600,
                          force_cpu=force_cpu)
            if r and r.get("rate"):
                rates[label].append(r["rate"])
            print(json.dumps({"label": f"obs/{label}", "rep": rep,
                              **(r or {"rate": None, "cfg": cfg})}),
                  flush=True)
    on, off = sorted(rates["spans_on"]), sorted(rates["spans_off"])
    if on and off:
        # statistics.median averages the middle pair on even arm sizes —
        # a timed-out repeat must not bias the gate toward either verdict
        import statistics

        med_on = statistics.median(on)
        med_off = statistics.median(off)
        # overhead = throughput lost with spans on (positive = spans cost)
        overhead = (med_off - med_on) / med_off * 100.0
        print(json.dumps({
            "label": "obs/overhead",
            "median_on": round(med_on, 1), "median_off": round(med_off, 1),
            "runs_per_arm": len(on),
            "spread_pct": round(
                (max(on + off) - min(on + off)) / med_off * 100.0, 1),
            "overhead_pct": round(overhead, 2),
            "pass_lt_2pct": overhead < 2.0,
        }), flush=True)


def _tiny_host_es(cfg, worker_mode="process"):
    """Shared tiny host-backend ES for the chaos / async-ab stages: a
    4→8→2 torch MLP and a quadratic-fitness agent whose rollout runs
    ``work_s`` of sleep (GIL-released, like a real env stepping in C) —
    enough per-member cost that generations have a cadence for
    stragglers to perturb."""
    import torch

    from estorch_tpu import ES

    class TinyPolicy(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.net = torch.nn.Sequential(
                torch.nn.Linear(4, 8), torch.nn.Tanh(), torch.nn.Linear(8, 2)
            )

        def forward(self, x):
            return self.net(x)

    work_s = float(cfg.get("work_s", 0.0))

    class QuadAgent:
        def rollout(self, policy):
            with torch.no_grad():
                v = torch.nn.utils.parameters_to_vector(policy.parameters())
                r = -float((v**2).sum())
            if work_s:
                time.sleep(work_s)
            self.last_episode_steps = 1
            return r

    return ES(TinyPolicy, QuadAgent, torch.optim.Adam,
              population_size=int(cfg.get("population", 16)), sigma=0.05,
              seed=0, optimizer_kwargs={"lr": 0.01}, table_size=1 << 12,
              worker_mode=worker_mode)


def _async_accounting(es, baseline=None):
    """The zero-silent-drop invariant, read once from the event log +
    counters (docs/async.md): every dispatched member is consumed (fold
    or fresh), discarded with evidence, or lost to a counted worker
    death.  All the async gates (--chaos mixed leg, --async-ab,
    --elastic-ab) report THIS block, so they can never check different
    invariants.  ``baseline`` is a counters snapshot taken before the
    timed run (an untimed warm-up shares ``es.obs.counters`` but gets
    its own event log — without the delta, warm-up folds could satisfy
    a timed-window gate)."""
    log = es.async_event_log
    counters = es.obs.counters.snapshot()
    base = baseline or {}

    def since(name):
        return int(counters.get(name, 0)) - int(base.get(name, 0))

    consumed = sum(len(u["consumed"]) for u in log.updates)
    dispatched = len(log.dispatches) * es.population_size
    return {
        "results_folded": since("results_folded"),
        "stale_discarded": since("stale_discarded"),
        "results_lost": since("results_lost"),
        "consumed": consumed,
        "dispatched": dispatched,
        "accounting_ok": (dispatched == consumed + len(log.discarded)
                          + len(log.lost)),
    }


def measure_chaos_one(cfg):
    """Child body for --stage-chaos-one: a tiny host-backend ES with fork
    workers, optionally under a chaos plan (worker kills, and — the
    mixed-fault async leg — straggler stalls with jitter), measured in
    generations/sec.  ``cfg["async"]`` routes through the event-driven
    scheduler (estorch_tpu/algo/scheduler.py) instead of the barrier
    loop.  Host path only: construction imports jax but never touches
    the device runtime, so this stays safe on a wedged-tunnel machine
    (run_lint exports JAX_PLATFORMS=cpu on top)."""
    from estorch_tpu.resilience.chaos import CHAOS_ENV, ChaosPlan

    gens = int(cfg.get("gens", 60))
    n_proc = int(cfg.get("n_proc", 2))
    if cfg.get("chaos"):
        plan = ChaosPlan.generate(
            seed=0, n_generations=gens,
            kill_every=int(cfg.get("kill_every", 0)),
            n_workers=n_proc,
            straggler_every=int(cfg.get("straggler_every", 0)),
            straggler_sleep_s=float(cfg.get("sleep_s", 1.0)),
            straggler_jitter_s=float(cfg.get("jitter_s", 0.0)),
            population_size=int(cfg.get("population", 16)),
        )
        os.environ[CHAOS_ENV] = plan.to_json()
    es = _tiny_host_es(cfg, worker_mode="process")
    es.train(1, n_proc=n_proc, verbose=False)  # warm-up: fork the pool
    t0 = time.perf_counter()
    if cfg.get("async"):
        es.train_async(gens, n_proc=n_proc, verbose=False,
                       max_stale=int(cfg.get("max_stale", 4096)))
    else:
        es.train(gens, n_proc=n_proc, verbose=False)
    dt = time.perf_counter() - t0
    counters = es.obs.counters.snapshot()
    out = {
        "gps": round(gens / dt, 2),
        "generations": len(es.history),
        "n_failed_total": int(sum(r["n_failed"] for r in es.history)),
        "workers_respawned": int(counters.get("workers_respawned", 0)),
        "members_retried": int(counters.get("members_retried", 0)),
        "chaos_worker_kills": int(counters.get("chaos_worker_kills", 0)),
        "generations_rejected": int(counters.get("generations_rejected", 0)),
        "cfg": cfg,
    }
    if cfg.get("async"):
        out.update(_async_accounting(es))
    es.engine.close()
    return out


def stage_chaos(selfcheck=False):
    """Recovery-overhead A/B (chaos vs clean) via the stage protocol; the
    selfcheck form is the run_lint.sh gate.  Returns the process exit
    code: 0 when recovery actually recovered (full participation under
    worker kills, and the async scheduler survived the MIXED
    straggler+kill plan with its accounting intact), 1 otherwise."""
    gens = 24 if selfcheck else 60
    kill_every = 8 if selfcheck else 20
    base = {"gens": gens, "kill_every": kill_every, "population": 16,
            "n_proc": 2}
    # the mixed-fault async leg: the SAME kills plus a straggler stall
    # (with jitter) every kill_every//2 generations, driven through the
    # event-driven scheduler — both fault classes against the async path
    mixed = {**base, "chaos": True, "async": True,
             "straggler_every": max(kill_every // 2, 2),
             "sleep_s": 0.3, "jitter_s": 0.2, "work_s": 0.002}
    rows = {}
    for label, cfg in (("clean", {**base, "chaos": False}),
                       ("chaos", {**base, "chaos": True}),
                       ("mixed_async", mixed)):
        argv = [sys.executable, __file__, "--stage-chaos-one",
                json.dumps(cfg)]
        # a pre-existing ESTORCH_CHAOS in the caller's environment
        # (resilience.chaos.CHAOS_ENV; literal here — the bench driver
        # stays import-free) would contaminate the CLEAN leg and turn the
        # A/B into chaos-vs-chaos; the chaos leg sets its own plan
        child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        child_env.pop("ESTORCH_CHAOS", None)
        try:
            r = subprocess.run(argv, timeout=600, capture_output=True,
                               text=True, env=child_env)
        except subprocess.TimeoutExpired:
            print(json.dumps({"label": f"chaos/{label}", "gps": None,
                              "error": "timeout after 600s"}), flush=True)
            continue
        try:
            last = [ln for ln in r.stdout.strip().splitlines()
                    if ln.startswith("{")][-1]
            rows[label] = json.loads(last)
        except (IndexError, ValueError):
            print(json.dumps({"label": f"chaos/{label}", "gps": None,
                              "error": f"stage exited {r.returncode}",
                              "stderr_tail": r.stderr[-800:]}), flush=True)
            continue
        print(json.dumps({"label": f"chaos/{label}", **rows[label]}),
              flush=True)
    clean, chaos = rows.get("clean"), rows.get("chaos")
    mixed_row = rows.get("mixed_async")
    if not clean or not chaos or not mixed_row:
        print(json.dumps({"label": "chaos/recovery", "error":
                          "one or more stages failed"}), flush=True)
        return 1
    overhead = (clean["gps"] - chaos["gps"]) / clean["gps"] * 100.0
    expected_kills = gens // kill_every
    # full recovery means: every generation trained, every kill respawned
    # (a kill at the FINAL generation has no next boundary to respawn at —
    # hence the -1), and NO member lost — the same-generation retry path
    # covered every killed worker's slice
    recovered = (
        chaos["generations"] == gens + 1  # incl. warm-up generation
        and chaos["chaos_worker_kills"] >= expected_kills
        and chaos["workers_respawned"] >= expected_kills - 1
        and chaos["n_failed_total"] == 0
    )
    # the async leg's contract is different by design: a killed worker's
    # in-flight slice is LOST (counted), not retried — recovery means
    # the scheduler finished every update anyway, respawned the killed
    # workers, and accounted every dispatched member (consumed /
    # discarded / lost), with zero silent drops
    mixed_ok = (
        mixed_row["generations"] == gens + 1
        and mixed_row["chaos_worker_kills"] >= expected_kills
        and mixed_row["workers_respawned"] >= expected_kills - 1
        and bool(mixed_row.get("accounting_ok"))
    )
    print(json.dumps({
        "label": "chaos/recovery",
        "clean_gps": clean["gps"],
        "chaos_gps": chaos["gps"],
        "overhead_pct": round(overhead, 1),
        "worker_kills": chaos["chaos_worker_kills"],
        "workers_respawned": chaos["workers_respawned"],
        "members_retried": chaos["members_retried"],
        "n_failed_total": chaos["n_failed_total"],
        "full_participation": chaos["n_failed_total"] == 0,
        "mixed_async": {
            "gps": mixed_row["gps"],
            "worker_kills": mixed_row["chaos_worker_kills"],
            "workers_respawned": mixed_row["workers_respawned"],
            "results_folded": mixed_row.get("results_folded"),
            "stale_discarded": mixed_row.get("stale_discarded"),
            "results_lost": mixed_row.get("results_lost"),
            "accounting_ok": mixed_row.get("accounting_ok"),
            "pass": mixed_ok,
        },
        "pass": recovered and mixed_ok,
    }), flush=True)
    return 0 if (recovered and mixed_ok) else 1


def measure_async_one(cfg):
    """Child body for --stage-async-one: ONE leg of the sync-vs-async
    A/B — a tiny host thread-worker ES under a deterministic straggler
    plan (sleep + jitter every K generations), driven either by the
    barrier loop (``ES.train``) or the event-driven scheduler
    (``ES.train_async``, estorch_tpu/algo/scheduler.py).  Both legs see
    the IDENTICAL plan (jitter is seeded by event id), so the only
    variable is the scheduling.  Prints one JSON row with the rate and
    — async leg — the fold/discard/lost accounting and the per-phase
    step-vs-max evidence the driver gates on."""
    from estorch_tpu.resilience.chaos import CHAOS_ENV, ChaosPlan

    gens = int(cfg.get("gens", 20))
    n_proc = int(cfg.get("n_proc", 2))
    plan = ChaosPlan.generate(
        seed=0, n_generations=gens,
        straggler_every=int(cfg.get("straggler_every", 2)),
        straggler_sleep_s=float(cfg.get("sleep_s", 0.3)),
        straggler_jitter_s=float(cfg.get("jitter_s", 0.2)),
        population_size=int(cfg.get("population", 16)),
    )
    os.environ[CHAOS_ENV] = plan.to_json()
    es = _tiny_host_es(cfg, worker_mode="thread")
    t0 = time.perf_counter()
    if cfg.get("async"):
        es.train_async(gens, n_proc=n_proc, verbose=False,
                       max_stale=int(cfg.get("max_stale", 4096)))
    else:
        es.train(gens, n_proc=n_proc, verbose=False)
    dt = time.perf_counter() - t0
    # per-update step-vs-max evidence from the recorded phase spans:
    # wall ≈ max(eval, update) is the async promise (the sync barrier
    # loop's wall is their SUM plus the straggler stall)
    walls, maxes = [], []
    for r in es.history:
        ph = r.get("phases") or {}
        ev, up = float(ph.get("eval", 0.0)), float(ph.get("update", 0.0))
        if ev or up:
            walls.append(float(r["wall_time_s"]))
            maxes.append(max(ev, up))
    import statistics

    step_max_ratio = (
        round(statistics.median(walls) / statistics.median(maxes), 3)
        if maxes and statistics.median(maxes) > 0 else None)
    counters = es.obs.counters.snapshot()
    out = {
        "mode": "async" if cfg.get("async") else "sync",
        "gps": round(gens / dt, 3),
        "wall_s": round(dt, 3),
        "generations": len(es.history),
        "step_max_ratio": step_max_ratio,
        "n_failed_total": int(sum(r["n_failed"] for r in es.history)),
        "cfg": cfg,
    }
    if cfg.get("async"):
        out.update(
            **_async_accounting(es),
            overlap_efficiency=counters.get("overlap_efficiency"),
            stale_reuse_ratio=counters.get("stale_reuse_ratio"),
        )
    es.engine.close()
    return out


def stage_async_ab(selfcheck=False):
    """Sync-barrier vs async-scheduler A/B under an injected straggler
    plan (ISSUE 9 acceptance; the selfcheck form is the run_lint.sh
    gate).  Interleaved repeats per arm (the --obs-ab loaded-host
    discipline), medians + a noise band learned from the repeats via
    ``obs regress``.  Exit 0 only when (1) async generation throughput
    beats sync by >= 1.25x beyond the learned band, (2) the async leg's
    step time ≈ max(eval, update) per the recorded spans, and (3) the
    zero-silent-drop accounting holds — every late result folded with a
    recorded weight or counted discarded/lost."""
    regress = _load_obs_regress()
    base = ({"gens": 14, "population": 16, "n_proc": 2,
             "straggler_every": 2, "sleep_s": 0.25, "jitter_s": 0.15,
             "work_s": 0.002, "max_stale": 4096}
            if selfcheck else
            {"gens": 30, "population": 16, "n_proc": 2,
             "straggler_every": 2, "sleep_s": 0.4, "jitter_s": 0.25,
             "work_s": 0.004, "max_stale": 4096})
    repeats = 2 if selfcheck else 3
    rates = {"sync": [], "async": []}
    async_rows = []
    for rep in range(repeats):
        for mode in ("sync", "async"):
            cfg = {**base, "async": mode == "async"}
            argv = [sys.executable, __file__, "--stage-async-one",
                    json.dumps(cfg)]
            child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
            child_env.pop("ESTORCH_CHAOS", None)  # the stage owns its plan
            try:
                r = subprocess.run(argv, timeout=600, capture_output=True,
                                   text=True, env=child_env)
                last = [ln for ln in r.stdout.strip().splitlines()
                        if ln.startswith("{")][-1]
                row = json.loads(last)
            except subprocess.TimeoutExpired:
                print(json.dumps({"label": f"async/{mode}", "rep": rep,
                                  "error": "timeout after 600s"}),
                      flush=True)
                continue
            except (IndexError, ValueError):
                print(json.dumps({"label": f"async/{mode}", "rep": rep,
                                  "error": f"stage exited {r.returncode}",
                                  "stderr_tail": r.stderr[-800:]}),
                      flush=True)
                continue
            rates[mode].append(row["gps"])
            if mode == "async":
                async_rows.append(row)
            print(json.dumps({"label": f"async/{mode}", "rep": rep,
                              **row}), flush=True)
    if not rates["sync"] or not rates["async"]:
        print(json.dumps({"label": "async/ab",
                          "error": "one or both arms have no samples"}),
              flush=True)
        return 1
    # medians + learned noise band: async as "current" vs sync as the
    # baseline — an honest win must clear the band AND the 1.25x floor
    verdict = regress.compare(rates["async"], rates["sync"],
                              metric="generations_per_sec")
    ratio = (verdict["current_median"] / verdict["baseline_median"]
             if verdict["baseline_median"] else None)
    folded = sum(r.get("results_folded", 0) for r in async_rows)
    accounting_ok = all(r.get("accounting_ok") for r in async_rows)
    step_ratios = [r["step_max_ratio"] for r in async_rows
                   if r.get("step_max_ratio") is not None]
    import statistics

    step_max = (round(statistics.median(step_ratios), 3)
                if step_ratios else None)
    ok = (
        ratio is not None and ratio >= 1.25
        and bool(verdict.get("improved"))
        and accounting_ok
        and folded > 0  # the straggler plan MUST have exercised the fold
        and step_max is not None and step_max <= 1.35
    )
    print(json.dumps({
        "label": "async/ab",
        "sync_median_gps": verdict["baseline_median"],
        "async_median_gps": verdict["current_median"],
        "ratio": round(ratio, 3) if ratio else None,
        "band_pct": verdict["band_pct"],
        "improved_beyond_band": bool(verdict.get("improved")),
        "results_folded": folded,
        "stale_discarded": sum(r.get("stale_discarded", 0)
                               for r in async_rows),
        "results_lost": sum(r.get("results_lost", 0) for r in async_rows),
        "accounting_ok": accounting_ok,
        "async_step_vs_max_phase": step_max,
        "pass": ok,
    }), flush=True)
    return 0 if ok else 1


def _elastic_spec(cfg):
    """The shared ES spec of every --elastic-ab process (coordinator,
    subprocess hosts, sync SPMD workers): same seed => same table =>
    same noise coordinates everywhere (parallel/elastic.py
    es_from_spec)."""
    return {
        "env": "CartPole",
        "population_size": int(cfg.get("population", 16)),
        "horizon": int(cfg.get("horizon", 64)),
        "seed": 7,
        "sigma": 0.1,
        "lr": 1e-2,
        "table_size": 1 << 18,
        "telemetry": True,
    }


def _elastic_plan_json(cfg):
    """The declared straggle_host plan BOTH legs run under — host 1 is
    slow at EVERY generation/dispatch (seeded jitter on top), so the
    sync leg's psum barrier pays the stall fleet-wide while the elastic
    leg only loses host 1's contribution rate.  Built identically in
    every child (same seed => same events => same jitter)."""
    from estorch_tpu.resilience.chaos import ChaosPlan

    plan = ChaosPlan.generate(
        seed=0,
        n_generations=int(cfg["gens"]) * 3 + 16,
        straggle_host_every=1,
        straggle_host=1,
        straggle_host_sleep_s=float(cfg.get("sleep_s", 0.3)),
        straggle_host_jitter_s=float(cfg.get("jitter_s", 0.1)),
    )
    return plan.to_json()


def elastic_sync_worker(cfg):
    """Child body for --stage-elastic-worker: ONE process of the
    synchronous 2-process SPMD multihost leg (jax.distributed over
    loopback + Gloo CPU collectives, tests/test_multiprocess.py
    layering).  Every process steps the same fused program under the
    declared straggle_host plan via multihost.train_sync — the psum
    barrier makes host 1's stall everyone's stall, which is exactly
    what the elastic leg is measured against.  The leader prints the
    timed row."""
    from estorch_tpu.resilience.chaos import CHAOS_ENV
    from estorch_tpu.utils.backend import force_cpu_backend

    force_cpu_backend(int(cfg.get("cpu_devices", 2)))
    os.environ[CHAOS_ENV] = _elastic_plan_json(cfg)
    import estorch_tpu.parallel.multihost as mh
    from estorch_tpu.parallel.elastic import es_from_spec

    assert mh.initialize(f"127.0.0.1:{cfg['port']}", num_processes=2,
                         process_id=int(cfg["pid"]), timeout_s=90,
                         cpu_collectives=True)
    es = es_from_spec(_elastic_spec(cfg),
                      mesh=mh.global_population_mesh())
    gens = int(cfg["gens"])
    es.train(1, verbose=False)  # warm-up: compile outside the window
    t0 = time.perf_counter()
    mh.train_sync(es, gens, verbose=False)
    dt = time.perf_counter() - t0
    return {
        "mode": "sync",
        "leader": mh.process_info()["is_leader"],
        "gps": round(gens / dt, 3),
        "wall_s": round(dt, 3),
        "generations": int(es.generation),
    }


def measure_elastic_one(cfg):
    """Child body for --stage-elastic-one (elastic leg): a live elastic
    fleet on this machine — the coordinator (device-backend ES + the
    host-granular fold scheduler, docs/multihost.md) plus two REAL
    subprocess hosts joined through the ``python -m
    estorch_tpu.parallel.elastic`` CLI, all under the same declared
    straggle_host plan the sync leg pays.  Prints the timed row with
    the dispatched == consumed + discarded + lost accounting."""
    import signal
    import subprocess as sp

    from estorch_tpu.resilience.chaos import CHAOS_ENV

    plan_json = _elastic_plan_json(cfg)
    os.environ[CHAOS_ENV] = plan_json
    spec = {**_elastic_spec(cfg), "cpu_devices": 2}
    from estorch_tpu.parallel.elastic import ElasticCoordinator, es_from_spec

    es = es_from_spec(spec)
    # grace must satisfy 4 * join_grace_s < the driver's 600s child
    # timeout: four consecutive grace-expired dispatches are what the
    # scheduler's dry-out diagnosis needs, and a SIGKILLed child loses
    # the host-log evidence this function exists to print
    coord = ElasticCoordinator(join_grace_s=120.0)
    host_env = {**os.environ, "JAX_PLATFORMS": "cpu",
                CHAOS_ENV: plan_json}
    # host output goes to FILES, never unread pipes: a chatty host
    # blocking on a full 64KB pipe mid-run would look exactly like the
    # dead-slow host this leg exists to measure
    logdir = tempfile.mkdtemp(prefix="elastic-hosts-")
    host_logs = [open(os.path.join(logdir, f"host{i}.log"), "w+")
                 for i in range(2)]
    hosts = [
        sp.Popen([sys.executable, "-m", "estorch_tpu.parallel.elastic",
                  "--join", f"{coord.address[0]}:{coord.address[1]}",
                  "--spec", json.dumps(spec), "--host", str(i)],
                 env=host_env, stdout=f, stderr=sp.STDOUT, text=True)
        for i, f in enumerate(host_logs)
    ]
    gens = int(cfg["gens"])
    try:
        # warm-up: coordinator fold/update compiles + both hosts join
        # and compile, all outside the timed window
        es.train_elastic(1, fleet=coord, verbose=False)
        warm = dict(es.obs.counters.snapshot())
        t0 = time.perf_counter()
        es.train_elastic(gens, fleet=coord, verbose=False)
        dt = time.perf_counter() - t0
    finally:
        coord.close()
        for i, h in enumerate(hosts):
            try:
                h.wait(timeout=10)
            except sp.TimeoutExpired:
                h.send_signal(signal.SIGKILL)
                h.wait(timeout=10)
            host_logs[i].close()
            if h.returncode not in (0, -signal.SIGKILL):
                with open(host_logs[i].name) as f:
                    print(f"elastic host {i} exited {h.returncode}: "
                          f"{f.read()[-800:]}", file=sys.stderr)
    counters = es.obs.counters.snapshot()
    return {
        "mode": "elastic",
        "gps": round(gens / dt, 3),
        "wall_s": round(dt, 3),
        "hosts": 2,
        "hosts_lost": int(counters.get("hosts_lost", 0))
        - int(warm.get("hosts_lost", 0)),
        "membership_events": len(es.async_event_log.membership),
        **_async_accounting(es, baseline=warm),
    }


def _run_elastic_leg(mode, base, rep=0):
    """Run ONE --elastic-ab leg in fresh child processes and return its
    timed row, or None after printing the failure evidence.  Shared by
    the A/B gate and --capture-baseline's committed elastic row."""
    import socket

    child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    child_env.pop("ESTORCH_CHAOS", None)  # legs own their plan
    if mode == "sync":
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, __file__, "--stage-elastic-worker",
             json.dumps({**base, "pid": pid, "port": port})],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=child_env) for pid in range(2)]
        row = None
        # drain BOTH workers' pipes concurrently: these are one SPMD
        # job, so worker 1 blocking on a full unread pipe while we
        # communicate() with worker 0 would stall the barrier fleet-wide
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(len(procs)) as pool:
            futs = [pool.submit(p.communicate, None, 600) for p in procs]
            try:
                outs = [f.result() for f in futs]
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                print(json.dumps({"label": "elastic/sync", "rep": rep,
                                  "error": "timeout after 600s"}),
                      flush=True)
                return None
        for p, (out, err) in zip(procs, outs):
            lines = [ln for ln in out.strip().splitlines()
                     if ln.startswith("{")]
            try:
                cand = json.loads(lines[-1]) if lines else None
            except ValueError:  # died mid-print: fail the leg, not the gate
                cand = None
            if p.returncode != 0 or cand is None:
                print(json.dumps(
                    {"label": "elastic/sync", "rep": rep,
                     "error": f"worker exited {p.returncode}",
                     "stderr_tail": err[-800:]}), flush=True)
            elif cand.get("leader"):
                row = cand
        return row
    argv = [sys.executable, __file__, "--stage-elastic-one",
            json.dumps(base)]
    try:
        r = subprocess.run(argv, timeout=600, capture_output=True,
                           text=True, env=child_env)
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        return json.loads(last)
    except subprocess.TimeoutExpired:
        print(json.dumps({"label": "elastic/elastic", "rep": rep,
                          "error": "timeout after 600s"}), flush=True)
        return None
    except (IndexError, ValueError):
        print(json.dumps(
            {"label": "elastic/elastic", "rep": rep,
             "error": f"stage exited {r.returncode}",
             "stderr_tail": r.stderr[-800:]}), flush=True)
        return None


def capture_elastic_row(gens=8):
    """The committed-baseline elastic row (--capture-baseline): one
    sync-SPMD + one elastic-fleet measurement under the shared declared
    straggle_host plan, summarized for BENCH_r*.json extras."""
    base = {"gens": int(gens), "population": 16, "horizon": 64,
            "sleep_s": 0.3, "jitter_s": 0.1}
    sync_row = _run_elastic_leg("sync", base)
    el_row = _run_elastic_leg("elastic", base)
    if not sync_row or not el_row:
        return {"error": "one or both elastic legs failed", "cfg": base}
    return {
        "cfg": base,
        "sync_gps": sync_row["gps"],
        "elastic_gps": el_row["gps"],
        "ratio": round(el_row["gps"] / sync_row["gps"], 3),
        "results_folded": el_row.get("results_folded"),
        "results_lost": el_row.get("results_lost"),
        "accounting_ok": el_row.get("accounting_ok"),
    }


def stage_elastic_ab(selfcheck=False):
    """Synchronous-SPMD-multihost vs elastic host-granular fold A/B
    under an identical declared straggle_host plan (ISSUE 15
    acceptance; the selfcheck form is the run_lint.sh gate).
    Interleaved repeats, medians + a noise band learned from the
    repeats via ``obs regress``.  Exit 0 only when (1) elastic
    generation throughput beats the synchronous multihost loop by >=
    1.25x beyond the band, (2) stale host contributions actually folded
    (the plan MUST have exercised the IW path), and (3) the
    zero-silent-drop accounting holds: dispatched == consumed +
    discarded + lost."""
    regress = _load_obs_regress()
    base = ({"gens": 6, "population": 16, "horizon": 64,
             "sleep_s": 0.3, "jitter_s": 0.1}
            if selfcheck else
            {"gens": 12, "population": 16, "horizon": 64,
             "sleep_s": 0.5, "jitter_s": 0.25})
    repeats = 2 if selfcheck else 3
    rates = {"sync": [], "elastic": []}
    elastic_rows = []
    for rep in range(repeats):
        for mode in ("sync", "elastic"):
            row = _run_elastic_leg(mode, base, rep)
            if row is None:
                continue
            if mode == "elastic":
                elastic_rows.append(row)
            rates[mode].append(row["gps"])
            print(json.dumps({"label": f"elastic/{mode}", "rep": rep,
                              **row}), flush=True)
    if not rates["sync"] or not rates["elastic"]:
        print(json.dumps({"label": "elastic/ab",
                          "error": "one or both arms have no samples"}),
              flush=True)
        return 1
    verdict = regress.compare(rates["elastic"], rates["sync"],
                              metric="generations_per_sec")
    ratio = (verdict["current_median"] / verdict["baseline_median"]
             if verdict["baseline_median"] else None)
    folded = sum(r.get("results_folded", 0) for r in elastic_rows)
    accounting_ok = all(r.get("accounting_ok") for r in elastic_rows)
    ok = (
        ratio is not None and ratio >= 1.25
        and bool(verdict.get("improved"))
        and accounting_ok
        and folded > 0  # stale host contributions MUST have folded
    )
    print(json.dumps({
        "label": "elastic/ab",
        "sync_median_gps": verdict["baseline_median"],
        "elastic_median_gps": verdict["current_median"],
        "ratio": round(ratio, 3) if ratio else None,
        "band_pct": verdict["band_pct"],
        "improved_beyond_band": bool(verdict.get("improved")),
        "results_folded": folded,
        "stale_discarded": sum(r.get("stale_discarded", 0)
                               for r in elastic_rows),
        "results_lost": sum(r.get("results_lost", 0)
                            for r in elastic_rows),
        "hosts_lost": sum(r.get("hosts_lost", 0) for r in elastic_rows),
        "accounting_ok": accounting_ok,
        "pass": ok,
    }), flush=True)
    return 0 if ok else 1


def measure_shard_ab(cfg):
    """Child body for --stage-shard-ab-one: replicated vs param-sharded
    same-seed A/B on the virtual CPU mesh (estorch_tpu/parallel/sharded.py,
    docs/sharding.md).  Three legs in one process:

    1. numerical — a table-noise sharded run must match the replicated
       fused path allclose at f32 (reduction order is the only licensed
       difference);
    2. memory — per-device peak bytes (compile ledger memory_analysis;
       shard sizes for sharded inputs) of the sharded program vs the
       replicated program's on the SAME config;
    3. sharded row — the program-noise sharded config's rate + MFU from
       the shard-aware cost model (the headline row's recipe).
    """
    from estorch_tpu.utils import enable_compilation_cache, force_cpu_backend

    force_cpu_backend(8)
    enable_compilation_cache()
    import numpy as np
    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs import SyntheticEnv

    env = SyntheticEnv()
    pk = {"action_dim": env.action_dim, "hidden": tuple(cfg["hidden"]),
          "discrete": False, "action_scale": 1.0}
    common = dict(
        policy=MLPPolicy, agent=JaxAgent, optimizer=optax.adam,
        population_size=cfg["population"], sigma=0.05,
        policy_kwargs=pk,
        agent_kwargs={"env": env, "horizon": cfg["horizon"]},
        optimizer_kwargs={"learning_rate": 1e-2}, seed=0,
        eval_chunk=cfg.get("eval_chunk", 8),
        table_size=cfg.get("table_size", 1 << 21),
        telemetry=True,
    )
    gens = int(cfg.get("gens", 3))
    out = {"cfg": cfg}

    def ledger_peak(es, program):
        for rec in es.history:
            for e in rec.get("compile_events", []):
                if e.get("program") == program and "peak_bytes" in e:
                    return e["peak_bytes"]
        return None

    es_r = ES(**common)
    es_r.train(gens, verbose=False)
    es_s = ES(shard_params=True, noise_mode="table",
              model_shards=cfg.get("model_shards"), **common)
    es_s.train(gens, verbose=False)
    a = np.asarray(es_r.state.params_flat)
    b = np.asarray(es_s.state.params_flat)
    max_rel = float(np.max(np.abs(a - b) / np.maximum(np.abs(a), 1e-6)))
    out["numerical"] = {
        "match": bool(np.allclose(a, b, rtol=2e-4, atol=1e-5)),
        "max_rel_err": max_rel,
        "steps_equal": all(
            r1["env_steps"] == r2["env_steps"]
            for r1, r2 in zip(es_r.history, es_s.history)),
        "generations": gens,
    }
    # the sharded headline-row recipe: program noise, rate + MFU from
    # the shard-aware cost model
    prog_cfg = {**cfg, "shard": True, "telemetry": True}
    prog_cfg.pop("table_size", None)
    row = measure_one(prog_cfg, force_cpu=False)  # backend already forced
    out["sharded_row"] = {
        "rate": round(row["rate"], 1),
        "mfu": row["mfu"],
        "mfu_basis": row["mfu_basis"],
        **(row.get("shard") or {}),
    }
    # memory verdict: the SCALING mode (program noise — the sharded
    # default) vs the replicated program, per-device.  The table-mode
    # peak is reported but not gated: its 4·table_size replicated
    # argument is counted by memory_analysis while the replicated
    # engine's closed-over table lowers as an embedded constant the
    # arg/temp accounting does not see — comparing those two would be
    # apples to oranges (the parity mode exists for numerics, not scale)
    rep_peak = ledger_peak(es_r, "generation_step")
    prog_peak = out["sharded_row"].get("per_device_peak_bytes")
    out["memory"] = {
        "replicated_per_device_peak_bytes": rep_peak,
        "sharded_per_device_peak_bytes": prog_peak,
        "sharded_table_mode_peak_bytes": ledger_peak(
            es_s, "generation_step_sharded"),
        "ratio": (round(prog_peak / rep_peak, 4)
                  if rep_peak and prog_peak else None),
        # the analytic replicated bound the test narrative uses: params
        # + adam moments, f32, on EVERY device when replicated
        "replicated_state_bytes": int(3 * es_r.engine.spec.dim * 4),
    }
    return out


def stage_shard_ab(selfcheck=False):
    """Replicated-vs-sharded A/B via the stage protocol; the selfcheck
    form is the run_lint.sh gate.  Exit 0 only when the sharded path (1)
    matches the replicated fused path numerically at the same seed, (2)
    fits in LESS per-device memory than the replicated program on the
    same config, and (3) produces a non-null MFU from the shard-aware
    cost model."""
    cfg = ({"env": "synthetic", "hidden": [64, 64], "population": 32,
            "horizon": 50, "gens": 3, "eval_chunk": 8}
           if selfcheck else
           {"env": "synthetic", "hidden": [768, 768], "population": 64,
            "horizon": 100, "gens": 3, "eval_chunk": 8})
    argv = [sys.executable, __file__, "--stage-shard-ab-one",
            json.dumps(cfg)]
    try:
        r = subprocess.run(
            argv, timeout=900, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        print(json.dumps({"label": "shard/ab",
                          "error": "timeout after 900s"}), flush=True)
        return 1
    try:
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        row = json.loads(last)
    except (IndexError, ValueError):
        print(json.dumps({"label": "shard/ab",
                          "error": f"stage exited {r.returncode}",
                          "stderr_tail": r.stderr[-800:]}), flush=True)
        return 1
    num = row.get("numerical") or {}
    mem = row.get("memory") or {}
    srow = row.get("sharded_row") or {}
    mem_ok = (mem.get("ratio") is not None and mem["ratio"] < 1.0)
    verdict = {
        "label": "shard/ab",
        "numerical_match": bool(num.get("match")),
        "max_rel_err": num.get("max_rel_err"),
        "steps_equal": bool(num.get("steps_equal")),
        "memory": mem,
        "sharded_row": srow,
        "pass": (bool(num.get("match")) and bool(num.get("steps_equal"))
                 and mem_ok and srow.get("mfu") is not None),
    }
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["pass"] else 1


def measure_scenario_one(cfg):
    """Child body for --stage-scenario-one: the scenario suite's two
    claims, measured (estorch_tpu/scenarios, docs/scenarios.md):

    1. wall-clock — ONE domain-randomized run (N variants drawn
       in-program, traced operands) vs the old way to cover N scenarios:
       N sequential single-scenario runs, each compiling its own
       closed-over constants;
    2. compile ledger — the randomized run's program count must be
       independent of variant count (the traced-operand contract): an
       N-variant run and an N//3-variant run build the SAME number of
       XLA programs.

    The persistent compilation cache is deliberately NOT enabled here:
    the sequential leg's per-variant recompiles are the phenomenon being
    measured, and a warm cache on the second lint run would fake the
    win away.
    """
    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(1)
    import dataclasses
    import time

    import optax

    from estorch_tpu import ES, JaxAgent, MLPPolicy
    from estorch_tpu.envs.pendulum import Pendulum
    from estorch_tpu.scenarios import ScenarioDistribution

    variants = int(cfg.get("variants", 10))
    gens = int(cfg.get("gens", 3))
    horizon = int(cfg.get("horizon", 30))
    pop = int(cfg.get("population", 32))
    hidden = tuple(cfg.get("hidden", [16]))
    base_env = Pendulum()
    # absolute ranges (not the ±spread helper): the sequential leg
    # instantiates concrete Pendulum(**draw) envs from the same draws
    ranges = {"g": (7.0, 13.0), "m": (0.7, 1.3), "l": (0.7, 1.3)}

    def build(env=None, dist=None):
        return ES(
            MLPPolicy, JaxAgent(env or base_env, horizon=horizon),
            optax.adam, population_size=pop, sigma=0.05, seed=0,
            policy_kwargs={"action_dim": 1, "hidden": hidden,
                           "discrete": False, "action_scale": 2.0},
            optimizer_kwargs={"learning_rate": 0.01},
            table_size=1 << 15, scenarios=dist, telemetry=True)

    def n_compiles(es):
        return sum(len(r.get("compile_events", [])) for r in es.history)

    def run_randomized(n):
        dist = ScenarioDistribution(ranges, n_variants=n, seed=0)
        t0 = time.perf_counter()
        es = build(dist=dist)
        es.train(gens, verbose=False)
        wall = time.perf_counter() - t0
        seen: set = set()
        for r in es.history:
            seen |= {v for v, c in enumerate(r["scenarios"]["counts"])
                     if c}
        return {"wall_s": round(wall, 3), "compiles": n_compiles(es),
                "variants_seen": len(seen),
                "block": es.history[-1]["scenarios"]}

    # untimed process warm-up: the first ES build in a process pays
    # one-off eager-dispatch/op-cache costs that would otherwise land
    # entirely on whichever timed leg runs first
    warm = build(env=base_env)
    warm.train(1, verbose=False)

    out = {"cfg": cfg}
    out["randomized"] = run_randomized(variants)
    # the O(1)-programs control: far fewer variants, same program count
    out["randomized_small"] = run_randomized(max(2, variants // 3))
    dist = ScenarioDistribution(ranges, n_variants=variants, seed=0)
    t0 = time.perf_counter()
    seq_compiles = 0
    for v in range(variants):
        env_v = dataclasses.replace(base_env, **dist.draw_concrete(v))
        es_v = build(env=env_v)
        es_v.train(gens, verbose=False)
        seq_compiles += n_compiles(es_v)
    out["sequential"] = {
        "wall_s": round(time.perf_counter() - t0, 3),
        "compiles": seq_compiles,
        "runs": variants,
    }
    out["speedup"] = round(
        out["sequential"]["wall_s"] / max(out["randomized"]["wall_s"],
                                          1e-9), 2)
    return out


SCENARIO_SPEEDUP_GATE = 3.0  # one randomized run vs N sequential runs
SCENARIO_COVERAGE_GATE = 0.9  # fraction of variants a run must visit


def stage_scenario_ab(selfcheck=False):
    """Scenario-suite A/B via the stage protocol; the selfcheck form is
    the run_lint.sh gate.  Exit 0 only when (1) the N-variant randomized
    run beats N sequential single-scenario runs >= 3x wall-clock, (2)
    the compile-ledger program count is O(1) in variant count (N-variant
    == N//3-variant), and (3) per-variant fitness is surfaced with >=90%
    of variants visited."""
    cfg = ({"variants": 10, "gens": 3, "population": 48,
            "horizon": 60, "hidden": [48, 48]}
           if selfcheck else
           {"variants": 10, "gens": 3, "population": 64,
            "horizon": 100, "hidden": [32, 32]})
    argv = [sys.executable, __file__, "--stage-scenario-one",
            json.dumps(cfg)]
    try:
        r = subprocess.run(
            argv, timeout=900, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        print(json.dumps({"label": "scenario/ab",
                          "error": "timeout after 900s"}), flush=True)
        return 1
    try:
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        row = json.loads(last)
    except (IndexError, ValueError):
        print(json.dumps({"label": "scenario/ab",
                          "error": f"stage exited {r.returncode}",
                          "stderr_tail": r.stderr[-800:]}), flush=True)
        return 1
    rand = row.get("randomized") or {}
    small = row.get("randomized_small") or {}
    seq = row.get("sequential") or {}
    block = rand.get("block") or {}
    variants = int(cfg["variants"])
    coverage = rand.get("variants_seen", 0) / variants
    verdict = {
        "label": "scenario/ab",
        "speedup": row.get("speedup"),
        "speedup_gate": SCENARIO_SPEEDUP_GATE,
        "randomized_compiles": rand.get("compiles"),
        "small_variant_compiles": small.get("compiles"),
        "sequential_compiles": seq.get("compiles"),
        "programs_o1": rand.get("compiles") == small.get("compiles"),
        "variants_seen": rand.get("variants_seen"),
        "coverage": round(coverage, 3),
        "fitness_block_ok": (
            block.get("n_variants") == variants
            and sum(block.get("counts", [])) == int(cfg["population"])),
        "pass": (
            (row.get("speedup") or 0) >= SCENARIO_SPEEDUP_GATE
            and rand.get("compiles") == small.get("compiles")
            and coverage >= SCENARIO_COVERAGE_GATE
            and block.get("n_variants") == variants
            and sum(block.get("counts", [])) == int(cfg["population"])),
    }
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["pass"] else 1


def measure_serve_one(cfg):
    """Child body for --stage-serve-one: export a trained pendulum bundle,
    then run the dynamic-batching vs batch-size-1 serving A/B against it
    (both legs are the SAME server binary, only --max-batch differs).
    Also verifies the bit-exactness contract (served responses vs this
    process's es.predict on a batch — same --cpu-devices 1 config on both
    sides) and the SIGTERM drain.  Returns one JSON row."""
    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(1)
    import signal

    import jax
    import optax

    from estorch_tpu import ES, JaxAgent
    from estorch_tpu.envs.pendulum import Pendulum
    from estorch_tpu.models import MLPPolicy
    from estorch_tpu.serve.loadgen import run_load

    hidden = int(cfg.get("hidden", 256))
    gens = int(cfg.get("gens", 1))
    duration = float(cfg.get("duration_s", 2.0))
    max_batch = int(cfg.get("max_batch", 32))
    # table must cover the (hidden x hidden)-dominated param dim; the next
    # power of two above 2*hidden^2 always does
    table_size = max(1 << 14, 1 << (2 * hidden * hidden).bit_length())
    es = ES(
        MLPPolicy, JaxAgent(Pendulum(), horizon=8), optax.adam,
        population_size=4, sigma=0.05, seed=0,
        policy_kwargs={"action_dim": 1, "hidden": (hidden, hidden),
                       "discrete": False, "action_scale": 2.0},
        optimizer_kwargs={"learning_rate": 0.01},
        table_size=table_size,
        device=jax.devices()[0],
    )
    es.train(gens, verbose=False)
    # anchor-sized check set: served responses chain to the ANCHOR
    # (largest) bucket via the batcher's verification, and the anchor
    # shape is where es.predict's direct program and the serving vmap
    # agree — a reference at any other batch shape could legitimately
    # differ by 1 ulp (tests/test_serve.py sizes its check set the same
    # way)
    rng = np.random.default_rng(0)
    check_obs = rng.standard_normal((max_batch, 3)).astype(np.float32)
    ref = np.asarray(es.predict(check_obs))

    def leg(mb, conns):
        port_file = os.path.join(workdir, f"port_{mb}.json")
        argv = [sys.executable, "-m", "estorch_tpu.serve", "--bundle",
                bundle, "--port", "0", "--port-file", port_file,
                "--cpu-devices", "1", "--max-batch", str(mb),
                "--beat-interval", "0.5"]
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "ESTORCH_OBS_HEARTBEAT": os.path.join(workdir,
                                                     f"hb_{mb}.json")}
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE, text=True,
                                env=env)
        try:
            ready = json.loads(proc.stdout.readline())
            addr = ready["url"]
            # correctness pass first: every response must be bit-equal to
            # the exporting run's es.predict rows (GEMM family — buckets
            # are >= 2 whenever max_batch >= 2; the max_batch=1 leg is
            # the GEMV family, equal to es.predict on single obs)
            chk = run_load(addr, conns=4, total=len(check_obs),
                           duration_s=30.0,
                           obs_list=[o.tolist() for o in check_obs],
                           collect_responses=True)
            if mb == 1:
                exact_ref = np.stack([
                    np.asarray(es.predict(o)) for o in check_obs])
            else:
                exact_ref = ref
            # a lost/non-200 check response is a FINDING (bit_exact
            # False + its row listed), not a stage crash
            acts = [r.get("action") if isinstance(r, dict) else None
                    for r in chk["responses"]]
            if any(a is None for a in acts):
                bit_exact = False
                mismatch_rows = [i for i, a in enumerate(acts)
                                 if a is None]
            else:
                got = np.asarray(acts, np.float32)
                bit_exact = got.tobytes() == exact_ref.tobytes()
                mismatch_rows = [] if bit_exact else [
                    i for i in range(len(check_obs))
                    if got[i].tobytes() != exact_ref[i].tobytes()]
            load = run_load(addr, conns=conns, duration_s=duration,
                            obs=[0.1, 0.2, 0.3])
            from estorch_tpu.serve.client import ServeClient

            with ServeClient(addr) as c:
                stats = c.stats()
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=60)
            final = json.loads(out.strip().splitlines()[-1])
            return {
                "rps": load["throughput_rps"],
                "p50_ms": load["latency_ms"]["p50"],
                "p99_ms": load["latency_ms"]["p99"],
                "errors": load["errors"] + chk["errors"],
                "shed": int(stats["shed_total"]),
                "recompiles": int(stats["recompiles"]),
                "n_buckets": len(stats["buckets"])
                + len(stats.get("buckets_excluded", [])),
                "buckets_excluded": stats.get("buckets_excluded", []),
                "mean_batch": stats["mean_batch"],
                "bit_exact": bit_exact,
                **({"bit_mismatch_rows": mismatch_rows}
                   if mismatch_rows else {}),
                "drain_clean": bool(final.get("clean"))
                and proc.returncode == 0,
            }
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    # the exported policy is large (hidden^2 params); the finally covers
    # EVERYTHING from export on, or a failed run leaks 100+ MB in /tmp
    import shutil

    workdir = tempfile.mkdtemp(prefix="serve_bench_")
    try:
        bundle = es.export_bundle(os.path.join(workdir, "bundle"))
        dyn = leg(max_batch, conns=int(cfg.get("conns", 32)))
        b1 = leg(1, conns=8)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    ratio = (dyn["rps"] / b1["rps"]) if b1["rps"] else None
    return {"hidden": hidden, "dyn": dyn, "b1": b1,
            "ratio": round(ratio, 2) if ratio else None, "cfg": cfg}


def stage_serve(selfcheck=False):
    """Serving A/B via the stage protocol; the selfcheck form is the
    run_lint.sh gate (functional: bit-exactness, clean drain, bucket
    accounting — the ≥3x throughput win is gated by the full form and by
    the tier-1 serving demo, which size the policy to be memory-bound).
    Returns the process exit code."""
    cfg = ({"hidden": 256, "gens": 1, "duration_s": 1.5, "conns": 16}
           if selfcheck else
           {"hidden": 4096, "gens": 1, "duration_s": 4.0, "conns": 32})
    argv = [sys.executable, __file__, "--stage-serve-one", json.dumps(cfg)]
    child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        r = subprocess.run(argv, timeout=900, capture_output=True,
                           text=True, env=child_env)
    except subprocess.TimeoutExpired:
        print(json.dumps({"label": "serve", "error": "timeout after 900s"}),
              flush=True)
        return 1
    try:
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        row = json.loads(last)
    except (IndexError, ValueError):
        print(json.dumps({"label": "serve", "error":
                          f"stage exited {r.returncode}",
                          "stderr_tail": r.stderr[-800:]}), flush=True)
        return 1
    dyn, b1 = row["dyn"], row["b1"]
    functional = (
        dyn["bit_exact"] and b1["bit_exact"]
        and dyn["drain_clean"] and b1["drain_clean"]
        and dyn["errors"] == 0 and b1["errors"] == 0
        and dyn["shed"] == 0 and b1["shed"] == 0
        and dyn["recompiles"] <= dyn["n_buckets"]
        and b1["recompiles"] <= b1["n_buckets"]
    )
    ok = functional if selfcheck else (
        functional and row["ratio"] is not None and row["ratio"] >= 3.0)
    print(json.dumps({"label": "serve/ab", **row, "pass": ok}), flush=True)
    return 0 if ok else 1


def measure_coldstart_one(cfg):
    """Child body for --stage-coldstart-one: export the demo pendulum
    policy as a WARM bundle (packed XLA-cache entries + bf16 opt-in,
    serve/warm.py), then measure, in fresh server processes:

    * warm vs cold (--no-warm) legs, ``repeats`` each: process spawn →
      ready, ready → first response (the JIT pause lands here on the
      cold leg), first-``first_n``-requests p99, and the compile-ledger
      proof (compiles_at_load / warm_cache_hits from /stats);
    * steady-state bf16 vs f32 batched throughput in-process at the
      anchor bucket, with the measured per-bucket divergence.

    Returns one JSON row; the parent (stage_coldstart) gates it."""
    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(1)
    import signal

    import jax
    import optax

    from estorch_tpu import ES, JaxAgent
    from estorch_tpu.envs.pendulum import Pendulum
    from estorch_tpu.models import MLPPolicy
    from estorch_tpu.serve.loadgen import coldstart_probe

    hidden = int(cfg.get("hidden", 6144))
    gens = int(cfg.get("gens", 1))
    max_batch = int(cfg.get("max_batch", 16))
    repeats = int(cfg.get("repeats", 3))
    first_n = int(cfg.get("first_n", 100))
    table_size = max(1 << 14, 1 << (2 * hidden * hidden).bit_length())
    es = ES(
        MLPPolicy, JaxAgent(Pendulum(), horizon=8), optax.adam,
        population_size=4, sigma=0.05, seed=0,
        policy_kwargs={"action_dim": 1, "hidden": (hidden, hidden),
                       "discrete": False, "action_scale": 2.0},
        optimizer_kwargs={"learning_rate": 0.01},
        table_size=table_size,
        device=jax.devices()[0],
    )
    es.train(gens, verbose=False)

    def leg(no_warm):
        port_file = os.path.join(workdir,
                                 f"port_{'c' if no_warm else 'w'}.json")
        argv = [sys.executable, "-m", "estorch_tpu.serve", "--bundle",
                bundle, "--port", "0", "--port-file", port_file,
                "--cpu-devices", "1", "--max-batch", str(max_batch),
                "--beat-interval", "0.5"] + (["--no-warm"] if no_warm
                                             else [])
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        t_spawn = time.perf_counter()
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE, text=True,
                                env=env)
        try:
            ready = json.loads(proc.stdout.readline())
            ready_s = time.perf_counter() - t_spawn
            addr = ready["url"].split("://", 1)[1]
            probe = coldstart_probe(addr, total=first_n, conns=4,
                                    obs=[0.1, 0.2, 0.3])
            from estorch_tpu.serve.client import ServeClient

            with ServeClient(addr) as c:
                stats = c.stats()
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
            final = json.loads(out.strip().splitlines()[-1])
            cold = stats.get("cold_start") or {}
            return {
                "ready_s": round(ready_s, 3),
                # spawn -> first answered request: THE cold-start metric
                "ttfr_s": round(ready_s + (probe["ttfr_s"] or 0.0), 3),
                "first_p99_ms": probe["first_p99_ms"],
                "first_p50_ms": probe["first_p50_ms"],
                "errors": probe["errors"],
                "compiles_at_load": cold.get("compiles_at_load"),
                "warm_cache_hits": cold.get("warm_cache_hits"),
                "warm_installed": bool((cold.get("warm") or {})
                                       .get("installed")),
                "drain_clean": bool(final.get("clean"))
                and proc.returncode == 0,
            }
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    def steady_state_bf16():
        """Anchor-bucket batched throughput, f32 vs bf16, in-process.
        Fenced (np.asarray materializes) and median-of-repeats."""
        import statistics

        import numpy as np

        from estorch_tpu.serve.batcher import measure_quant_divergence
        from estorch_tpu.serve.bundle import load_bundle

        b = load_bundle(bundle, install_warm=True)
        f32 = b.batched_predict_fn()
        bf16 = b.batched_predict_fn(dtype="bf16")
        rng = np.random.default_rng(0)
        obs = rng.standard_normal(
            (max_batch,) + b.obs_shape).astype(np.float32)
        div = measure_quant_divergence(bf16, f32, b.obs_shape,
                                       [max_batch])
        out = {}
        for name, fn in (("f32", f32), ("bf16", bf16)):
            fn(obs)  # compile/warm outside the timed window
            ts = []
            for _ in range(7):
                t0 = time.perf_counter()
                np.asarray(fn(obs))
                ts.append(time.perf_counter() - t0)
            med = statistics.median(ts)
            out[name] = {"ms_per_batch": round(med * 1e3, 3),
                         "rows_per_s": round(max_batch / med, 1)}
        ratio = (out["f32"]["ms_per_batch"] / out["bf16"]["ms_per_batch"]
                 if out["bf16"]["ms_per_batch"] else None)
        return {
            **out,
            "throughput_ratio": round(ratio, 3) if ratio else None,
            "divergence": {str(k): round(v, 6) for k, v in div.items()},
            # XLA:CPU has no bf16 GEMM kernel (measured: the upconvert
            # path is SLOWER than f32) — the >=1.5x gate applies where
            # the hardware has one (TPU MXU); off-chip the number is
            # recorded honestly and the MACHINERY is what's gated
            "bf16_native": jax.default_backend() == "tpu",
            "platform": jax.default_backend(),
        }

    import shutil

    workdir = tempfile.mkdtemp(prefix="coldstart_bench_")
    try:
        t0 = time.perf_counter()
        bundle = es.export_bundle(os.path.join(workdir, "bundle"),
                                  warm=True, warm_max_batch=max_batch,
                                  serve_bf16=True)
        export_warm_s = round(time.perf_counter() - t0, 3)
        warm_rows = [leg(no_warm=False) for _ in range(repeats)]
        cold_rows = [leg(no_warm=True) for _ in range(repeats)]
        bf16_row = steady_state_bf16()
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return {"hidden": hidden, "max_batch": max_batch,
            "first_n": first_n, "export_warm_s": export_warm_s,
            "warm": warm_rows, "cold": cold_rows, "bf16": bf16_row,
            "platform": "cpu", "cfg": cfg}


def stage_coldstart(selfcheck=False):
    """Cold-start + quantized-serving gate (docs/serving.md "Cold start
    & quantized serving"); the selfcheck form is the run_lint.sh gate.

    Gates: the warm leg loads with ZERO fresh XLA builds (all
    persistent-cache hits) while the cold leg provably pays the storm;
    warm time-to-first-response beats cold beyond the learned noise band
    (obs regress compare on repeat medians); every bf16 bucket's
    divergence is MEASURED and inside the documented bound; and — on
    hardware with a native bf16 path (TPU) — bf16 steady-state batch
    throughput >= 1.5x f32.  Off-chip the ratio is recorded honestly
    (XLA:CPU's bf16 lowering is an upconvert; see BENCHMARKS.md) and the
    accuracy machinery is what gates."""
    regress = _load_obs_regress()
    cfg = ({"hidden": 1024, "gens": 1, "repeats": 3, "first_n": 40,
            "max_batch": 16}
           if selfcheck else
           {"hidden": 6144, "gens": 1, "repeats": 3, "first_n": 100,
            "max_batch": 16})
    argv = [sys.executable, __file__, "--stage-coldstart-one",
            json.dumps(cfg)]
    child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        r = subprocess.run(argv, timeout=1800, capture_output=True,
                           text=True, env=child_env)
    except subprocess.TimeoutExpired:
        print(json.dumps({"label": "coldstart",
                          "error": "timeout after 1800s"}), flush=True)
        return 1
    try:
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        row = json.loads(last)
    except (IndexError, ValueError):
        print(json.dumps({"label": "coldstart", "error":
                          f"stage exited {r.returncode}",
                          "stderr_tail": r.stderr[-800:]}), flush=True)
        return 1
    problems = []
    for leg, rows in (("warm", row["warm"]), ("cold", row["cold"])):
        for i, x in enumerate(rows):
            # per-repeat BENCH rows so `obs regress --label
            # coldstart/<leg>` can gate them against a committed file:
            # value is a RATE (first responses per second) because the
            # regress verdict treats higher as better
            print(json.dumps({
                "label": f"coldstart/{leg}", "rep": i,
                "metric": "first_response_per_s",
                "value": round(1.0 / x["ttfr_s"], 4),
                "platform": row["platform"], **x}), flush=True)
            if x["errors"]:
                problems.append(f"{leg} rep {i}: {x['errors']} errors")
            if not x["drain_clean"]:
                problems.append(f"{leg} rep {i}: unclean drain")
    for i, x in enumerate(row["warm"]):
        if x["compiles_at_load"] != 0:
            problems.append(
                f"warm rep {i}: {x['compiles_at_load']} fresh XLA builds "
                "at load (want 0 — every program a cache/AOT hit)")
        if not x["warm_cache_hits"]:
            problems.append(f"warm rep {i}: zero cache hits")
        if not x["warm_installed"]:
            problems.append(f"warm rep {i}: warmth not installed")
    for i, x in enumerate(row["cold"]):
        if not x["compiles_at_load"]:
            problems.append(
                f"cold rep {i}: no fresh builds — the control leg did "
                "not pay the JIT storm this A/B exists to show")
    # warm beats cold on time-to-first-response beyond the learned band
    warm_rates = [1.0 / x["ttfr_s"] for x in row["warm"]]
    cold_rates = [1.0 / x["ttfr_s"] for x in row["cold"]]
    verdict = regress.compare(warm_rates, cold_rates,
                              metric="first_response_per_s")
    if not verdict["improved"]:
        problems.append(
            f"warm TTFR does not beat cold beyond the noise band: "
            f"warm median {verdict['current_median']}/s vs cold "
            f"{verdict['baseline_median']}/s (band "
            f"{verdict['band_pct']}%)")
    bf16 = row["bf16"]
    bound_key = max(bf16["divergence"], key=lambda k: bf16["divergence"][k])
    from estorch_tpu.serve.warm import BF16_DIVERGENCE_BOUND

    if bf16["divergence"][bound_key] > BF16_DIVERGENCE_BOUND:
        problems.append(
            f"bf16 divergence {bf16['divergence']} exceeds the bound "
            f"{BF16_DIVERGENCE_BOUND}")
    if bf16["bf16_native"] and (bf16["throughput_ratio"] or 0) < 1.5:
        problems.append(
            f"bf16 steady-state ratio {bf16['throughput_ratio']} < 1.5x "
            "on a native-bf16 platform")
    ok = not problems
    print(json.dumps({"label": "coldstart", "export_warm_s":
                      row["export_warm_s"], "ttfr": verdict,
                      "bf16": bf16, "problems": problems, "pass": ok}),
          flush=True)
    return 0 if ok else 1


def measure_fleet_one(cfg):
    """Child body for --stage-fleet-one: export a warm bundle, run a
    2-replica fleet + front router (serve/fleet.py) with a declared
    ``kill_replica`` chaos event mid-load, then a capacity sweep against
    the router.  Returns one JSON row; stage_fleet gates it."""
    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(1)
    import jax
    import optax

    from estorch_tpu import ES, JaxAgent
    from estorch_tpu.envs.pendulum import Pendulum
    from estorch_tpu.models import MLPPolicy
    from estorch_tpu.resilience.chaos import CHAOS_ENV
    from estorch_tpu.serve.client import ServeClient
    from estorch_tpu.serve.fleet import Fleet
    from estorch_tpu.serve.loadgen import capacity_sweep, run_load

    hidden = int(cfg.get("hidden", 64))
    max_batch = int(cfg.get("max_batch", 4))
    duration_s = float(cfg.get("duration_s", 6.0))
    kill_at_s = float(cfg.get("kill_at_s", 2.0))
    es = ES(
        MLPPolicy, JaxAgent(Pendulum(), horizon=8), optax.adam,
        population_size=4, sigma=0.05, seed=0,
        policy_kwargs={"action_dim": 1, "hidden": (hidden, hidden),
                       "discrete": False, "action_scale": 2.0},
        optimizer_kwargs={"learning_rate": 0.01},
        table_size=1 << 14, device=jax.devices()[0],
    )
    es.train(1, verbose=False)

    import shutil

    workdir = tempfile.mkdtemp(prefix="fleet_bench_")
    fleet = None
    try:
        bundle = es.export_bundle(os.path.join(workdir, "bundle"),
                                  warm=True, warm_max_batch=max_batch)
        fleet = Fleet(
            {"schema": 1, "bundle": bundle, "replicas": 2,
             "serve": {"max_batch": max_batch, "cpu_devices": 1},
             "router": {"retry_budget": 2, "breaker_open_s": 0.5},
             "respawn": {"backoff_s": 0.2}},
            os.path.join(workdir, "run"), port=0)
        fleet.start()
        if not fleet.wait_ready(180):
            return {"error": "fleet did not come up",
                    "status": fleet.status()}
        # INITIAL spawns carry the same warmth proof as respawns:
        # wait_ready() pinned each slot's cold_start from /stats
        initial_cold = [
            {"replica": s.name,
             "compiles_at_load": (s.cold_start or {}).get(
                 "compiles_at_load")}
            for s in fleet.slots]
        # declare the chaos only once the fleet serves: kill_at_s means
        # seconds into SERVING, not into the replicas' jax import
        os.environ[CHAOS_ENV] = json.dumps({
            "events": [{"kind": "kill_replica", "at_s": kill_at_s,
                        "replica": 1}],
            "ledger": os.path.join(workdir, "chaos_ledger")})
        fleet.arm_chaos()
        addr = f"{fleet.router.host}:{fleet.router.port}"
        load = run_load(addr, conns=8, duration_s=duration_s,
                        obs=[0.1, 0.2, 0.3])
        # wait for the respawn to land so its warm proof is readable
        t0 = time.monotonic()
        respawned = False
        while time.monotonic() - t0 < 120:
            slot = fleet.slots[1]
            breakers = {r.name: r.breaker.state
                        for r in fleet.router.replicas()}
            if (slot.restarts >= 1 and slot.state == "up"
                    and breakers.get("r1") == "closed"):
                respawned = True
                break
            time.sleep(0.2)
        cold = None
        if respawned:
            with ServeClient(fleet.slots[1].address) as c:
                cold = c.stats().get("cold_start")
        sweep = capacity_sweep(addr, slo_ms=float(
            cfg.get("slo_ms", 2000.0)),
            rps_ladder=[float(r) for r in cfg.get("rps_ladder",
                                                  [50, 100])],
            conns=8, rung_duration_s=float(cfg.get("rung_s", 1.0)),
            obs=[0.1, 0.2, 0.3])
        st = fleet.router.stats()
        return {
            "load": {k: load[k] for k in ("requests", "errors", "shed",
                                          "throughput_rps",
                                          "latency_ms")},
            "counters": st["counters"],
            "respawned": respawned,
            "respawn_cold_start": cold,
            "initial_cold_starts": initial_cold,
            "events": [e["event"] for e in fleet.events],
            "capacity": sweep,
            "platform": "cpu", "cfg": cfg,
        }
    finally:
        if fleet is not None:
            fleet.shutdown()
        os.environ.pop(CHAOS_ENV, None)
        shutil.rmtree(workdir, ignore_errors=True)


def stage_fleet(selfcheck=False):
    """Fleet robustness gate (serve/router.py + serve/fleet.py,
    docs/serving.md "Fleet"); the selfcheck form is the run_lint.sh
    gate.  Gates: a replica SIGKILLed under concurrent load loses ZERO
    client answers (failover retries within the budget), the breaker
    opened and re-closed, the fleet respawned the corpse WARM
    (compiles_at_load == 0 — PR-12 bundles make a respawn free), and
    the capacity sweep reports a sane max-RPS-at-SLO ladder."""
    cfg = ({"hidden": 48, "duration_s": 5.0, "kill_at_s": 2.0,
            "rps_ladder": [40, 80], "rung_s": 0.8}
           if selfcheck else
           {"hidden": 512, "duration_s": 10.0, "kill_at_s": 3.0,
            "rps_ladder": [50, 100, 200, 400], "rung_s": 2.0})
    argv = [sys.executable, __file__, "--stage-fleet-one",
            json.dumps(cfg)]
    child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        r = subprocess.run(argv, timeout=900, capture_output=True,
                           text=True, env=child_env)
    except subprocess.TimeoutExpired:
        print(json.dumps({"label": "fleet", "error": "timeout after "
                                                     "900s"}),
              flush=True)
        return 1
    try:
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        row = json.loads(last)
    except (IndexError, ValueError):
        print(json.dumps({"label": "fleet", "error":
                          f"stage exited {r.returncode}",
                          "stderr_tail": r.stderr[-800:]}), flush=True)
        return 1
    problems = []
    if row.get("error"):
        problems.append(row["error"])
    else:
        load = row["load"]
        if load["errors"] or load["shed"]:
            problems.append(
                f"lost client answers under the kill: {load['errors']} "
                f"errors, {load['shed']} shed of {load['requests']}")
        if load["requests"] < 50:
            problems.append(f"load too thin to prove anything: "
                            f"{load['requests']} requests")
        c = row["counters"]
        if not c.get("router_breaker_opens_total"):
            problems.append("breaker never opened for the killed "
                            "replica")
        # INITIAL spawns are judged by the same warmth bar as respawns
        # (today's bundles make the first load free too)
        for ic in row.get("initial_cold_starts") or []:
            if ic.get("compiles_at_load") != 0:
                problems.append(
                    f"initial spawn {ic.get('replica')} was not warm: "
                    f"compiles_at_load={ic.get('compiles_at_load')} "
                    f"(want 0)")
        if not row["respawned"]:
            problems.append("fleet did not respawn the killed replica "
                            "(or its breaker never re-closed)")
        else:
            # warmth is only measurable on a replica that DID respawn
            cold = row.get("respawn_cold_start") or {}
            if cold.get("compiles_at_load") != 0:
                problems.append(
                    f"respawn was not warm: compiles_at_load="
                    f"{cold.get('compiles_at_load')} (want 0)")
        cap = row["capacity"]
        if cap.get("max_rps_at_slo") is None:
            problems.append(f"capacity sweep found no passing rung: "
                            f"{cap}")
        if any(not rung["requests"] for rung in cap["rungs"]):
            problems.append(f"capacity rung ran zero requests: "
                            f"{cap['rungs']}")
    ok = not problems
    print(json.dumps({"label": "fleet", **row, "problems": problems,
                      "pass": ok}), flush=True)
    return 0 if ok else 1


def measure_autoscale_one(cfg):
    """Child body for --stage-autoscale-one: the full closed control
    loop on loopback — warm bundle, 2-replica fleet, in-process
    collector scraping the router into a store, capacity artifact from
    a real sweep, and the autoscaler actuating over HTTP POST /scale.
    Offered load triples mid-run, a declared ``kill_replica`` chaos
    event lands during the scale-up, then traffic drops to a trickle so
    the low-watermark path retires a replica.  Returns one JSON row;
    stage_autoscale gates it."""
    import threading

    from estorch_tpu.utils import force_cpu_backend

    force_cpu_backend(1)
    import jax
    import optax

    from estorch_tpu import ES, JaxAgent
    from estorch_tpu.envs.pendulum import Pendulum
    from estorch_tpu.models import MLPPolicy
    from estorch_tpu.obs.agg import autoscale as azmod
    from estorch_tpu.obs.agg.collector import Collector, Target
    from estorch_tpu.obs.agg.store import SeriesStore
    from estorch_tpu.resilience.chaos import CHAOS_ENV
    from estorch_tpu.serve.fleet import Fleet
    from estorch_tpu.serve.loadgen import (capacity_sweep, run_load,
                                           write_capacity_artifact)

    hidden = int(cfg.get("hidden", 48))
    max_batch = int(cfg.get("max_batch", 4))
    slo_ms = float(cfg.get("slo_ms", 2000.0))
    base_rps = float(cfg.get("base_rps", 25.0))
    es = ES(
        MLPPolicy, JaxAgent(Pendulum(), horizon=8), optax.adam,
        population_size=4, sigma=0.05, seed=0,
        policy_kwargs={"action_dim": 1, "hidden": (hidden, hidden),
                       "discrete": False, "action_scale": 2.0},
        optimizer_kwargs={"learning_rate": 0.01},
        table_size=1 << 14, device=jax.devices()[0],
    )
    es.train(1, verbose=False)

    import shutil

    workdir = tempfile.mkdtemp(prefix="autoscale_bench_")
    fleet = scaler = None
    col_stop = threading.Event()
    col_thread = None
    try:
        bundle = es.export_bundle(os.path.join(workdir, "bundle"),
                                  warm=True, warm_max_batch=max_batch)
        fleet = Fleet(
            {"schema": 1, "bundle": bundle, "replicas": 2,
             "serve": {"max_batch": max_batch, "cpu_devices": 1},
             "router": {"retry_budget": 2, "breaker_open_s": 0.5},
             "respawn": {"backoff_s": 0.2},
             "autoscale": {"min_replicas": 2, "max_replicas": 4}},
            os.path.join(workdir, "run"), port=0)
        fleet.start()
        if not fleet.wait_ready(180):
            return {"error": "fleet did not come up",
                    "status": fleet.status()}
        addr = f"{fleet.router.host}:{fleet.router.port}"
        # per-replica capacity model from a REAL sweep against one
        # replica (not the router): the artifact the policy trusts
        sweep = capacity_sweep(
            fleet.slots[0].address, slo_ms=slo_ms,
            rps_ladder=[float(cfg.get("cap_rps", 40.0))], conns=8,
            rung_duration_s=float(cfg.get("cap_rung_s", 1.0)),
            obs=[0.1, 0.2, 0.3])
        if sweep.get("max_rps_at_slo") is None:
            return {"error": f"capacity sweep saturated: {sweep}"}
        cap_path = os.path.join(workdir, "capacity.json")
        write_capacity_artifact(sweep, cap_path, bundle=bundle)
        # in-process collector: scrape the router into the store the
        # autoscaler reads — the daemon never sees the fleet directly
        store_dir = os.path.join(workdir, "store")
        col = Collector([Target("fleet", url=f"http://{addr}/metrics",
                                timeout_s=5.0)],
                        SeriesStore(store_dir), None, serve_http=False)

        def scrape_loop():
            while not col_stop.is_set():
                col.tick()
                col_stop.wait(0.4)

        col_thread = threading.Thread(target=scrape_loop,
                                      name="bench-collector",
                                      daemon=True)
        col_thread.start()
        scaler = azmod.Autoscaler(
            store_dir, capacity=cap_path, fleet_admin=addr,
            interval_s=float(cfg.get("scaler_interval_s", 0.5)),
            policy={"min_replicas": 2, "max_replicas": 4,
                    "headroom": 1.2,
                    "window_s": float(cfg.get("window_s", 5.0)),
                    "up_cooldown_s": 3.0, "down_cooldown_s": 4.0,
                    "low_watermark": 0.5,
                    "low_hold_s": float(cfg.get("low_hold_s", 3.0))})
        scaler.start_background()
        # chaos declared now: at_s counts from arm — the kill lands in
        # the high-load phase, i.e. during/just after the scale-up
        os.environ[CHAOS_ENV] = json.dumps({
            "events": [{"kind": "kill_replica",
                        "at_s": float(cfg.get("kill_at_s", 8.0)),
                        "replica": 1}],
            "ledger": os.path.join(workdir, "chaos_ledger")})
        fleet.arm_chaos()
        phases = {}
        # phase A: baseline load the min fleet absorbs (target < min)
        phases["base"] = run_load(
            addr, mode="open", target_rps=base_rps,
            duration_s=float(cfg.get("base_s", 5.0)),
            conns=8, obs=[0.1, 0.2, 0.3])
        # phase B: offered load TRIPLES — demand math wants 3 replicas
        phases["spike"] = run_load(
            addr, mode="open", target_rps=base_rps * 3,
            duration_s=float(cfg.get("spike_s", 10.0)),
            conns=16, obs=[0.1, 0.2, 0.3])
        # the scale-up may still be spawning when the spike ends: wait
        # for desired AND actual to converge above the floor
        scaled_up = False
        t0 = time.monotonic()
        while time.monotonic() - t0 < 120:
            sc = fleet.status()["scale"]
            if sc["desired"] > 2 and sc["actual"] >= sc["desired"]:
                scaled_up = True
                break
            time.sleep(0.2)
        up_status = fleet.status()
        # phase C: trickle — utilization sits under the low watermark
        # until the sustained window retires a replica, drained
        phases["trickle"] = run_load(
            addr, mode="open", target_rps=float(cfg.get("trickle_rps",
                                                        4.0)),
            duration_s=float(cfg.get("trickle_s", 14.0)),
            conns=4, obs=[0.1, 0.2, 0.3])
        scaled_down = False
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            sc = fleet.status()["scale"]
            if sc["desired"] < up_status["scale"]["desired"] \
                    and sc["actual"] == sc["desired"]:
                scaled_down = True
                break
            time.sleep(0.2)
        scaler.stop()
        col_stop.set()
        rep = azmod.replay(scaler.log_path)
        events = [e["event"] for e in fleet.events]
        scale_events = [e for e in fleet.events
                        if e["event"].startswith("scale_")
                        or e["event"].startswith("replica_retir")]
        return {
            "phases": {k: {kk: v[kk] for kk in
                           ("requests", "errors", "shed",
                            "throughput_rps", "latency_ms")}
                       for k, v in phases.items()},
            "capacity": {"max_rps_at_slo": sweep["max_rps_at_slo"],
                         "slo_ms": sweep["slo_ms"]},
            "scaled_up": scaled_up,
            "scaled_down": scaled_down,
            "scale_status": fleet.status()["scale"],
            "scale_events": scale_events,
            "events": events,
            "counters": fleet.router.stats()["counters"],
            "replay": {"ok": rep["ok"], "decisions": rep["decisions"],
                       "mismatches": rep["mismatches"][:3]},
            "platform": "cpu", "cfg": cfg,
        }
    finally:
        if scaler is not None:
            scaler.stop()
        col_stop.set()
        if col_thread is not None:
            col_thread.join(timeout=10)
        if fleet is not None:
            fleet.shutdown()
        os.environ.pop(CHAOS_ENV, None)
        shutil.rmtree(workdir, ignore_errors=True)


def stage_autoscale(selfcheck=False):
    """Autoscaler E2E gate (obs/agg/autoscale.py + serve/fleet.py,
    docs/serving.md "Autoscaling"); the selfcheck form is the
    run_lint.sh gate.  Gates: offered load triples mid-run and the
    replica count demonstrably tracks it (up past the floor, back down
    after the trickle), p99 stays inside the SLO through every phase,
    ZERO client errors/shed including through a declared kill_replica
    during the scale-up, every scale-up replica loads warm
    (compiles_at_load == 0), the retirement drains cleanly, and the
    decision log replays bit-exactly."""
    cfg = ({"hidden": 48, "base_rps": 25.0, "base_s": 5.0,
            "spike_s": 10.0, "trickle_s": 14.0, "kill_at_s": 8.0}
           if selfcheck else
           {"hidden": 256, "base_rps": 40.0, "base_s": 8.0,
            "spike_s": 15.0, "trickle_s": 20.0, "kill_at_s": 12.0,
            "cap_rps": 60.0, "cap_rung_s": 2.0})
    argv = [sys.executable, __file__, "--stage-autoscale-one",
            json.dumps(cfg)]
    child_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    try:
        r = subprocess.run(argv, timeout=900, capture_output=True,
                           text=True, env=child_env)
    except subprocess.TimeoutExpired:
        print(json.dumps({"label": "autoscale",
                          "error": "timeout after 900s"}), flush=True)
        return 1
    try:
        last = [ln for ln in r.stdout.strip().splitlines()
                if ln.startswith("{")][-1]
        row = json.loads(last)
    except (IndexError, ValueError):
        print(json.dumps({"label": "autoscale", "error":
                          f"stage exited {r.returncode}",
                          "stderr_tail": r.stderr[-800:]}), flush=True)
        return 1
    problems = []
    if row.get("error"):
        problems.append(row["error"])
    else:
        slo_ms = row["capacity"]["slo_ms"]
        for name, load in row["phases"].items():
            if load["errors"] or load["shed"]:
                problems.append(
                    f"{name}: lost client answers: {load['errors']} "
                    f"errors, {load['shed']} shed of "
                    f"{load['requests']}")
            if load["latency_ms"]["p99"] > slo_ms:
                problems.append(
                    f"{name}: p99 {load['latency_ms']['p99']}ms "
                    f"breached the {slo_ms}ms SLO")
        if row["phases"]["spike"]["requests"] < 100:
            problems.append("spike phase too thin to prove tracking")
        if not row["scaled_up"]:
            problems.append(
                f"replica count never tracked the 3x load spike: "
                f"{row['scale_status']}")
        if not row["scaled_down"]:
            problems.append(
                f"no scale-down after the trickle window: "
                f"{row['scale_status']}")
        # every scale_up must be matched by a scale_up_warm proof
        # (compiles_at_load == 0 read off the new replica's /stats)
        for ev in row["scale_events"]:
            if ev["event"] == "scale_up_cold":
                problems.append(f"scale-up spawned COLD: {ev}")
        ups = [e for e in row["scale_events"]
               if e["event"] == "scale_up"]
        warm = [e for e in row["scale_events"]
                if e["event"] == "scale_up_warm"]
        if row["scaled_up"] and not ups:
            problems.append("scale-up left no added-replica evidence")
        if len(warm) < len(ups):
            problems.append(f"{len(ups)} scale-up(s) but only "
                            f"{len(warm)} warm proof(s)")
        retired = [e for e in row["scale_events"]
                   if e["event"] == "replica_retired"]
        if row["scaled_down"] and not any(e.get("drained")
                                          for e in retired):
            problems.append(f"retirement did not drain: {retired}")
        if "chaos_kill_replica" not in row["events"]:
            problems.append("declared kill_replica chaos never fired")
        if not row["replay"]["ok"] or not row["replay"]["decisions"]:
            problems.append(
                f"decision log did not replay bit-exactly: "
                f"{row['replay']}")
    ok = not problems
    print(json.dumps({"label": "autoscale", **row,
                      "problems": problems, "pass": ok}), flush=True)
    return 0 if ok else 1


def _default_regress_baseline() -> str | None:
    """Newest committed BENCH_r*.json beside this file, by name."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    cands = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    return cands[-1] if cands else None


def stage_regress(baseline: str | None, repeats: int = 3,
                  force_cpu: bool = False) -> int:
    """Perf gate against a committed baseline (obs/export/regress.py).

    The headline config is measured ``repeats`` times in fresh stage
    children (the --obs-ab repeat discipline: one run cannot resolve a
    small effect on a loaded shared core, so the verdict compares the
    repeat median and learns its noise band from the repeats); a drop
    beyond the band vs the baseline's recorded value exits 1.  Rows and
    the verdict land as JSON lines like every other stage."""
    regress = _load_obs_regress()
    baseline = baseline or _default_regress_baseline()
    if not baseline:
        print(json.dumps({"label": "regress", "error":
                          "no BENCH_r*.json baseline found"}), flush=True)
        return 2
    try:
        base_rows = regress.load_rows(baseline)
        base_samples, base_metric = regress.extract_samples(base_rows)
    except (OSError, ValueError) as e:
        print(json.dumps({"label": "regress",
                          "error": f"baseline: {e}"}), flush=True)
        return 2
    base_platform = regress.measurement_platform(base_rows)
    # probe BEFORE measuring: on a wedged host the repeats would each eat
    # a full stage timeout; the probe's cpu fallback surfaces the
    # platform mismatch against a TPU baseline in seconds instead
    force_cpu = _probe_or_force_cpu(force_cpu)
    rates = []
    cur_platform = None
    for rep in range(int(repeats)):
        r = run_stage(dict(SMALL), timeout_s=1200 if force_cpu else 600,
                      force_cpu=force_cpu)
        if r and r.get("rate"):
            rates.append(r["rate"])
            cur_platform = r.get("platform") or cur_platform
        print(json.dumps({"label": "regress/repeat", "rep": rep,
                          **(r or {"rate": None, "cfg": SMALL})}),
              flush=True)
    if not rates:
        print(json.dumps({"label": "regress",
                          "error": "every repeat failed"}), flush=True)
        return 2
    try:
        # the ONE platform guard compare_files uses: a cross-platform
        # verdict is a platform mismatch, not a perf result
        regress.ensure_same_platform(cur_platform, base_platform,
                                     cur_what="this run",
                                     base_what=baseline)
    except ValueError as e:
        print(json.dumps({"label": "regress", "baseline": baseline,
                          "error": str(e)}), flush=True)
        return 2
    verdict = regress.compare(rates, base_samples, metric=base_metric)
    print(json.dumps({"label": "regress", "baseline": baseline,
                      **verdict}), flush=True)
    return 0 if verdict["verdict"] == "pass" else 1


def stage_capture_baseline(out_path: str | None = None, repeats: int = 3,
                           gens: int = 12, skip: int = 2,
                           force_cpu: bool = False) -> int:
    """``bench.py --capture-baseline``: produce a committed-baseline
    artifact carrying what ALL the gates need (ROADMAP item 5) — the
    aggregate headline (median of fresh-process repeats), per-generation
    ``phase_rows`` embedded so ``obs regress --phases`` and ``--tail``
    can finally compare against committed history instead of ad-hoc
    reruns, and the typed device-probe verdict.  Writes the BENCH_r*
    schema (atomic tmp+rename) and prints the artifact path + headline
    as JSON lines."""
    regress = _load_obs_regress()
    probe = _probe_platform()
    fell_back = force_cpu or probe.get("status") != "ok"
    rates: list[float] = []
    phase_rows: list[dict] = []
    dtype = platform = None
    workdir = _bench_workdir()
    for rep in range(int(repeats)):
        hist_path = os.path.join(workdir, f"capture_hist_{rep}.jsonl")
        # skip covers the warm-up generation PLUS the first timed
        # generation(s): measured captures show the first timed gen
        # still pays compile/cache-load (~7s dispatch vs ~0.5ms steady),
        # and a tail baseline must defend steady state, not the warm-up
        cfg = {**SMALL, "gens": int(gens), "history_out": hist_path,
               "history_skip": int(skip)}
        r = run_stage(cfg, timeout_s=1800 if fell_back else 900,
                      force_cpu=fell_back)
        row = {"label": "capture/repeat", "rep": rep}
        if r and r.get("rate"):
            rates.append(r["rate"])
            dtype = r.get("dtype") or dtype
            platform = r.get("platform") or platform
            row["rate"] = round(r["rate"], 1)
            try:
                with open(hist_path) as f:
                    for ln in f:
                        rec = json.loads(ln)
                        rec["repeat"] = rep
                        phase_rows.append(rec)
                os.remove(hist_path)
            except (OSError, ValueError) as e:
                row["history_error"] = str(e)
        else:
            row["rate"] = None
        print(json.dumps(row), flush=True)
    if not rates or not phase_rows:
        print(json.dumps({"label": "capture", "error":
                          "no successful repeat with phase rows"}),
              flush=True)
        return 2
    rates.sort()
    n = len(rates)
    headline = rates[n // 2] if n % 2 else 0.5 * (rates[n // 2 - 1]
                                                  + rates[n // 2])
    # per-group p99s ride the extras so a human reading the committed
    # JSON sees the tail the --tail gate will defend
    groups = regress.extract_tail_groups(phase_rows)
    tail_headline = {
        name: {"p99_s": round(regress._quantile(samples, 0.99), 6),
               "n": len(samples)}
        for name, samples in sorted(groups.items())
    }
    phases_headline: dict = {}
    for name, samples in regress.extract_phase_samples(phase_rows).items():
        ss = sorted(samples)
        m = len(ss)
        phases_headline[name] = round(
            ss[m // 2] if m % 2 else 0.5 * (ss[m // 2 - 1] + ss[m // 2]), 6)
    # the elastic multi-host row (docs/multihost.md): one sync-SPMD +
    # one elastic-fleet leg under the shared straggle_host plan, so the
    # committed trajectory carries the barrier-vs-fold contrast the
    # --elastic-ab gate defends
    elastic_row = capture_elastic_row()
    print(json.dumps({"label": "capture/elastic", **elastic_row}),
          flush=True)
    artifact = {
        "n": len(rates),
        "cmd": "python bench.py --capture-baseline",
        "rc": 0,
        "platform": platform,
        "parsed": {
            "metric": "env_steps_per_sec_per_chip",
            "value": round(headline, 1),
            "unit": (f"env-steps/s/chip (Pendulum MLP64x64 pop4096 h200 "
                     f"standard/{dtype}, {platform})"),
        },
        "extras": {
            "device_probe": {**probe, "cpu_fallback": fell_back},
            "repeat_rates": [round(x, 1) for x in rates],
            "phases_headline": phases_headline,
            "tail_headline": tail_headline,
            "elastic": elastic_row,
        },
        # the embedded history the --phases/--tail gates consume
        # (obs/export/regress.py expand_embedded_rows)
        "phase_rows": phase_rows,
    }
    if out_path is None:
        here = os.path.dirname(os.path.abspath(__file__))
        idx = 1
        import glob

        for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
            tail = os.path.basename(p)[len("BENCH_r"):-len(".json")]
            if tail.isdigit():
                idx = max(idx, int(tail) + 1)
        out_path = os.path.join(here, f"BENCH_r{idx:02d}.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    os.replace(tmp, out_path)
    print(json.dumps({"label": "capture", "out": out_path,
                      "value": artifact["parsed"]["value"],
                      "n_phase_rows": len(phase_rows),
                      "phases": sorted(phases_headline)}), flush=True)
    _cleanup_bench_workdir()
    return 0


class EvidenceLockBusy(Exception):
    """The evidence flock is held by another measurement/study process."""


def acquire_evidence_lock(max_wait_s=None, respect_env=True):
    """THE lock protocol for the single host core (round-4 load-
    contamination lesson): every on-chip measurement and CPU-mesh study
    stage serializes through an flock on `.evidence.lock` at the repo
    root.  One implementation — bench.py, examples/ab_onchip_driver.py,
    and examples/tpu_watch.py all call this.

    Returns an open fd holding the lock (kernel releases it at process
    exit), or None when `respect_env` and EVIDENCE_LOCK_HELD is set (a
    parent — the watcher — already holds the lock and spawned us;
    re-taking it would self-deadlock).  `max_wait_s`: None blocks
    indefinitely, 0 is a non-blocking attempt, otherwise a bounded poll;
    on busy at the deadline raises EvidenceLockBusy."""
    if respect_env and os.environ.get("EVIDENCE_LOCK_HELD"):
        return None
    import fcntl
    fd = os.open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              ".evidence.lock"), os.O_CREAT | os.O_RDWR)
    if max_wait_s is None:
        fcntl.flock(fd, fcntl.LOCK_EX)
        return fd
    deadline = time.time() + max_wait_s
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return fd
        except BlockingIOError:
            if time.time() >= deadline:
                os.close(fd)
                raise EvidenceLockBusy(
                    f"evidence lock still busy after {max_wait_s:.0f}s")
            time.sleep(10.0)


def _lock_or_warn(max_wait_s=300.0):
    """Bounded wait, then proceed with a stderr note rather than risk an
    external caller's timeout nulling the round's one recorded bench."""
    try:
        return acquire_evidence_lock(max_wait_s=max_wait_s)
    except EvidenceLockBusy:
        print(f"bench: evidence lock still busy after {max_wait_s:.0f}s — "
              "proceeding; rates may be load-shared", file=sys.stderr)
        return None


def _probe_platform(timeout_s: float = 20.0) -> dict:
    """Platform decision in SECONDS, not by 480s stage-timeout discovery:
    the typed staged probe (doctor.check_device) proves the device path
    alive-or-wedged with a reason code, and the verdict — not a wedged
    stage's corpse — decides the cpu fallback for every stage driver."""
    probe = _load_doctor().check_device(timeout_s=timeout_s)
    print(f"bench: device probe: {probe.get('status')}"
          + (f" ({probe.get('reason')})" if probe.get("reason") else
             f" platform={probe.get('platform')}")
          + f" in {probe.get('elapsed_s')}s", file=sys.stderr)
    return probe


def _probe_or_force_cpu(force_cpu: bool) -> bool:
    """The stage drivers' platform decision: an explicit --cpu skips the
    probe; otherwise a failed probe forces the cpu fallback up front so a
    wedged device path costs one probe timeout, not a full stage timeout
    per repeat."""
    if force_cpu:
        return True
    return _probe_platform().get("status") != "ok"


def main():
    _lock_or_warn()
    _sweep_stale_bench_dirs()
    # the verdict rides the artifact as extras["device_probe"]
    probe = _probe_platform()
    # dtype deliberately unset: measure_one picks bf16 on TPU, f32 elsewhere.
    # Headline runs the STANDARD forward: the CPU A/B (bench_ab_cpu.jsonl,
    # committed) measures decomposed ~10% behind standard off-chip, and
    # flipping the headline before on-chip evidence would front-run the
    # A/B's decision
    headline_cfg = dict(SMALL)
    fell_back = False
    if probe.get("status") == "ok":
        result = run_stage(headline_cfg)
        if result is None:
            # probe said alive but the stage still died — fall back, and
            # the probe verdict in the artifact shows the contradiction
            with _filtered_stderr():
                result = measure_one(headline_cfg, force_cpu=True)
            fell_back = True
    else:
        with _filtered_stderr():
            result = measure_one(headline_cfg, force_cpu=True)
        fell_back = True
    rate, platform = result["rate"], result["platform"]
    on_tpu = platform == "tpu"
    base_rate = measure_reference_style_baseline()

    mfu = result["mfu"]
    extras = {
        "mfu_headline": mfu,
        # what the headline MFU's denominator IS: the v5e bf16 datasheet
        # peak on TPU, this host's measured GEMM ceiling off-chip —
        # cpu_calibrated numbers are honest, not comparable to silicon
        "mfu_basis": result.get("mfu_basis"),
        # typed probe verdict + reason code (replaces the old
        # "TPU-PATH-FAILED — see stderr" prose in the unit string)
        "device_probe": {**probe, "cpu_fallback": fell_back},
        "phases_headline": result.get("phases"),
    }
    # the sharded headline row (docs/sharding.md): the big-policy shape on
    # the param-sharded engine — in-program noise, donated generations,
    # MFU from the shard-aware cost model, per-device peak bytes from the
    # compile ledger.  Measured on both platforms (f32 by engine contract)
    shard_cfg = {**BIG, "shard": True, "gens": 3 if on_tpu else 2}
    r = run_stage(shard_cfg, timeout_s=600 if on_tpu else 1200,
                  force_cpu=not on_tpu)
    extras["sharded"] = (
        {"rate": round(r["rate"], 1),
         "mfu": round(r["mfu"], 6) if r["mfu"] is not None else None,
         "dtype": r["dtype"],
         **({} if on_tpu else {"cpu_relative": True}),
         **(r.get("shard") or {})}
        if r else None
    )
    if on_tpu:
        for name, base in (("big_policy", BIG), ("pop10k", POP10K),
                           ("locomotion", LOCO)):
            r = run_stage({**base, "gens": 3}, timeout_s=600)
            extras[name] = (
                {"rate": round(r["rate"], 1),
                 "mfu": round(r["mfu"], 6) if r["mfu"] is not None else None,
                 "dtype": r["dtype"],
                 "peak_hbm_gb": r.get("peak_hbm_gb")}
                if r else None
            )
    else:
        # Wedged-round artifact (round-4 verdict weak #1 / next #4): the one
        # JSON everyone reads must still show the architecture's scaling,
        # not just the smallest matmul.  Measure the big-policy / pop-10k /
        # locomotion / config-3-scale points on the CPU mesh, clearly
        # labeled cpu_relative (comparable to each other and to
        # bench_ab_cpu.jsonl, NOT to any TPU number).  Modes follow the CPU
        # A/B winners (low_rank=1 dominates the big/pop-10k shapes
        # off-chip); gens=2 keeps the wedged-round bench bounded.
        for name, cfg in (
            ("big_policy", {**BIG, "low_rank": 1, "gens": 2}),
            ("pop10k", {**POP10K, "low_rank": 1, "gens": 2}),
            ("locomotion", {**LOCO, "gens": 2}),
            ("loco10k", {**LOCO10K, "low_rank": 1, "gens": 2}),
        ):
            r = run_stage(cfg, timeout_s=1200, force_cpu=True)
            extras[name] = (
                {"rate": round(r["rate"], 1), "cpu_relative": True,
                 "dtype": r["dtype"],
                 "mode": "low_rank=1" if cfg.get("low_rank") else "standard",
                 "peak_rss_gb": r.get("peak_rss_gb")}
                if r else None
            )

    # the unit names what was measured; the fallback story lives in the
    # TYPED extras["device_probe"], not in prose stuffed into the unit
    unit = (f"env-steps/s/chip (Pendulum MLP64x64 pop4096 h200 "
            f"standard/{result['dtype']}, {platform})")
    print(
        json.dumps(
            {
                "metric": "env_steps_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": unit,
                "vs_baseline": round(rate / base_rate, 2),
                "platform": platform,
                "extras": extras,
            }
        )
    )
    _cleanup_bench_workdir()


_USAGE = """\
usage: bench.py [MODE]

no arguments        full headline benchmark (device probe decides the
                    platform; prints exactly one JSON line)
  --stage-ab        standard-vs-decomposed forward A/B
  --obs-ab          telemetry-overhead A/B
  --chaos [--selfcheck]   recovery-overhead A/B under injected faults
                    (clean vs kills vs a mixed straggler+kill plan on
                     the async scheduler)
  --async-ab [--selfcheck]  sync barrier loop vs event-driven async
                    scheduler under an injected straggler plan
                    (medians + learned noise band via obs regress;
                     gates the >=1.25x throughput win and the
                     zero-silent-drop accounting)
  --elastic-ab [--selfcheck]  synchronous 2-process SPMD multihost loop
                    vs the elastic host-granular fold scheduler under an
                    identical declared straggle_host plan (medians +
                    learned band via obs regress; gates the >=1.25x
                    throughput win, stale-host folds actually firing,
                    and dispatched == consumed + discarded + lost)
  --serve [--selfcheck]   dynamic-batching serving A/B
  --fleet [--selfcheck]   serving-fleet robustness gate: replica SIGKILL
                    under load (declared ESTORCH_CHAOS kill_replica)
                    loses zero client answers, breaker opens/closes,
                    warm respawn (compiles_at_load==0), capacity-sweep
                    max-RPS-at-SLO ladder
  --autoscale [--selfcheck]  autoscaler E2E gate: collector store +
                    capacity artifact + POST /scale close the loop —
                    load triples mid-run, gates p99-in-SLO, zero client
                    errors/shed (including through a declared
                    kill_replica during the scale-up), replica count
                    tracking load both directions, warm scale-ups,
                    drained retirement, bit-exact decision-log replay
  --coldstart [--selfcheck]  warm-bundle vs cold-start A/B + bf16
                    steady-state throughput (gates zero-fresh-builds
                    warm loads, warm-beats-cold TTFR beyond the learned
                    band, measured bf16 divergence; >=1.5x bf16
                    throughput on native-bf16 hardware)
  --shard-ab [--selfcheck]  replicated vs param-sharded same-seed A/B
                    (numerical match + per-device peak bytes + MFU row)
  --scenario-ab [--selfcheck]  scenario-suite A/B: one 10-variant
                    domain-randomized run vs 10 sequential
                    single-scenario runs (gates the >=3x wall-clock win,
                    compile-ledger programs O(1) in variant count, and
                    per-variant fitness coverage)
  --capture-baseline [--out PATH] [--repeats N] [--gens N] [--skip N] [--cpu]
                    produce a committed-baseline BENCH_r*.json carrying
                    the headline median PLUS embedded STEADY-STATE
                    per-generation phase_rows (--skip drops the leading
                    warm-up/compile generations per repeat, default 2),
                    so `obs regress --phases/--tail` gate against
                    committed history
  --regress [BASELINE] [--repeats N] [--cpu]   gate vs newest BENCH_r*.json
(--stage-one/--stage-chaos-one/--stage-async-one/--stage-elastic-one/
 --stage-elastic-worker/--stage-serve-one/--stage-fleet-one/
 --stage-autoscale-one/--stage-shard-ab-one/--stage-scenario-one are
 internal child modes)
"""


if __name__ == "__main__":
    if "-h" in sys.argv or "--help" in sys.argv:
        print(_USAGE, end="")
        sys.exit(0)
    if "--stage-one" in sys.argv:
        cfg = json.loads(sys.argv[sys.argv.index("--stage-one") + 1])
        out = measure_one(cfg, force_cpu="--cpu" in sys.argv)
        print(json.dumps(out))
    elif "--stage-ab" in sys.argv:
        _lock_or_warn()
        _sweep_stale_bench_dirs()
        stage_ab(force_cpu="--cpu" in sys.argv)
        _cleanup_bench_workdir()
    elif "--obs-ab" in sys.argv:
        _lock_or_warn()
        _sweep_stale_bench_dirs()
        stage_obs_ab(force_cpu="--cpu" in sys.argv)
        _cleanup_bench_workdir()
    elif "--stage-chaos-one" in sys.argv:
        cfg = json.loads(sys.argv[sys.argv.index("--stage-chaos-one") + 1])
        print(json.dumps(measure_chaos_one(cfg)))
    elif "--stage-async-one" in sys.argv:
        cfg = json.loads(sys.argv[sys.argv.index("--stage-async-one") + 1])
        print(json.dumps(measure_async_one(cfg)))
    elif "--async-ab" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (tiny host config,
        # no device): skip the evidence lock a full measurement takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_async_ab(selfcheck="--selfcheck" in sys.argv))
    elif "--stage-elastic-worker" in sys.argv:
        cfg = json.loads(
            sys.argv[sys.argv.index("--stage-elastic-worker") + 1])
        print(json.dumps(elastic_sync_worker(cfg)))
    elif "--stage-elastic-one" in sys.argv:
        cfg = json.loads(
            sys.argv[sys.argv.index("--stage-elastic-one") + 1])
        print(json.dumps(measure_elastic_one(cfg)))
    elif "--elastic-ab" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (tiny config, CPU
        # processes over loopback): skip the evidence lock a full
        # measurement takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_elastic_ab(selfcheck="--selfcheck" in sys.argv))
    elif "--stage-shard-ab-one" in sys.argv:
        cfg = json.loads(sys.argv[sys.argv.index("--stage-shard-ab-one") + 1])
        print(json.dumps(measure_shard_ab(cfg)))
    elif "--shard-ab" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (tiny config, forced
        # CPU mesh in the child): skip the evidence lock a full
        # measurement takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_shard_ab(selfcheck="--selfcheck" in sys.argv))
    elif "--stage-scenario-one" in sys.argv:
        cfg = json.loads(
            sys.argv[sys.argv.index("--stage-scenario-one") + 1])
        print(json.dumps(measure_scenario_one(cfg)))
    elif "--scenario-ab" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (tiny config, CPU
        # child): skip the evidence lock a full measurement takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_scenario_ab(selfcheck="--selfcheck" in sys.argv))
    elif "--stage-serve-one" in sys.argv:
        cfg = json.loads(sys.argv[sys.argv.index("--stage-serve-one") + 1])
        print(json.dumps(measure_serve_one(cfg)))
    elif "--stage-fleet-one" in sys.argv:
        cfg = json.loads(sys.argv[sys.argv.index("--stage-fleet-one") + 1])
        print(json.dumps(measure_fleet_one(cfg)))
    elif "--stage-autoscale-one" in sys.argv:
        cfg = json.loads(
            sys.argv[sys.argv.index("--stage-autoscale-one") + 1])
        print(json.dumps(measure_autoscale_one(cfg)))
    elif "--autoscale" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (tiny policy, CPU,
        # loopback only): skip the evidence lock a full measurement takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_autoscale(selfcheck="--selfcheck" in sys.argv))
    elif "--fleet" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (tiny policy, CPU,
        # loopback only): skip the evidence lock a full measurement takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_fleet(selfcheck="--selfcheck" in sys.argv))
    elif "--stage-coldstart-one" in sys.argv:
        cfg = json.loads(
            sys.argv[sys.argv.index("--stage-coldstart-one") + 1])
        print(json.dumps(measure_coldstart_one(cfg)))
    elif "--coldstart" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (smaller policy,
        # CPU, loopback only): skip the evidence lock a full measurement
        # takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_coldstart(selfcheck="--selfcheck" in sys.argv))
    elif "--capture-baseline" in sys.argv:
        _lock_or_warn()
        _sweep_stale_bench_dirs()
        kw = {}
        if "--out" in sys.argv:
            kw["out_path"] = sys.argv[sys.argv.index("--out") + 1]
        if "--repeats" in sys.argv:
            kw["repeats"] = int(sys.argv[sys.argv.index("--repeats") + 1])
        if "--gens" in sys.argv:
            kw["gens"] = int(sys.argv[sys.argv.index("--gens") + 1])
        if "--skip" in sys.argv:
            kw["skip"] = int(sys.argv[sys.argv.index("--skip") + 1])
        sys.exit(stage_capture_baseline(force_cpu="--cpu" in sys.argv,
                                        **kw))
    elif "--regress" in sys.argv:
        _lock_or_warn()
        idx = sys.argv.index("--regress")
        baseline = None
        if idx + 1 < len(sys.argv) and not sys.argv[idx + 1].startswith("-"):
            baseline = sys.argv[idx + 1]
        repeats = 3
        if "--repeats" in sys.argv:
            repeats = int(sys.argv[sys.argv.index("--repeats") + 1])
        _sweep_stale_bench_dirs()
        rc = stage_regress(baseline, repeats=repeats,
                           force_cpu="--cpu" in sys.argv)
        _cleanup_bench_workdir()
        sys.exit(rc)
    elif "--serve" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (tiny policy, CPU,
        # loopback only): skip the evidence lock a full measurement takes
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_serve(selfcheck="--selfcheck" in sys.argv))
    elif "--chaos" in sys.argv:
        # the selfcheck form runs inside run_lint.sh (single tiny host
        # config, no device): skip the evidence lock a full measurement
        # would take
        if "--selfcheck" not in sys.argv:
            _lock_or_warn()
        sys.exit(stage_chaos(selfcheck="--selfcheck" in sys.argv))
    elif len(sys.argv) > 1:
        # the default full bench takes NO arguments — a typo'd flag
        # silently launching a multi-minute measurement is the worst
        # possible "help" (this happened: `--help` ran the benchmark)
        print(f"bench.py: unrecognized arguments: "
              f"{' '.join(sys.argv[1:])}\n{_USAGE}",
              end="", file=sys.stderr)
        sys.exit(2)
    else:
        main()
