#!/bin/bash
# Round-5 session-3 test validation, serialized behind the CPU studies
# via the evidence flock (single-core discipline).
set -u
cd /root/repo
LOCK=/root/repo/.evidence.lock
LOG=/root/repo/validation_r05.log
stage() {
  echo "--- stage: $*" >> "$LOG"
  flock "$LOCK" "$@" >> "$LOG" 2>&1
  echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
}
stage /opt/venv/bin/python -m pytest tests/test_recurrent.py -x -q
stage /opt/venv/bin/python -m pytest tests/ -x -q
echo "validation done $(date -u +%FT%TZ)" >> "$LOG"
