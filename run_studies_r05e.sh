#!/bin/bash
# Round-5 session-3 follow-up studies: valley seeds 2-3, learned-carry A/B.
set -u
cd /root/repo
LOCK=/root/repo/.evidence.lock
LOG=/root/repo/studies_r05e.log
stage() {
  echo "--- stage: $*" >> "$LOG"
  flock "$LOCK" "$@" >> "$LOG" 2>&1
  echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
}
stage /opt/venv/bin/python examples/deceptive_valley_novelty.py 400 512 2 0.55 2
stage /opt/venv/bin/python examples/learned_carry_ab.py 120 256 2
echo "queue done $(date -u +%FT%TZ)" >> "$LOG"
