#!/bin/bash
set -u
cd /root/repo
LOCK=/root/repo/.evidence.lock
LOG=/root/repo/studies_r05f.log
stage() {
  echo "--- stage: $*" >> "$LOG"
  flock "$LOCK" "$@" >> "$LOG" 2>&1
  echo "exit $? $(date -u +%FT%TZ)" >> "$LOG"
}
stage /opt/venv/bin/python examples/humanoid_v3_pooled.py 75 512 0 --resume
stage /opt/venv/bin/python examples/humanoid_v3_pooled.py 90 512 0 --resume
echo "g-queue done $(date -u +%FT%TZ)" >> "$LOG"
