"""Expose pure-JAX envs through the gymnasium interface.

Closes the loop with the reference's ecosystem: a device-native env
(envs/cartpole.py etc.) can be driven by ANY gym-consuming code — the
reference's own Agent.rollout pattern, third-party eval scripts, video
recorders — without a second env implementation.  Also the easy way to
eyeball-check a policy trained on the device path inside host tooling.
"""

from __future__ import annotations

from typing import Any

import numpy as np

import jax
import jax.numpy as jnp


import gymnasium as gym


class GymFromJax(gym.Env):
    """gymnasium.Env over a JaxEnv — composes with standard gym wrappers."""

    metadata: dict = {"render_modes": []}
    render_mode = None

    def __init__(self, env: Any, seed: int = 0, max_steps: int | None = None):
        super().__init__()
        self._env = env
        self._key = jax.random.PRNGKey(seed)
        self._state = None
        self._steps = 0
        self._max_steps = (
            int(env.default_horizon) if max_steps is None else int(max_steps)
        )
        self._step_jit = jax.jit(env.step)
        self._reset_jit = jax.jit(env.reset)

        if env.discrete:
            self.action_space = gym.spaces.Discrete(env.action_dim)
        else:
            # honor the env's real bounds where declared (action_bound);
            # unbounded Box otherwise
            bound = float(getattr(env, "action_bound", np.inf))
            self.action_space = gym.spaces.Box(
                low=-bound, high=bound, shape=(env.action_dim,), dtype=np.float32
            )
        self.observation_space = gym.spaces.Box(
            low=-np.inf, high=np.inf, shape=(env.obs_dim,), dtype=np.float32
        )

    def reset(self, *, seed: int | None = None, options=None):
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self._key, sub = jax.random.split(self._key)
        self._state, obs = self._reset_jit(sub)
        self._steps = 0
        return np.asarray(obs, np.float32), {}

    def step(self, action):
        if self._state is None:
            raise RuntimeError("Cannot call env.step() before calling env.reset()")
        a = jnp.asarray(action)
        self._state, obs, reward, done = self._step_jit(self._state, a)
        self._steps += 1
        truncated = self._steps >= self._max_steps
        return (
            np.asarray(obs, np.float32),
            float(reward),
            bool(done),
            bool(truncated),
            {},
        )

    def close(self) -> None:
        pass
