"""Gymnasium vector-env pool with the NativeEnvPool interface.

Lets ANY gymnasium env — MuJoCo included — ride the pooled execution path
(parallel/pooled.py): N = population envs stepped through
``gym.vector`` while the device runs one batched policy forward per step.
This is how BASELINE configs 2-3 (HalfCheetah/Humanoid) get device-batched
inference without MJX: physics on host workers, the population's matmuls
on the accelerator.

Use via the ``gym:`` prefix:  ``PooledAgent(env_name="gym:HalfCheetah-v5")``.

Interface-compatible with NativeEnvPool: ``reset() -> obs``,
``step(actions) -> (obs, rew, done)``, float32 flat observation buffers
plus ``obs_shape`` for the policy-facing view.  ONE documented semantic
difference: gymnasium ≥1.0 vector envs auto-reset in NEXT_STEP mode — on
the done step you receive the TERMINAL observation (the C++ pool returns
the fresh reset state there).  The pooled engine masks with ``alive`` and
never reads past done, so both semantics evaluate identically; consumers
reading post-done observations must not assume the native-pool behavior.

Worker model: gym.vector forks ONE process per env (async) — fine up to a
couple of workers per core, a fork storm beyond.  ``asynchronous`` defaults
to sync on this basis; batched-many-envs-per-worker pools are what the C++
envpool is for (ROADMAP: ALE/EnvPool-style batching for gym envs).
"""

from __future__ import annotations

import numpy as np


class GymVecPool:
    """N gymnasium envs behind the pool interface (auto-reset semantics)."""

    def __init__(self, env_id: str, n_envs: int, n_threads: int = 0, seed: int = 0,
                 asynchronous: bool | None = None,
                 env_kwargs: dict | None = None):
        import gymnasium as gym

        self.env_name = f"gym:{env_id}"
        self.env_kwargs = dict(env_kwargs or {})
        self.n_envs = int(n_envs)
        if n_threads:
            # interface parity with NativeEnvPool only — gym.vector has no
            # thread knob (sync = in-process, async = one fork per env)
            import warnings

            warnings.warn(
                f"n_threads={n_threads} has no effect on gym: envs (it tunes "
                "the C++ native pool); gym.vector parallelism is controlled "
                "by `asynchronous` instead",
                stacklevel=3,
            )
        # async forks one process per env: only worth it with >1 core and a
        # sane worker-to-core ratio; n_envs==1 is always sync (pure overhead)
        if asynchronous is None:
            import os

            cores = (
                len(os.sched_getaffinity(0))
                if hasattr(os, "sched_getaffinity")
                else (os.cpu_count() or 1)
            )
            asynchronous = cores > 1 and 1 < self.n_envs <= 2 * cores
        ctor = gym.vector.AsyncVectorEnv if asynchronous else gym.vector.SyncVectorEnv
        self._vec = ctor(
            [self._make_one(env_id, self.env_kwargs)
             for _ in range(self.n_envs)]
        )
        self._seed = int(seed)
        self._seeded = False

        obs_space = self._vec.single_observation_space
        act_space = self._vec.single_action_space
        self.obs_shape = tuple(obs_space.shape)
        self.obs_dim = int(np.prod(self.obs_shape))
        if hasattr(act_space, "n"):  # Discrete
            self.discrete = True
            self.n_actions = int(act_space.n)
            self.act_dim = 1
        else:
            self.discrete = False
            self.n_actions = 0
            self.act_dim = int(np.prod(act_space.shape))
        self._act_shape = tuple(getattr(act_space, "shape", ()) or ())

    @staticmethod
    def _make_one(env_id: str, env_kwargs: dict):
        def thunk():
            import gymnasium as gym

            return gym.make(env_id, **env_kwargs)

        return thunk

    @property
    def is_native(self) -> bool:
        return False

    def reset(self) -> np.ndarray:
        # seed only ONCE: later resets continue the envs' RNG streams, so
        # every generation draws fresh initial states (native-pool parity) —
        # reseeding each call would evaluate identical starts forever
        if not self._seeded:
            obs, _ = self._vec.reset(seed=self._seed)
            self._seeded = True
        else:
            obs, _ = self._vec.reset()
        return np.asarray(obs, np.float32).reshape(self.n_envs, self.obs_dim)

    def step(self, actions: np.ndarray):
        a = np.asarray(actions)
        if self.discrete:
            a = a.reshape(self.n_envs).astype(np.int64)
        else:
            a = a.reshape((self.n_envs,) + self._act_shape).astype(np.float32)
        obs, rew, term, trunc, _ = self._vec.step(a)
        done = np.asarray(term) | np.asarray(trunc)
        return (
            np.asarray(obs, np.float32).reshape(self.n_envs, self.obs_dim),
            np.asarray(rew, np.float32),
            done,
        )

    def close(self) -> None:
        try:
            self._vec.close()
        except Exception:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def make_pool(env_name: str, n_envs: int, n_threads: int = 0, seed: int = 0,
              env_kwargs: dict | None = None):
    """Pool factory: ``gym:<EnvId>`` → GymVecPool, else the C++ NativeEnvPool.

    ``env_kwargs`` forward to ``gym.make`` (e.g. HalfCheetah's
    ``exclude_current_positions_from_observation=False``, which puts the
    x-position in the observation — the canonical locomotion BC); the
    in-tree native envs take no kwargs."""
    if env_name.startswith("gym:"):
        return GymVecPool(env_name[4:], n_envs, n_threads=n_threads, seed=seed,
                          env_kwargs=env_kwargs)
    if env_kwargs:
        raise ValueError(
            f"env_kwargs only apply to gym: envs; {env_name!r} is an "
            "in-tree native env with a fixed construction"
        )
    from .native_pool import NativeEnvPool

    return NativeEnvPool(env_name, n_envs, n_threads=n_threads, seed=seed)


def pool_env_spec(env_name: str, env_kwargs: dict | None = None) -> dict:
    """env_spec covering both pool families (probe-free for native envs).

    Rejects env_kwargs for native envs HERE, not just in make_pool: the
    spec probe runs first in ES._init_pooled, and a silently-ignored
    kwarg would otherwise surface only after policy shapes were built."""
    if env_name.startswith("gym:"):
        import gymnasium as gym

        env = gym.make(env_name[4:], **(env_kwargs or {}))
        obs_shape = tuple(env.observation_space.shape)
        act = env.action_space
        spec = {
            "obs_dim": int(np.prod(obs_shape)),
            "obs_shape": obs_shape,
            "discrete": hasattr(act, "n"),
            "n_actions": int(getattr(act, "n", 0)),
            "act_dim": 1 if hasattr(act, "n") else int(np.prod(act.shape)),
        }
        env.close()
        return spec
    if env_kwargs:
        raise ValueError(
            f"env_kwargs only apply to gym: envs; {env_name!r} is an "
            "in-tree native env with a fixed construction"
        )
    from .native_pool import env_spec

    return env_spec(env_name)
