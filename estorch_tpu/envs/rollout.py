"""Compiled episode rollouts: fixed-horizon ``lax.scan`` with done masking.

Replaces the reference's host-side ``while not done: policy(obs); env.step``
loop (SURVEY.md §3.3) with a single traced scan so XLA sees the whole
episode — and, after ``vmap``, the whole population — as one program:
policy matmuls batch onto the MXU, env math fuses into the surrounding ops,
and nothing touches the host until the generation's fitness vector exists.

Done masking: after an episode terminates, further steps still execute
(static shapes — the TPU way) but rewards are masked and state is frozen,
so results are exactly equal to early termination.  ``steps`` counts the
genuinely-alive steps for honest env-steps/sec accounting.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class RolloutResult(NamedTuple):
    total_reward: jax.Array  # () float32 — the episode return (fitness)
    bc: jax.Array  # (bc_dim,) float32 — behavior characterization
    steps: jax.Array  # () int32 — alive steps actually taken


def select_action(policy_out: jax.Array, discrete: bool) -> jax.Array:
    """Reference action rule: argmax for discrete policies (SURVEY.md §3.3);
    continuous policies emit actions directly (models apply their own squash)."""
    if discrete:
        return jnp.argmax(policy_out, axis=-1)
    return policy_out


def make_rollout(
    env: Any,
    policy_apply: Callable[[Any, jax.Array], jax.Array],
    horizon: int,
) -> Callable[[Any, jax.Array], RolloutResult]:
    """Build ``rollout(params, key) -> RolloutResult`` for one episode.

    ``policy_apply(params, obs) -> action logits/values``.  The returned
    function is pure and jit/vmap-safe; vmap it over ``(params, key)`` to
    evaluate a whole population slice in one program.
    """
    discrete = bool(env.discrete)

    def rollout(params: Any, key: jax.Array) -> RolloutResult:
        state0, obs0 = env.reset(key)

        def step_fn(carry, _):
            state, obs, done, total, steps = carry
            out = policy_apply(params, obs)
            action = select_action(out, discrete)
            nstate, nobs, reward, ndone = env.step(state, action)
            alive = jnp.logical_not(done)
            alive_f = alive.astype(jnp.float32)
            total = total + reward * alive_f
            steps = steps + alive.astype(jnp.int32)
            # freeze state/obs after termination so BC reads the final frame
            keep = lambda new, old: jnp.where(alive, new, old)
            state_next = jax.tree_util.tree_map(keep, nstate, state)
            obs_next = keep(nobs, obs)
            done_next = done | ndone
            return (state_next, obs_next, done_next, total, steps), None

        init = (
            state0,
            obs0,
            jnp.bool_(False),
            jnp.float32(0.0),
            jnp.int32(0),
        )
        (state, obs, done, total, steps), _ = jax.lax.scan(
            step_fn, init, None, length=horizon
        )
        bc = env.behavior(state, obs).astype(jnp.float32)
        return RolloutResult(total_reward=total, bc=bc, steps=steps)

    return rollout


def make_population_rollout(
    env: Any,
    policy_apply: Callable[[Any, jax.Array], jax.Array],
    horizon: int,
) -> Callable[[Any, jax.Array], RolloutResult]:
    """vmap of ``make_rollout`` over stacked params and per-member keys.

    ``params`` leaves have a leading population axis; ``keys`` is (n,).
    Returns batched RolloutResult arrays — (n,), (n, bc_dim), (n,).
    """
    single = make_rollout(env, policy_apply, horizon)
    return jax.vmap(single, in_axes=(0, 0))
