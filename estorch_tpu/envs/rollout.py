"""Compiled episode rollouts: fixed-horizon ``lax.scan`` with done masking.

Replaces the reference's host-side ``while not done: policy(obs); env.step``
loop (SURVEY.md §3.3) with a single traced scan so XLA sees the whole
episode — and, after ``vmap``, the whole population — as one program:
policy matmuls batch onto the MXU, env math fuses into the surrounding ops,
and nothing touches the host until the generation's fitness vector exists.

Done masking: after an episode terminates, further steps still execute
(static shapes — the TPU way) but rewards are masked and state is frozen,
so results are exactly equal to early termination.  ``steps`` counts the
genuinely-alive steps for honest env-steps/sec accounting.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


def carry_init_takes_params(carry_init: Callable[..., Any]) -> bool:
    """Whether ``carry_init`` is the params-aware form (``carry_init(params)
    -> carry``, the learned episode-start carry of models/policies.py) or
    the historical zero-arg form (``carry_init() -> carry``).

    Detected ONCE at build time and shared by every consumer of the compat
    contract (make_rollout, the engine's bf16 carry wrapper, ES.predict) so
    the two forms can never diverge between code paths.  When
    ``inspect.signature`` cannot introspect the callable, the form is
    PROBED — the zero-arg call is attempted under ``except TypeError`` —
    instead of guessed, so a non-introspectable zero-arg callable works
    rather than crashing at trace time with an unexpected argument.
    """
    try:
        return bool(inspect.signature(carry_init).parameters)
    except (TypeError, ValueError):
        pass
    try:
        carry_init()
        return False
    except TypeError:
        return True


class RolloutResult(NamedTuple):
    total_reward: jax.Array  # () float32 — the episode return (fitness)
    bc: jax.Array  # (bc_dim,) float32 — behavior characterization
    steps: jax.Array  # () int32 — alive steps actually taken


def select_action(policy_out: jax.Array, discrete: bool) -> jax.Array:
    """Reference action rule: argmax for discrete policies (SURVEY.md §3.3);
    continuous policies emit actions directly (models apply their own squash)."""
    if discrete:
        return jnp.argmax(policy_out, axis=-1)
    return policy_out


def make_rollout(
    env: Any,
    policy_apply: Callable[..., jax.Array],
    horizon: int,
    carry_init: Callable[..., Any] | None = None,
    with_obs_moments: bool = False,
    with_env_metrics: bool = False,
) -> Callable[[Any, jax.Array], Any]:
    """Build ``rollout(params, key) -> RolloutResult`` for one episode.

    ``policy_apply(params, obs) -> action logits/values``.  The returned
    function is pure and jit/vmap-safe; vmap it over ``(params, key)`` to
    evaluate a whole population slice in one program.

    Recurrent policies (``carry_init`` given): ``policy_apply(params, obs,
    h) -> (out, h')`` and the hidden carry is threaded through the episode
    scan — reset to ``carry_init(params)`` at episode start (so a policy
    with a LEARNED initial carry reads it from the member's perturbed
    params — models/policies.py ``learned_carry``), frozen (like env
    state) after termination.  The reference has no recurrent machinery
    (its ``agent.rollout`` owns the loop, SURVEY.md §3.3, so torch users
    thread hidden state themselves); here the loop is a compiled scan, so
    the framework must thread it.

    ``with_obs_moments=True``: the SAME scan additionally accumulates
    alive-masked raw-observation moments over the observations the policy
    acted on (including the reset frame) and the rollout returns
    ``(RolloutResult, (count, obs_sum, obs_sumsq))`` — the obs_norm
    probe's data source (parallel/engine.py), sharing one step body with
    the plain rollout so the two can never desynchronize.

    ``with_env_metrics=True`` (requires ``env.step_metrics(state) ->
    (k,) float32``): the scan additionally sums the env's per-step metric
    vector over the states reached by alive steps, and the rollout
    returns ``(RolloutResult, metric_sums (k,))``.  The env converts the
    sums into episode quantities via ``env.episode_metrics`` (e.g. the
    locomotion family's upright fraction) — measured gait claims instead
    of reward-scale ones.
    """
    discrete = bool(env.discrete)
    stateful = carry_init is not None
    if stateful:
        # carry_init may be the historical zero-arg form (custom user
        # callables) or the params-aware form (learned episode-start
        # carry, models/policies.py) — detect once at build time
        _ci_takes_params = carry_init_takes_params(carry_init)
    if with_env_metrics and with_obs_moments:
        raise ValueError("one aux channel per rollout: obs moments are the "
                         "training probe, env metrics the evaluation one")
    if with_env_metrics:
        n_metrics = len(env.metric_names)

    def rollout(params: Any, key: jax.Array):
        state0, obs0 = env.reset(key)
        # episode-start carry may be learned: carry_init reads it from the
        # member's (perturbed) params when the policy asks for that
        if stateful:
            h0 = carry_init(params) if _ci_takes_params else carry_init()
        else:
            h0 = None
        zeros = jnp.zeros_like(obs0, dtype=jnp.float32)

        def step_fn(carry, _):
            state, obs, done, total, steps, h, moments = carry
            alive = jnp.logical_not(done)
            alive_f = alive.astype(jnp.float32)
            if with_obs_moments:
                cnt, osum, osumsq = moments
                of = obs.astype(jnp.float32)
                moments = (
                    cnt + alive_f,
                    osum + alive_f * of,
                    osumsq + alive_f * of * of,
                )
            if stateful:
                out, h_new = policy_apply(params, obs, h)
            else:
                out, h_new = policy_apply(params, obs), h
            action = select_action(out, discrete)
            nstate, nobs, reward, ndone = env.step(state, action)
            if with_env_metrics:
                # metrics of the state this alive step REACHED; frozen
                # (post-termination) pseudo-steps contribute nothing
                moments = moments + alive_f * env.step_metrics(nstate)
            total = total + reward * alive_f
            steps = steps + alive.astype(jnp.int32)
            # freeze state/obs after termination so BC reads the final frame
            keep = lambda new, old: jnp.where(alive, new, old)
            state_next = jax.tree_util.tree_map(keep, nstate, state)
            obs_next = keep(nobs, obs)
            h_next = jax.tree_util.tree_map(keep, h_new, h)
            done_next = done | ndone
            return (
                state_next, obs_next, done_next, total, steps, h_next, moments
            ), None

        if with_obs_moments:
            aux0 = (jnp.float32(0.0), zeros, zeros)
        elif with_env_metrics:
            aux0 = jnp.zeros((n_metrics,), jnp.float32)
        else:
            aux0 = None
        init = (
            state0,
            obs0,
            jnp.bool_(False),
            jnp.float32(0.0),
            jnp.int32(0),
            h0,
            aux0,
        )
        (state, obs, done, total, steps, _, moments), _ = jax.lax.scan(
            step_fn, init, None, length=horizon
        )
        bc = env.behavior(state, obs).astype(jnp.float32)
        res = RolloutResult(total_reward=total, bc=bc, steps=steps)
        return (
            (res, moments) if (with_obs_moments or with_env_metrics) else res
        )

    return rollout


def make_obs_probe(
    env: Any,
    policy_apply: Callable[..., jax.Array],
    horizon: int,
    carry_init: Callable[..., Any] | None = None,
) -> Callable[[Any, jax.Array], tuple[jax.Array, jax.Array, jax.Array]]:
    """One episode's raw-observation moments: ``probe(params, key) ->
    (count, obs_sum, obs_sumsq)``.

    Thin wrapper over :func:`make_rollout` with ``with_obs_moments=True``
    — the probe IS a center-policy episode (same step body, same
    termination/freeze semantics); only the moments are kept.  When the
    apply is the engine's normalization-packed form, normalization
    happens inside it, so the moments stay in raw observation space (what
    the running stats normalize).  Powers ``EngineConfig.obs_norm``.
    """
    rollout = make_rollout(env, policy_apply, horizon,
                           carry_init=carry_init, with_obs_moments=True)

    def probe(params: Any, key: jax.Array):
        _, moments = rollout(params, key)
        return moments

    return probe


def make_population_rollout(
    env: Any,
    policy_apply: Callable[..., jax.Array],
    horizon: int,
    carry_init: Callable[..., Any] | None = None,
) -> Callable[[Any, jax.Array], RolloutResult]:
    """vmap of ``make_rollout`` over stacked params and per-member keys.

    ``params`` leaves have a leading population axis; ``keys`` is (n,).
    Returns batched RolloutResult arrays — (n,), (n, bc_dim), (n,).
    ``carry_init`` as in :func:`make_rollout` (recurrent policies).
    """
    single = make_rollout(env, policy_apply, horizon, carry_init=carry_init)
    return jax.vmap(single, in_axes=(0, 0))


def make_batched_rollout(
    env: Any,
    horizon: int,
) -> Callable[[Callable[[jax.Array], jax.Array], jax.Array], RolloutResult]:
    """Population-batched episode scan: ONE policy call per step for ALL
    members, instead of vmapping a per-member rollout.

    ``rollout(batched_apply, keys)``: ``batched_apply(obs_batch (n, obs_dim))
    -> (n, act)`` closes over whatever per-member parameterization the
    caller uses — this is the entry point for the Pallas streamed forward
    (ops/pallas_noise.py::mlp_streamed_apply), whose population kernel
    cannot live under a member vmap.  Env dynamics are vmapped; masking
    semantics are identical to :func:`make_rollout`.
    """
    discrete = bool(env.discrete)
    v_reset = jax.vmap(env.reset)
    v_step = jax.vmap(env.step)
    v_behavior = jax.vmap(env.behavior)

    def rollout(batched_apply, keys: jax.Array) -> RolloutResult:
        states0, obs0 = v_reset(keys)
        n = obs0.shape[0]

        def step_fn(carry, _):
            states, obs, done, total, steps = carry
            out = batched_apply(obs)
            action = select_action(out, discrete)
            nstate, nobs, reward, ndone = v_step(states, action)
            alive = jnp.logical_not(done)
            total = total + reward * alive.astype(jnp.float32)
            steps = steps + alive.astype(jnp.int32)

            def keep(new, old):
                mask = alive.reshape((-1,) + (1,) * (new.ndim - 1))
                return jnp.where(mask, new, old)

            states_next = jax.tree_util.tree_map(keep, nstate, states)
            obs_next = keep(nobs, obs)
            done_next = done | ndone
            return (states_next, obs_next, done_next, total, steps), None

        init = (
            states0,
            obs0,
            jnp.zeros((n,), jnp.bool_),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.int32),
        )
        (states, obs, done, total, steps), _ = jax.lax.scan(
            step_fn, init, None, length=horizon
        )
        bc = v_behavior(states, obs).astype(jnp.float32)
        return RolloutResult(total_reward=total, bc=bc, steps=steps)

    return rollout
