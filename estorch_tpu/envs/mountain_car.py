"""Pure-JAX MountainCarContinuous-v0, faithful to the Gym dynamics.

A sparse-reward continuous env — the classic novelty-search showcase (a
reward-only ES stalls; NS-ES explores by final-position behavior).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MountainCarContinuous:
    min_position: float = -1.2
    max_position: float = 0.6
    max_speed: float = 0.07
    goal_position: float = 0.45
    goal_velocity: float = 0.0
    power: float = 0.0015

    obs_dim: int = 2
    action_dim: int = 1
    discrete: bool = False
    default_horizon: int = 999
    bc_dim: int = 1
    action_bound: float = 1.0  # force clipped to ±1

    # physics constants liftable into a traced ScenarioParams operand
    # (estorch_tpu/scenarios, docs/scenarios.md)
    SCENARIO_FIELDS = ("power", "max_speed")

    def scenario_defaults(self) -> dict:
        return {n: float(getattr(self, n)) for n in self.SCENARIO_FIELDS}

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        pos = jax.random.uniform(key, (), minval=-0.6, maxval=-0.4)
        state = jnp.stack([pos, jnp.float32(0.0)])
        return state, state

    def step(self, state, action):
        return self.step_p(None, state, action)

    def step_p(self, params, state, action):
        """ONE dynamics definition for both forms (see Pendulum.step_p)."""
        from .base import scenario_value as sv

        power = sv(params, "power", self.power)
        max_speed = sv(params, "max_speed", self.max_speed)
        position, velocity = state[0], state[1]
        force = jnp.clip(action.reshape(()), -1.0, 1.0)

        velocity = velocity + force * power - 0.0025 * jnp.cos(3 * position)
        velocity = jnp.clip(velocity, -max_speed, max_speed)
        position = position + velocity
        position = jnp.clip(position, self.min_position, self.max_position)
        velocity = jnp.where(
            (position == self.min_position) & (velocity < 0), 0.0, velocity
        )

        done = (position >= self.goal_position) & (velocity >= self.goal_velocity)
        reward = jnp.where(done, 100.0, 0.0) - 0.1 * force**2

        new_state = jnp.stack([position, velocity])
        return new_state, new_state, reward, done

    def behavior(self, state, obs) -> jax.Array:
        """BC = final position (the NS-ES paper's BC for deceptive mazes)."""
        return state[:1]
