"""Pure-JAX Acrobot-v1 (classic control), faithful to the Gym dynamics.

The 'book' variant of the underactuated double pendulum (Sutton & Barto)
with RK4 integration, matching gymnasium's Acrobot-v1 step-for-step
(parity-tested in tests/test_envs.py).  Discrete torques {-1, 0, +1}.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


def _wrap(x, lo, hi):
    return lo + (x - lo) % (hi - lo)


@dataclasses.dataclass(frozen=True)
class Acrobot:
    dt: float = 0.2
    link_length_1: float = 1.0
    link_mass_1: float = 1.0
    link_mass_2: float = 1.0
    link_com_1: float = 0.5
    link_com_2: float = 0.5
    link_moi: float = 1.0
    max_vel_1: float = 4 * jnp.pi
    max_vel_2: float = 9 * jnp.pi
    g: float = 9.8

    obs_dim: int = 6
    action_dim: int = 3
    discrete: bool = True
    default_horizon: int = 500
    bc_dim: int = 2

    # physics constants liftable into a traced ScenarioParams operand
    # (estorch_tpu/scenarios, docs/scenarios.md)
    SCENARIO_FIELDS = ("link_mass_1", "link_mass_2", "link_length_1",
                       "link_com_1", "link_com_2", "g")

    def scenario_defaults(self) -> dict:
        return {n: float(getattr(self, n)) for n in self.SCENARIO_FIELDS}

    def _obs(self, s):
        t1, t2, dt1, dt2 = s[0], s[1], s[2], s[3]
        return jnp.stack([jnp.cos(t1), jnp.sin(t1), jnp.cos(t2), jnp.sin(t2), dt1, dt2])

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        s = jax.random.uniform(key, (4,), minval=-0.1, maxval=0.1)
        return s, self._obs(s)

    def _dsdt(self, s, torque, params=None):
        from .base import scenario_value as sv

        m1 = sv(params, "link_mass_1", self.link_mass_1)
        m2 = sv(params, "link_mass_2", self.link_mass_2)
        l1 = sv(params, "link_length_1", self.link_length_1)
        lc1 = sv(params, "link_com_1", self.link_com_1)
        lc2 = sv(params, "link_com_2", self.link_com_2)
        I1 = I2 = self.link_moi
        g = sv(params, "g", self.g)
        t1, t2, dt1, dt2 = s[0], s[1], s[2], s[3]

        d1 = (
            m1 * lc1**2
            + m2 * (l1**2 + lc2**2 + 2 * l1 * lc2 * jnp.cos(t2))
            + I1
            + I2
        )
        d2 = m2 * (lc2**2 + l1 * lc2 * jnp.cos(t2)) + I2
        phi2 = m2 * lc2 * g * jnp.cos(t1 + t2 - jnp.pi / 2.0)
        phi1 = (
            -m2 * l1 * lc2 * dt2**2 * jnp.sin(t2)
            - 2 * m2 * l1 * lc2 * dt2 * dt1 * jnp.sin(t2)
            + (m1 * lc1 + m2 * l1) * g * jnp.cos(t1 - jnp.pi / 2.0)
            + phi2
        )
        # the 'book' equations (gymnasium default)
        ddt2 = (
            torque + d2 / d1 * phi1 - m2 * l1 * lc2 * dt1**2 * jnp.sin(t2) - phi2
        ) / (m2 * lc2**2 + I2 - d2**2 / d1)
        ddt1 = -(d2 * ddt2 + phi1) / d1
        return jnp.stack([dt1, dt2, ddt1, ddt2])

    def step(self, state, action):
        return self.step_p(None, state, action)

    def step_p(self, params, state, action):
        """ONE dynamics definition for both forms (see Pendulum.step_p)."""
        torque = (action - 1).astype(jnp.float32)  # {0,1,2} -> {-1,0,+1}

        # RK4 over one dt with constant torque (gymnasium's rk4)
        s = state
        h = self.dt
        k1 = self._dsdt(s, torque, params)
        k2 = self._dsdt(s + h / 2.0 * k1, torque, params)
        k3 = self._dsdt(s + h / 2.0 * k2, torque, params)
        k4 = self._dsdt(s + h * k3, torque, params)
        ns = s + h / 6.0 * (k1 + 2 * k2 + 2 * k3 + k4)

        t1 = _wrap(ns[0], -jnp.pi, jnp.pi)
        t2 = _wrap(ns[1], -jnp.pi, jnp.pi)
        dt1 = jnp.clip(ns[2], -self.max_vel_1, self.max_vel_1)
        dt2 = jnp.clip(ns[3], -self.max_vel_2, self.max_vel_2)
        new_state = jnp.stack([t1, t2, dt1, dt2])

        done = -jnp.cos(t1) - jnp.cos(t2 + t1) > 1.0
        reward = jnp.where(done, 0.0, -1.0)
        return new_state, self._obs(new_state), reward, done

    def behavior(self, state, obs) -> jax.Array:
        """BC = final tip position (the swing-up frontier), in the same
        downward-vertical angle convention as the terminal height check."""
        t1, t2 = state[0], state[1]
        x = jnp.sin(t1) + jnp.sin(t1 + t2)
        y = -jnp.cos(t1) - jnp.cos(t1 + t2)
        return jnp.stack([x, y])
