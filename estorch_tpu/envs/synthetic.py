"""Synthetic benchmark env: configurable obs dim, negligible step cost.

Exists for throughput benchmarking at Humanoid-like observation sizes
(obs 376 / act 17) without MuJoCo physics: the reference benchmarks ES on
MuJoCo tasks where virtually all device FLOPs are the policy forward (the
physics run on CPU workers, SURVEY.md §3.3); this env reproduces that FLOP
profile on-device — elementwise-only dynamics (O(obs_dim) per step, ~1e-3
of the policy matmul cost at Humanoid size) so a measured env-steps/sec is
an honest policy-throughput number, not inflated by a trivial policy or
deflated by synthetic physics.

Never terminates (like Pendulum), so every scanned step is a live step.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SyntheticEnv:
    """Leaky shift-register dynamics driven by the action.

    state' = a·state + b·roll(state, 1) + scatter(action);  obs = state;
    reward = -mean(state²).  Chaotic enough that the observation stream is
    not constant (policy inputs vary step to step, defeating value reuse),
    cheap enough that the policy forward dominates.
    """

    obs_dim: int = 376
    action_dim: int = 17
    discrete: bool = False
    default_horizon: int = 200
    bc_dim: int = 2
    action_bound: float = 1.0
    # |decay + mix·e^{iθ}| ≤ 0.99 < 1: the linear part is contractive, so
    # bounded actions give bounded state (steady-state ≲ 0.1/(1-0.99) = 10)
    decay: float = 0.95
    mix: float = 0.04

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        state = 0.1 * jax.random.normal(key, (self.obs_dim,))
        return state, state

    def step(self, state, action):
        act = jnp.clip(jnp.atleast_1d(action), -1.0, 1.0)
        drive = jnp.zeros((self.obs_dim,)).at[: self.action_dim].set(act)
        new_state = (
            self.decay * state + self.mix * jnp.roll(state, 1) + 0.1 * drive
        )
        reward = -jnp.mean(new_state**2)
        return new_state, new_state, reward, jnp.bool_(False)

    def behavior(self, state, obs) -> jax.Array:
        return state[: self.bc_dim]


@dataclasses.dataclass(frozen=True)
class RecallEnv:
    """Memory probe (POMDP): a ±1 signal is observable ONLY before the
    first step; reward each step is ``clip(action)·signal``.

    A memoryless policy sees the signal exactly once (the first policy
    call) and zeros afterwards, so over the symmetric ±1 episode
    distribution its expected return caps at ~1 (the first step); a policy
    that latches the signal into recurrent state earns ~horizon.  The gap
    is the cleanest possible test that hidden state actually flows through
    the compiled rollout scan (envs/rollout.py ``carry_init`` path).

    Never terminates; state = [signal, t].
    """

    obs_dim: int = 1
    action_dim: int = 1
    discrete: bool = False
    default_horizon: int = 32
    bc_dim: int = 1

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        sign = jnp.where(jax.random.bernoulli(key), 1.0, -1.0)
        state = jnp.stack([sign, jnp.float32(0.0)])
        return state, state[:1]

    def step(self, state, action):
        sign, t = state[0], state[1]
        act = jnp.clip(jnp.atleast_1d(action), -1.0, 1.0)[0]
        reward = act * sign
        nstate = jnp.stack([sign, t + 1.0])
        # the signal is gone from every post-reset observation
        obs = jnp.zeros((1,))
        return nstate, obs, reward, jnp.bool_(False)

    def behavior(self, state, obs) -> jax.Array:
        return state[:1]
