"""Agent wrappers — the bridge from the reference's rollout contract.

The reference's ``Agent`` is duck-typed host code: ``rollout(policy) ->
reward`` or ``(reward, bc)`` (SURVEY.md §1, Appendix A).  estorch_tpu keeps
that host contract for arbitrary Gym envs (envs/host_pool.py), and adds the
device-native equivalent: a ``JaxAgent`` simply names a ``JaxEnv`` and a
horizon, and the engine compiles the rollouts itself — the agent never steps
anything in Python.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class PooledAgent:
    """Pooled-backend agent: names a C++ envpool env (envs/native_pool.py).

    The population's envs step in native threads while the device runs one
    batched policy forward per env step (parallel/pooled.py) — the execution
    model for host-only envs (reference's Gym/Atari configs).
    """

    env_name: str
    horizon: int = 500
    n_threads: int = 0
    double_buffer: bool = False  # overlap device forwards with env stepping
    # (two half-population pools; see parallel/pooled.py)
    env_kwargs: dict | None = None  # forwarded to gym.make for gym: envs
    # (e.g. exclude_current_positions_from_observation=False)
    bc_indices: tuple | None = None  # behavior characterization = these
    # final-observation dims instead of the full final obs (e.g. (0,) for
    # the final x-position — the Conti et al. locomotion BC the novelty
    # family searches over)
    # ALE-standard preprocessing (envs/atari_wrappers.py); defaults are
    # pass-through so non-Atari pooled configs are untouched
    frame_stack: int = 1
    action_repeat: int = 1
    sticky_prob: float = 0.0
    max_pool2: bool = False

    @property
    def prep(self) -> dict | None:
        """Wrapper kwargs, or None when everything is at pass-through."""
        if (self.frame_stack, self.action_repeat, self.sticky_prob,
                self.max_pool2) == (1, 1, 0.0, False):
            return None
        return {
            "frame_stack": self.frame_stack,
            "action_repeat": self.action_repeat,
            "sticky_prob": self.sticky_prob,
            "max_pool2": self.max_pool2,
        }


@dataclasses.dataclass
class JaxAgent:
    """Device-native agent: wraps a pure-JAX env for the compiled path.

    Parameters mirror what the reference's Agent constructor would close
    over (the env); ``horizon`` bounds the fixed-length rollout scan.
    """

    env: Any
    horizon: int | None = None

    @property
    def rollout_horizon(self) -> int:
        return int(self.horizon or self.env.default_horizon)


def collect_reference_batch(env: Any, key: jax.Array, n_steps: int = 128) -> jax.Array:
    """Observations from a random-action rollout, for VirtualBatchNorm.

    The OpenAI-ES trick: VBN statistics come from a fixed batch of states
    gathered with random actions at startup; the reference leaves this to
    user code, we bundle it.  Runs as one compiled scan on device.
    """

    def step_fn(carry, k):
        state, obs = carry
        if env.discrete:
            action = jax.random.randint(k, (), 0, env.action_dim)
        else:
            action = jax.random.uniform(k, (env.action_dim,), minval=-1.0, maxval=1.0)
        nstate, nobs, _, done = env.step(state, action)
        # restart from the same initial state on termination to keep shapes static
        keep = lambda new, old: jnp.where(done, old, new)
        return (jax.tree_util.tree_map(keep, nstate, state), keep(nobs, obs)), obs

    key, rkey = jax.random.split(key)
    state0, obs0 = env.reset(rkey)
    keys = jax.random.split(key, n_steps)
    _, obs_batch = jax.lax.scan(step_fn, (state0, obs0), keys)
    return obs_batch
