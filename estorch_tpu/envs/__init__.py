from .acrobot import Acrobot
from .base import EnvSpec, JaxEnv
from .cartpole import CartPole
from .mountain_car import MountainCarContinuous
from .mountain_car_discrete import MountainCar
from .pendulum import Pendulum
from .rollout import RolloutResult, make_population_rollout, make_rollout, select_action

__all__ = [
    "Acrobot",
    "EnvSpec",
    "JaxEnv",
    "CartPole",
    "MountainCar",
    "MountainCarContinuous",
    "Pendulum",
    "RolloutResult",
    "make_population_rollout",
    "make_rollout",
    "select_action",
]
