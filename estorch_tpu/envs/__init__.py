from .acrobot import Acrobot
from .base import EnvSpec, JaxEnv
from .cartpole import CartPole
from .mountain_car import MountainCarContinuous
from .mountain_car_discrete import MountainCar
from .locomotion import (Cheetah2D, DeceptiveValley, Hopper2D,
                         Humanoid2D, PositionOnly,
                         Swimmer2D, Walker2D)
from .pendulum import Pendulum
from .rollout import RolloutResult, make_population_rollout, make_rollout, select_action
from .synthetic import RecallEnv, SyntheticEnv

__all__ = [
    "Acrobot",
    "EnvSpec",
    "JaxEnv",
    "CartPole",
    "Cheetah2D",
    "Hopper2D",
    "Humanoid2D",
    "DeceptiveValley",
    "PositionOnly",
    "Swimmer2D",
    "Walker2D",
    "MountainCar",
    "MountainCarContinuous",
    "Pendulum",
    "RecallEnv",
    "SyntheticEnv",
    "RolloutResult",
    "make_population_rollout",
    "make_rollout",
    "select_action",
]
