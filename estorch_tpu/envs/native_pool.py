"""ctypes bindings for the C++ envpool (+ NumPy fallback).

The native host env-stepper (estorch_tpu/native/envpool.cpp) replaces the
reference's per-process Python rollout workers for host-env configs: N envs
step in parallel C++ threads while the TPU runs the batched policy forward.
If the shared library is missing, it is built on demand with ``make``; if no
compiler is available, a NumPy vectorized fallback with identical semantics
(auto-reset on done, same dynamics) keeps everything functional.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libenvpool.so"))

ENV_IDS = {"cartpole": 0, "pendulum": 1, "pong84": 2}
# policy-facing observation shape; differs from the flat buffer for pixels
_OBS_SHAPES = {0: (4,), 1: (3,), 2: (84, 84, 1)}
_OBS_DIMS = {k: int(np.prod(v)) for k, v in _OBS_SHAPES.items()}
_ACT_DIMS = {0: 1, 1: 1, 2: 1}
_DISCRETE = {0: True, 1: False, 2: True}
_N_ACTIONS = {0: 2, 1: 0, 2: 3}  # discrete action count (0 = continuous)
_NUMPY_FALLBACK_IDS = (0, 1)  # envs _NumpyPool actually implements


def env_spec(env_name: str) -> dict:
    """Static facts about a pool env — no pool construction needed."""
    if env_name not in ENV_IDS:
        raise ValueError(f"unknown env {env_name!r}; available: {sorted(ENV_IDS)}")
    eid = ENV_IDS[env_name]
    return {
        "env_id": eid,
        "obs_dim": _OBS_DIMS[eid],
        "obs_shape": _OBS_SHAPES[eid],
        "act_dim": _ACT_DIMS[eid],
        "discrete": _DISCRETE[eid],
        "n_actions": _N_ACTIONS[eid],
    }


def _stale(lib_path: str) -> bool:
    src = os.path.join(_NATIVE_DIR, "envpool.cpp")
    try:
        return os.path.getmtime(lib_path) < os.path.getmtime(src)
    except OSError:
        return True


def _load_library() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_LIB_PATH) or _stale(_LIB_PATH):
        # serialize concurrent builders (fork workers, parallel test runs):
        # without the lock two `make -B` runs race and one process can load
        # a partially-written .so; under the lock the loser re-checks and
        # finds the winner's fresh library
        lock_path = os.path.join(os.path.abspath(_NATIVE_DIR), ".build.lock")
        try:
            import fcntl  # POSIX-only; ImportError lands in the fallback path

            with open(lock_path, "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                if not os.path.exists(_LIB_PATH) or _stale(_LIB_PATH):
                    subprocess.run(
                        ["make", "-C", os.path.abspath(_NATIVE_DIR), "-B"],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
        except (subprocess.SubprocessError, ImportError, OSError):
            if not os.path.exists(_LIB_PATH):
                return None
            # stale-but-present: fall through and load it anyway
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.envpool_create.restype = ctypes.c_void_p
    lib.envpool_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
    ]
    lib.envpool_destroy.argtypes = [ctypes.c_void_p]
    lib.envpool_obs_dim.argtypes = [ctypes.c_void_p]
    lib.envpool_obs_dim.restype = ctypes.c_int
    lib.envpool_act_dim.argtypes = [ctypes.c_void_p]
    lib.envpool_act_dim.restype = ctypes.c_int
    f32p = ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.envpool_reset.argtypes = [ctypes.c_void_p, f32p]
    lib.envpool_step.argtypes = [ctypes.c_void_p, f32p, f32p, f32p, u8p]
    return lib


_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB = _load_library()
        _LIB_TRIED = True
    return _LIB


class NativeEnvPool:
    """N batched envs stepped by the C++ thread pool (NumPy fallback inside).

    API (all arrays are (n_envs, ...) float32):
        obs = pool.reset()
        obs, rew, done = pool.step(actions)   # auto-resets finished envs
    """

    def __init__(self, env: str, n_envs: int, n_threads: int = 0, seed: int = 0):
        if env not in ENV_IDS:
            raise ValueError(f"unknown env {env!r}; available: {sorted(ENV_IDS)}")
        self.env_name = env
        self.env_id = ENV_IDS[env]
        self.n_envs = int(n_envs)
        self.obs_dim = _OBS_DIMS[self.env_id]
        self.obs_shape = _OBS_SHAPES[self.env_id]
        self.act_dim = _ACT_DIMS[self.env_id]
        self.discrete = _DISCRETE[self.env_id]
        self.n_actions = _N_ACTIONS[self.env_id]
        n_threads = n_threads or min(os.cpu_count() or 1, 16)

        self._lib = _get_lib()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.envpool_create(
                self.env_id, self.n_envs, int(n_threads), int(seed)
            )
        if self._handle is None:
            if self.env_id not in _NUMPY_FALLBACK_IDS:
                raise RuntimeError(
                    f"{env!r} requires the C++ envpool (the NumPy fallback "
                    f"implements only "
                    f"{[k for k, v in ENV_IDS.items() if v in _NUMPY_FALLBACK_IDS]}); "
                    "ensure g++/make are available so estorch_tpu/native builds"
                )
            self._fallback = _NumpyPool(self.env_id, self.n_envs, seed)
        else:
            self._fallback = None

        self._obs = np.empty((self.n_envs, self.obs_dim), np.float32)
        self._rew = np.empty((self.n_envs,), np.float32)
        self._done = np.empty((self.n_envs,), np.uint8)

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def reset(self) -> np.ndarray:
        if self._fallback is not None:
            return self._fallback.reset()
        self._lib.envpool_reset(
            self._handle, self._obs.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )
        return self._obs.copy()

    def step(self, actions: np.ndarray):
        if self._fallback is not None:
            return self._fallback.step(actions)
        acts = np.ascontiguousarray(
            np.asarray(actions, np.float32).reshape(self.n_envs, self.act_dim)
        )
        f32p = ctypes.POINTER(ctypes.c_float)
        self._lib.envpool_step(
            self._handle,
            acts.ctypes.data_as(f32p),
            self._obs.ctypes.data_as(f32p),
            self._rew.ctypes.data_as(f32p),
            self._done.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return self._obs.copy(), self._rew.copy(), self._done.astype(bool)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.envpool_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _NumpyPool:
    """Vectorized NumPy twin of the C++ pool (same dynamics, same auto-reset)."""

    def __init__(self, env_id: int, n_envs: int, seed: int):
        self.env_id = env_id
        self.n = n_envs
        self.rng = np.random.default_rng(seed)
        self.state = None

    def reset(self) -> np.ndarray:
        if self.env_id == 0:
            self.state = self.rng.uniform(-0.05, 0.05, (self.n, 4)).astype(np.float32)
            return self.state.copy()
        th = self.rng.uniform(-np.pi, np.pi, self.n).astype(np.float32)
        thdot = self.rng.uniform(-1.0, 1.0, self.n).astype(np.float32)
        self.state = np.stack([th, thdot], 1)
        return self._pendulum_obs()

    def _reset_rows(self, rows: np.ndarray) -> None:
        k = int(rows.sum())
        if k == 0:
            return
        if self.env_id == 0:
            self.state[rows] = self.rng.uniform(-0.05, 0.05, (k, 4)).astype(np.float32)
        else:
            th = self.rng.uniform(-np.pi, np.pi, k)
            thdot = self.rng.uniform(-1.0, 1.0, k)
            self.state[rows] = np.stack([th, thdot], 1).astype(np.float32)

    def _pendulum_obs(self) -> np.ndarray:
        th, thdot = self.state[:, 0], self.state[:, 1]
        return np.stack([np.cos(th), np.sin(th), thdot], 1).astype(np.float32)

    def step(self, actions: np.ndarray):
        a = np.asarray(actions, np.float32).reshape(self.n, -1)
        if self.env_id == 0:
            g, mc, mp, l, fm, tau = 9.8, 1.0, 0.1, 0.5, 10.0, 0.02
            x, x_dot, th, th_dot = (self.state[:, i] for i in range(4))
            force = np.where(a[:, 0] > 0.5, fm, -fm)
            costh, sinth = np.cos(th), np.sin(th)
            tm = mc + mp
            pml = mp * l
            temp = (force + pml * th_dot**2 * sinth) / tm
            thacc = (g * sinth - costh * temp) / (l * (4.0 / 3.0 - mp * costh**2 / tm))
            xacc = temp - pml * thacc * costh / tm
            self.state = np.stack(
                [x + tau * x_dot, x_dot + tau * xacc, th + tau * th_dot,
                 th_dot + tau * thacc], 1,
            ).astype(np.float32)
            done = (np.abs(self.state[:, 0]) > 2.4) | (
                np.abs(self.state[:, 2]) > 12 * 2 * np.pi / 360
            )
            rew = np.ones(self.n, np.float32)
            self._reset_rows(done)
            return self.state.copy(), rew, done
        # pendulum
        ms, mt, dt, g, m, l = 8.0, 2.0, 0.05, 10.0, 1.0, 1.0
        th, thdot = self.state[:, 0], self.state[:, 1]
        u = np.clip(a[:, 0], -mt, mt)
        an = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = an**2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = np.clip(
            thdot + (3 * g / (2 * l) * np.sin(th) + 3.0 / (m * l**2) * u) * dt, -ms, ms
        )
        self.state = np.stack([th + newthdot * dt, newthdot], 1).astype(np.float32)
        done = np.zeros(self.n, bool)
        return self._pendulum_obs(), (-cost).astype(np.float32), done
