"""Pure-JAX CartPole-v1 (classic control), bit-faithful to the Gym dynamics.

The reference's README example is CartPole ES through a host Gym env
(SURVEY.md §2 item 9).  Here the same physics run ON the TPU inside the
rollout scan, so population × horizon env steps happen in one compiled
program.  Dynamics follow the standard Barto-Sutton-Anderson cart-pole with
Euler integration and the Gym constants; parity with ``gymnasium``'s
CartPole-v1 is asserted step-for-step in tests/test_envs.py.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CartPole:
    gravity: float = 9.8
    masscart: float = 1.0
    masspole: float = 0.1
    length: float = 0.5  # half the pole's length
    force_mag: float = 10.0
    tau: float = 0.02
    theta_threshold: float = 12 * 2 * jnp.pi / 360
    x_threshold: float = 2.4

    obs_dim: int = 4
    action_dim: int = 2
    discrete: bool = True
    default_horizon: int = 500
    bc_dim: int = 2

    # physics constants liftable into a traced ScenarioParams operand
    # (estorch_tpu/scenarios, docs/scenarios.md)
    SCENARIO_FIELDS = ("gravity", "masscart", "masspole", "length",
                       "force_mag")

    def scenario_defaults(self) -> dict:
        return {n: float(getattr(self, n)) for n in self.SCENARIO_FIELDS}

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        state = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        return state, state

    def step(self, state, action):
        return self.step_p(None, state, action)

    def step_p(self, params, state, action):
        """ONE dynamics definition for both forms (see Pendulum.step_p)."""
        from .base import scenario_value as sv

        gravity = sv(params, "gravity", self.gravity)
        masscart = sv(params, "masscart", self.masscart)
        masspole = sv(params, "masspole", self.masspole)
        length = sv(params, "length", self.length)
        force_mag = sv(params, "force_mag", self.force_mag)
        x, x_dot, theta, theta_dot = state[0], state[1], state[2], state[3]
        force = jnp.where(action == 1, force_mag, -force_mag)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        total_mass = masscart + masspole
        polemass_length = masspole * length

        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc

        new_state = jnp.stack([x, x_dot, theta, theta_dot])
        done = (
            (jnp.abs(x) > self.x_threshold) | (jnp.abs(theta) > self.theta_threshold)
        )
        reward = jnp.float32(1.0)
        return new_state, new_state, reward, done

    def behavior(self, state, obs) -> jax.Array:
        """BC = final cart position and pole angle."""
        return jnp.stack([state[0], state[2]])
