"""Pure-JAX Pendulum-v1 (continuous control), faithful to the Gym dynamics.

Continuous-action counterpart for the device-native rollout path; parity
with ``gymnasium``'s Pendulum-v1 asserted in tests/test_envs.py.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


def _angle_normalize(x):
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


@dataclasses.dataclass(frozen=True)
class Pendulum:
    max_speed: float = 8.0
    max_torque: float = 2.0
    dt: float = 0.05
    g: float = 10.0
    m: float = 1.0
    l: float = 1.0

    obs_dim: int = 3
    action_dim: int = 1
    discrete: bool = False
    default_horizon: int = 200
    bc_dim: int = 2
    action_bound: float = 2.0  # |torque| ≤ max_torque

    # physics constants liftable into a traced ScenarioParams operand
    # (estorch_tpu/scenarios, docs/scenarios.md)
    SCENARIO_FIELDS = ("g", "m", "l", "max_torque")

    def scenario_defaults(self) -> dict:
        return {n: float(getattr(self, n)) for n in self.SCENARIO_FIELDS}

    def _obs(self, state):
        th, thdot = state[0], state[1]
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot])

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        hi = jnp.array([jnp.pi, 1.0])
        state = jax.random.uniform(key, (2,), minval=-hi, maxval=hi)
        return state, self._obs(state)

    def step(self, state, action):
        return self.step_p(None, state, action)

    def step_p(self, params, state, action):
        """ONE dynamics definition for both forms: ``params`` is None
        (plain path — constants stay Python floats, graph unchanged) or a
        ScenarioParams pytree whose values enter as traced operands."""
        from .base import scenario_value as sv

        g = sv(params, "g", self.g)
        m = sv(params, "m", self.m)
        l = sv(params, "l", self.l)
        max_torque = sv(params, "max_torque", self.max_torque)
        th, thdot = state[0], state[1]
        u = jnp.clip(action.reshape(()), -max_torque, max_torque)
        cost = _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2

        newthdot = thdot + (
            3 * g / (2 * l) * jnp.sin(th) + 3.0 / (m * l**2) * u
        ) * self.dt
        newthdot = jnp.clip(newthdot, -self.max_speed, self.max_speed)
        newth = th + newthdot * self.dt

        new_state = jnp.stack([newth, newthdot])
        return new_state, self._obs(new_state), -cost, jnp.bool_(False)

    def behavior(self, state, obs) -> jax.Array:
        """BC = final angle (cos, sin) — where the pendulum ended up."""
        return jnp.stack([jnp.cos(state[0]), jnp.sin(state[0])])
