"""Pure-JAX Pendulum-v1 (continuous control), faithful to the Gym dynamics.

Continuous-action counterpart for the device-native rollout path; parity
with ``gymnasium``'s Pendulum-v1 asserted in tests/test_envs.py.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


def _angle_normalize(x):
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


@dataclasses.dataclass(frozen=True)
class Pendulum:
    max_speed: float = 8.0
    max_torque: float = 2.0
    dt: float = 0.05
    g: float = 10.0
    m: float = 1.0
    l: float = 1.0

    obs_dim: int = 3
    action_dim: int = 1
    discrete: bool = False
    default_horizon: int = 200
    bc_dim: int = 2
    action_bound: float = 2.0  # |torque| ≤ max_torque

    def _obs(self, state):
        th, thdot = state[0], state[1]
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot])

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        hi = jnp.array([jnp.pi, 1.0])
        state = jax.random.uniform(key, (2,), minval=-hi, maxval=hi)
        return state, self._obs(state)

    def step(self, state, action):
        th, thdot = state[0], state[1]
        u = jnp.clip(action.reshape(()), -self.max_torque, self.max_torque)
        cost = _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2

        newthdot = thdot + (
            3 * self.g / (2 * self.l) * jnp.sin(th) + 3.0 / (self.m * self.l**2) * u
        ) * self.dt
        newthdot = jnp.clip(newthdot, -self.max_speed, self.max_speed)
        newth = th + newthdot * self.dt

        new_state = jnp.stack([newth, newthdot])
        return new_state, self._obs(new_state), -cost, jnp.bool_(False)

    def behavior(self, state, obs) -> jax.Array:
        """BC = final angle (cos, sin) — where the pendulum ended up."""
        return jnp.stack([jnp.cos(state[0]), jnp.sin(state[0])])
