"""Pure-JAX MountainCar-v0 (discrete), faithful to the Gym dynamics.

Completes the classic-control family on the device path (CartPole, Acrobot,
Pendulum, MountainCarContinuous, MountainCar); parity-tested against
gymnasium in tests/test_envs.py.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MountainCar:
    min_position: float = -1.2
    max_position: float = 0.6
    max_speed: float = 0.07
    goal_position: float = 0.5
    goal_velocity: float = 0.0
    force: float = 0.001
    gravity: float = 0.0025

    obs_dim: int = 2
    action_dim: int = 3  # push left / no-op / push right
    discrete: bool = True
    default_horizon: int = 200
    bc_dim: int = 1

    # physics constants liftable into a traced ScenarioParams operand
    # (estorch_tpu/scenarios, docs/scenarios.md)
    SCENARIO_FIELDS = ("force", "gravity", "max_speed")

    def scenario_defaults(self) -> dict:
        return {n: float(getattr(self, n)) for n in self.SCENARIO_FIELDS}

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        pos = jax.random.uniform(key, (), minval=-0.6, maxval=-0.4)
        state = jnp.stack([pos, jnp.float32(0.0)])
        return state, state

    def step(self, state, action):
        return self.step_p(None, state, action)

    def step_p(self, params, state, action):
        """ONE dynamics definition for both forms (see Pendulum.step_p)."""
        from .base import scenario_value as sv

        force_c = sv(params, "force", self.force)
        gravity = sv(params, "gravity", self.gravity)
        max_speed = sv(params, "max_speed", self.max_speed)
        position, velocity = state[0], state[1]
        velocity = velocity + (action - 1) * force_c + jnp.cos(
            3 * position
        ) * (-gravity)
        velocity = jnp.clip(velocity, -max_speed, max_speed)
        position = jnp.clip(position + velocity, self.min_position, self.max_position)
        velocity = jnp.where(
            (position == self.min_position) & (velocity < 0), 0.0, velocity
        )
        done = (position >= self.goal_position) & (velocity >= self.goal_velocity)
        reward = jnp.float32(-1.0)
        new_state = jnp.stack([position, velocity])
        return new_state, new_state, reward, done

    def behavior(self, state, obs) -> jax.Array:
        """BC = final position (how far up the hill it got)."""
        return state[:1]
