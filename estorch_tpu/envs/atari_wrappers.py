"""ALE-standard preprocessing for the pooled path: stack, repeat, sticky.

The Atari-ES staples the reference's users rely on (SURVEY.md §2 item 6 —
VBN's raison d'être is pixel policies; upstream estorch leaves preprocessing
to user-side Gym wrappers):

- **frame stacking** — the policy sees the last N macro-frames concatenated
  along the channel axis: (84, 84, 1) → (84, 84, 4), NatureCNN's designed
  input.  Velocity is unobservable from a single frame.
- **action repeat** — each policy action is applied for K raw env steps
  with rewards summed (ALE frame-skip), cutting policy forwards 4×.
- **sticky actions** — with probability ς the env repeats the previous
  macro-action instead of the commanded one (ALE v5's determinism-breaking
  evaluation protocol).
- **2-frame max-pooling** — optional max over the last two raw frames of a
  repeat (sprite-flicker removal on real Atari hardware).

Implemented at the POOL level (wrapping NativeEnvPool / GymVecPool), not
per-env: the pooled engine's contract is one batched (n_envs, obs_dim)
buffer per step, so the wrapper keeps the stack as one (n_envs, H, W, C·N)
ring and the whole transform stays vectorized NumPy — no per-env Python.

Auto-reset caveat (inherited from the pool contract): when an env finishes
mid-repeat, remaining raw steps of that macro-step run in the fresh episode;
the wrapper reports done=True and refills that env's stack at the NEXT
macro-step, and the pooled engine's alive-mask stops reading the env after
done — so, as with the underlying pools, post-done frames never influence
fitness.
"""

from __future__ import annotations

import numpy as np


class AtariPreprocessPool:
    """Wrap any pool with frame-stack / action-repeat / sticky actions."""

    def __init__(
        self,
        pool,
        frame_stack: int = 4,
        action_repeat: int = 1,
        sticky_prob: float = 0.0,
        max_pool2: bool = False,
        seed: int = 0,
    ):
        if frame_stack < 1 or action_repeat < 1:
            raise ValueError(
                f"frame_stack and action_repeat must be ≥1, got "
                f"{frame_stack}/{action_repeat}"
            )
        if not 0.0 <= sticky_prob < 1.0:
            raise ValueError(f"sticky_prob must be in [0, 1), got {sticky_prob}")
        if max_pool2 and action_repeat < 2:
            raise ValueError("max_pool2 needs action_repeat ≥ 2 (it maxes "
                             "the last two raw frames of a repeat)")
        self._pool = pool
        self.frame_stack = int(frame_stack)
        self.action_repeat = int(action_repeat)
        self.sticky_prob = float(sticky_prob)
        self.max_pool2 = bool(max_pool2)
        self._rng = np.random.default_rng(seed ^ 0xA7A21)

        self.env_name = getattr(pool, "env_name", "?")
        self.n_envs = pool.n_envs
        self.discrete = pool.discrete
        self.n_actions = pool.n_actions
        self.act_dim = pool.act_dim
        base_shape = tuple(pool.obs_shape)
        if len(base_shape) == 1:  # vector obs: stack as a trailing axis
            base_shape = base_shape + (1,)
        self._base_shape = base_shape
        self.obs_shape = base_shape[:-1] + (base_shape[-1] * self.frame_stack,)
        self.obs_dim = int(np.prod(self.obs_shape))

        self._stack = np.zeros((self.n_envs,) + self.obs_shape, np.float32)
        self._prev_action: np.ndarray | None = None
        self._pending_refill = np.zeros(self.n_envs, bool)

    def is_native(self) -> bool:
        # the pool families disagree on the spelling (NativeEnvPool:
        # property; GymVecPool: method) — accept both, so wrapping a
        # real C++ pool doesn't crash on a bool() call
        probe = self._pool.is_native
        return bool(probe() if callable(probe) else probe)

    # ------------------------------------------------------------ internals

    def _push(self, frames: np.ndarray, refill_mask=None):
        """Shift the ring one macro-frame left and append ``frames``."""
        c = self._base_shape[-1]
        frames = frames.reshape((self.n_envs,) + self._base_shape)
        if refill_mask is not None and refill_mask.any():
            # envs that auto-reset since last macro-step: their history
            # belongs to the dead episode — fill every slot with the fresh
            # frame instead of leaking pre-reset pixels into the stack
            tiled = np.concatenate([frames[refill_mask]] * self.frame_stack, -1)
            self._stack[refill_mask] = tiled
            live = ~refill_mask
            self._stack[live, ..., :-c] = self._stack[live, ..., c:]
            self._stack[live, ..., -c:] = frames[live]
        else:
            self._stack[..., :-c] = self._stack[..., c:]
            self._stack[..., -c:] = frames
        return self._stack.reshape(self.n_envs, self.obs_dim).copy()

    # ------------------------------------------------------------ interface

    def reset(self) -> np.ndarray:
        obs = self._pool.reset().reshape((self.n_envs,) + self._base_shape)
        self._stack = np.concatenate([obs] * self.frame_stack, -1)
        self._prev_action = None
        self._pending_refill[:] = False
        return self._stack.reshape(self.n_envs, self.obs_dim).copy()

    def step(self, actions: np.ndarray):
        a = np.asarray(actions, np.float32).reshape(self.n_envs, -1)
        if self.sticky_prob and self._prev_action is not None:
            sticky = self._rng.random(self.n_envs) < self.sticky_prob
            a = np.where(sticky[:, None], self._prev_action, a)
        self._prev_action = a.copy()

        total_rew = np.zeros(self.n_envs, np.float32)
        done = np.zeros(self.n_envs, bool)
        prev_frame = None
        frame = None
        for k in range(self.action_repeat):
            frame, rew, d = self._pool.step(a)
            # rewards after an env's first done belong to the auto-reset
            # successor episode — mask them out of this macro-step
            total_rew += np.where(done, 0.0, rew)
            done |= np.asarray(d, bool)
            if self.max_pool2 and k == self.action_repeat - 2:
                prev_frame = frame
        if prev_frame is not None:
            frame = np.maximum(frame, prev_frame)

        refill = self._pending_refill
        obs = self._push(frame, refill_mask=refill if refill.any() else None)
        # envs that finished THIS macro-step get their stack refilled next
        # macro-step (their current frame may be terminal or already-reset
        # depending on pool family; either way the next episode starts clean)
        self._pending_refill = done.copy()
        return obs, total_rew, done

    def close(self) -> None:
        self._pool.close()


def apply_prep_to_spec(spec: dict, frame_stack: int = 4) -> dict:
    """Adjust a pool_env_spec for the wrapper's stacked observation shape."""
    base = tuple(spec["obs_shape"])
    if len(base) == 1:
        base = base + (1,)
    shape = base[:-1] + (base[-1] * int(frame_stack),)
    return dict(spec, obs_shape=shape, obs_dim=int(np.prod(shape)))
