"""JaxEnv — the device-native environment contract.

The reference's rollout contract is a duck-typed host object:
``Agent.rollout(policy) -> reward`` (or ``(reward, bc)`` for the novelty
variants) stepping a Gym env in a Python while-loop (SURVEY.md §3.3).  That
per-step host↔device ping-pong is the reference's throughput ceiling.

The TPU-native contract is a pair of PURE functions over explicit state:

    state, obs = env.reset(key)
    state, obs, reward, done = env.step(state, action)

so an entire episode compiles into one ``lax.scan`` (envs/rollout.py) and an
entire population of episodes into one ``vmap`` — the whole generation is a
single XLA program.  Host-side envs (MuJoCo, Atari, arbitrary Gym) remain
supported through envs/host_pool.py, which implements the same duck-typed
``Agent.rollout`` surface as the reference.

Envs are frozen dataclasses of static Python scalars (closed over at trace
time, never traced), with state as a small pytree of arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Protocol, Tuple

import jax

EnvState = Any  # pytree of arrays


def scenario_value(params, name: str, default):
    """THE lookup rule of every parameterized env family: the scenario
    pytree's traced value when the draw includes ``name``, else the env's
    static dataclass constant.

    ``params is None`` (the plain ``step`` path) short-circuits to the
    Python-float default, so the un-randomized graph is IDENTICAL to the
    pre-scenario one — goldens and parity tests see no change.  Presence
    of a name in ``params`` is a Python-level (static) fact, so variant
    count never shows up in program structure: N variants differ only in
    traced VALUES, one XLA program total (estorch_tpu/scenarios,
    docs/scenarios.md)."""
    if params is None:
        return default
    return params.get(name, default)


class JaxEnv(Protocol):
    """Structural type for device-native envs."""

    obs_dim: int
    action_dim: int  # number of discrete actions, or continuous action dims
    discrete: bool
    default_horizon: int
    bc_dim: int  # behavior-characterization dims (novelty variants)

    def reset(self, key: jax.Array) -> Tuple[EnvState, jax.Array]: ...

    def step(
        self, state: EnvState, action: jax.Array
    ) -> Tuple[EnvState, jax.Array, jax.Array, jax.Array]: ...

    def behavior(self, state: EnvState, obs: jax.Array) -> jax.Array:
        """BC vector for novelty search; default impls use final observation."""
        ...


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """Static facts the engine needs about an env (shapes, modes)."""

    obs_dim: int
    action_dim: int
    discrete: bool
    horizon: int
    bc_dim: int

    @staticmethod
    def of(env: JaxEnv, horizon: int | None = None) -> "EnvSpec":
        return EnvSpec(
            obs_dim=env.obs_dim,
            action_dim=env.action_dim,
            discrete=env.discrete,
            horizon=int(horizon or env.default_horizon),
            bc_dim=env.bc_dim,
        )
