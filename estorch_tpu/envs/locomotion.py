"""Pure-JAX planar locomotion: articulated chains with soft joints/contact.

Device-native MuJoCo-class locomotion (SURVEY.md §7 Path A: the north-star
route is physics *inside* the compiled generation program; `mujoco.mjx` is
not importable in this image, so this module provides the fallback the
round-1 verdict called for — "a pure-JAX locomotion env of honest
difficulty").

Physics formulation (chosen for XLA, not copied from anywhere): maximal
coordinates — every body carries (position, angle, velocity, angular
velocity) — with joints enforced as stiff spring-dampers between anchor
points and ground contact as a penalty spring with regularized Coulomb
friction, integrated by semi-implicit Euler at a small physics dt with an
action frame-skip.  This is the standard "soft/spring" rigid-body scheme
(the same family brax's spring backend and classic game physics use): every
step is a fixed small stack of elementwise ops over (n_bodies, …) arrays —
no constraint solver, no data-dependent branching — so a whole episode
compiles into one ``lax.scan`` and a population of episodes into one
``vmap`` over it, exactly like the classic-control envs (envs/base.py).

Honesty of difficulty: the tasks reward forward velocity with control
costs, terminate on falling (hopper, walker, humanoid), and are deceptive
enough that random
policies score ~0; they are NOT step-for-step MuJoCo ports (different
integrator, soft joints) and make no parity claim — reward scales are
task-local.  MuJoCo-the-library stays supported on the host/pooled paths
(envs/gym_vec_pool.py).

Bodies are rods of half-length ``half_len`` with anchors at their two ends;
a chain is described by joint rows (parent, child, parent_end, child_end,
angle offset, limits, motor gear).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

# Physics state: a dict pytree of (n_bodies,) or (n_bodies, 2) arrays plus
# a step counter — see _init_state.


def _rot(theta):
    c, s = jnp.cos(theta), jnp.sin(theta)
    return jnp.stack([jnp.stack([c, -s], -1), jnp.stack([s, c], -1)], -2)


@dataclasses.dataclass(frozen=True)
class _Chain:
    """Static description of a planar articulated chain (tuples: hashable,

    closed over at trace time; converted to jnp constants inside step)."""

    # per body
    mass: tuple
    half_len: tuple
    init_pos: tuple  # (x, y) world
    init_angle: tuple
    # per joint: (parent, child) body indices and which end of each
    parent: tuple
    child: tuple
    parent_end: tuple  # +1 → tip (+half_len side), -1 → tail
    child_end: tuple
    rest_angle: tuple  # child minus parent rest angle
    limit_lo: tuple
    limit_hi: tuple
    gear: tuple  # motor ANGULAR authority per joint (rad/s²): torque =
    # gear · action · I_red, with I_red the joint's reduced inertia — so a
    # unit action accelerates any joint comparably regardless of how light
    # the child body is (absolute torques made foot joints, I ~1e-3,
    # integrate at Δω ≈ 30 rad/s per physics step → instant blow-up)
    # world
    gravity: float = -9.81
    ground: bool = True
    # spring/damper constants (per unit mass of the lighter body)
    k_joint: float = 4000.0
    c_joint: float = 60.0
    # angular constants, all scaled by the joint's reduced inertia: spring
    # frequency √k_limit and damping rates c_limit/joint_damping are then
    # joint-independent, and explicit-integration stability is one global
    # check (dt·√k ≲ 0.5, dt·c ≲ 0.5) instead of per-body luck
    k_limit: float = 8000.0
    c_limit: float = 100.0
    joint_damping: float = 30.0
    k_contact: float = 3000.0
    c_contact: float = 30.0
    friction: float = 1.0
    drag: float = 0.0  # linear drag (swimmer's fluid); 0 on land
    angular_drag: float = 0.0
    dt: float = 0.002
    frame_skip: int = 8

    @property
    def n_bodies(self):
        return len(self.mass)

    @property
    def n_joints(self):
        return len(self.parent)


def _solve_init_positions(chain: _Chain) -> tuple:
    """Derive init positions so every joint's anchors coincide exactly.

    Hand-specified positions inevitably leave anchor gaps that the stiff
    joint springs turn into huge t=0 forces; only the root position and the
    per-body angles are trusted, the rest follows from the joint graph
    (joints are listed parent-before-child).  Pure NumPy at construction.
    """
    import numpy as np

    pos = [np.asarray(p, np.float64) for p in chain.init_pos]
    ang = [float(a) for a in chain.init_angle]

    def end_off(i, end):
        return np.array([np.cos(ang[i]), np.sin(ang[i])]) * end * chain.half_len[i]

    for j in range(chain.n_joints):
        p, c = chain.parent[j], chain.child[j]
        anchor = pos[p] + end_off(p, chain.parent_end[j])
        pos[c] = anchor - end_off(c, chain.child_end[j])
    return tuple((float(p[0]), float(p[1])) for p in pos)


def _anchor_world(pos, theta, half_len, end):
    """World coordinates of a rod end: pos + R(θ)·(end·half_len, 0)."""
    local = jnp.stack([end * half_len, jnp.zeros_like(half_len)], -1)
    return pos + jnp.einsum("...ij,...j->...i", _rot(theta), local), local


def _physics_step(chain: _Chain, state, motor_torque):
    """One semi-implicit Euler step of the whole chain. Pure, jit-safe."""
    pos, theta = state["pos"], state["theta"]  # (B,2), (B,)
    vel, omega = state["vel"], state["omega"]

    mass = jnp.asarray(chain.mass)
    half = jnp.asarray(chain.half_len)
    inertia = mass * (2 * half) ** 2 / 12.0 + 1e-6  # rod about center

    force = jnp.zeros_like(pos)
    torque = jnp.zeros_like(theta)

    # gravity
    force = force.at[:, 1].add(mass * chain.gravity)

    # fluid / air drag (swimmer locomotion medium)
    if chain.drag:
        # anisotropic rod drag: normal component resisted ~30x the axial —
        # this asymmetry is what makes undulation propel the swimmer
        tang = jnp.stack([jnp.cos(theta), jnp.sin(theta)], -1)
        v_ax = jnp.sum(vel * tang, -1, keepdims=True) * tang
        v_nrm = vel - v_ax
        force = force - chain.drag * (0.1 * v_ax + 3.0 * v_nrm) * (2 * half)[:, None]
        torque = torque - chain.angular_drag * omega * (2 * half) ** 3

    pj = jnp.asarray(chain.parent, jnp.int32)
    cj = jnp.asarray(chain.child, jnp.int32)
    pe = jnp.asarray(chain.parent_end)
    ce = jnp.asarray(chain.child_end)

    # --- joints: stiff spring-damper pulling the two anchors together ---
    a_w, a_loc = _anchor_world(pos[pj], theta[pj], half[pj], pe)
    b_w, b_loc = _anchor_world(pos[cj], theta[cj], half[cj], ce)
    # anchor world velocities: v + ω × r  (2-D cross: ω×(x,y) = (-ωy, ωx))
    a_r = a_w - pos[pj]
    b_r = b_w - pos[cj]
    a_v = vel[pj] + jnp.stack([-omega[pj] * a_r[:, 1], omega[pj] * a_r[:, 0]], -1)
    b_v = vel[cj] + jnp.stack([-omega[cj] * b_r[:, 1], omega[cj] * b_r[:, 0]], -1)
    m_eff = jnp.minimum(mass[pj], mass[cj])
    f_j = (-chain.k_joint * (a_w - b_w) - chain.c_joint * (a_v - b_v)) * m_eff[:, None]

    # joint angle, limits, motors (equal/opposite torques on the pair)
    q = theta[cj] - theta[pj] - jnp.asarray(chain.rest_angle)
    qdot = omega[cj] - omega[pj]
    lo, hi = jnp.asarray(chain.limit_lo), jnp.asarray(chain.limit_hi)
    i_red = inertia[pj] * inertia[cj] / (inertia[pj] + inertia[cj])
    t_lim = (
        chain.k_limit * (jnp.maximum(lo - q, 0.0) - jnp.maximum(q - hi, 0.0))
        - chain.c_limit * qdot * ((q < lo) | (q > hi))
    ) * i_red
    t_act = jnp.asarray(chain.gear) * motor_torque * i_red
    t_damp = -chain.joint_damping * qdot * i_red
    t_pair = t_lim + t_act + t_damp

    # scatter joint forces/torques to bodies
    force = force.at[pj].add(f_j).at[cj].add(-f_j)
    cross_a = a_r[:, 0] * f_j[:, 1] - a_r[:, 1] * f_j[:, 0]
    cross_b = b_r[:, 0] * (-f_j[:, 1]) - b_r[:, 1] * (-f_j[:, 0])
    torque = torque.at[pj].add(cross_a - t_pair).at[cj].add(cross_b + t_pair)

    # --- ground contact at both rod ends (penalty + regularized friction) ---
    if chain.ground:
        for end in (-1.0, 1.0):
            p_w, _ = _anchor_world(pos, theta, half, jnp.full_like(half, end))
            r = p_w - pos
            v_p = vel + jnp.stack([-omega * r[:, 1], omega * r[:, 0]], -1)
            depth = jnp.minimum(p_w[:, 1], 0.0)  # ≤0 when penetrating
            fn = (-chain.k_contact * depth - chain.c_contact * v_p[:, 1] * (depth < 0)) * mass
            fn = jnp.maximum(fn, 0.0) * (depth < 0)
            ft = -chain.friction * fn * jnp.tanh(v_p[:, 0] / 0.1)
            f_c = jnp.stack([ft, fn], -1)
            force = force + f_c
            torque = torque + r[:, 0] * f_c[:, 1] - r[:, 1] * f_c[:, 0]

    # --- semi-implicit Euler ---
    vel = vel + chain.dt * force / mass[:, None]
    omega = omega + chain.dt * torque / inertia
    pos = pos + chain.dt * vel
    theta = theta + chain.dt * omega
    return {"pos": pos, "theta": theta, "vel": vel, "omega": omega,
            "t": state["t"]}


def _init_state(chain: _Chain, key):
    pos = jnp.asarray(chain.init_pos, jnp.float32)
    theta = jnp.asarray(chain.init_angle, jnp.float32)
    # small random perturbation (MuJoCo-style reset noise)
    k1, k2 = jax.random.split(key)
    theta = theta + 0.01 * jax.random.normal(k1, theta.shape)
    vel = 0.01 * jax.random.normal(k2, pos.shape)
    return {"pos": pos, "theta": theta, "vel": vel,
            "omega": jnp.zeros_like(theta), "t": jnp.int32(0)}


class _PlanarBase:
    """Shared JaxEnv plumbing over a _Chain; subclasses define the chain and
    set the obs/reward knobs below (or override `_obs`/`_reward_done`
    outright, as the swimmer's observation does).

    Class-level knobs (plain attributes, not dataclass fields):
      upright_offset — torso rest angle, subtracted in obs and used as the
                       lean reference for termination
      alive_bonus / ctrl_cost — reward shaping
      min_height / max_lean — falling termination; min_height None → the
                       env never terminates (swimmer, cheetah)
    """

    chain: _Chain
    discrete: bool = False
    action_bound: float = 1.0
    upright_offset: float = 0.0
    alive_bonus: float = 0.0
    ctrl_cost: float = 1e-3
    min_height = None
    max_lean = None

    # chain constants liftable into a traced ScenarioParams operand
    # (estorch_tpu/scenarios, docs/scenarios.md).  The chain's absolute
    # constants are per-body/per-joint TUPLES tuned jointly for
    # integrator stability, so the family randomizes multiplicative
    # SCALES (default 1.0) rather than absolute values — a ±30% mass or
    # gravity scale preserves the dt·√k stability margins the class
    # docstring above derives.
    SCENARIO_FIELDS = ("gravity_scale", "mass_scale", "friction_scale",
                       "gear_scale")

    def scenario_defaults(self) -> dict:
        return {n: 1.0 for n in self.SCENARIO_FIELDS}

    def _scenario_chain(self, params) -> _Chain:
        """The chain with any drawn scales applied.  ``params is None``
        returns ``self.chain`` itself — no replace, identical graph.
        Traced scales live INSIDE the rebuilt chain's fields (tuples of
        traced scalars stack fine under ``jnp.asarray``), so the physics
        step needs no second code path."""
        if params is None:
            return self.chain
        ch = self.chain
        kw = {}
        if "gravity_scale" in params:
            kw["gravity"] = ch.gravity * params["gravity_scale"]
        if "mass_scale" in params:
            s = params["mass_scale"]
            kw["mass"] = tuple(m * s for m in ch.mass)
        if "friction_scale" in params:
            kw["friction"] = ch.friction * params["friction_scale"]
        if "gear_scale" in params:
            s = params["gear_scale"]
            kw["gear"] = tuple(g * s for g in ch.gear)
        return dataclasses.replace(ch, **kw) if kw else ch

    def _obs(self, state):
        """Standard runner observation: torso height + lean, joint angles,
        torso velocity/spin, joint rates (the MuJoCo runner layout)."""
        return jnp.concatenate([
            jnp.array([state["pos"][0, 1],
                       state["theta"][0] - self.upright_offset]),
            _joint_angles(self.chain, state),
            state["vel"][0] * 0.3,
            jnp.array([state["omega"][0] * 0.1]),
            _joint_rates(self.chain, state) * 0.1,
        ])

    def _reward_done(self, prev, state, action):
        vx = (state["pos"][0, 0] - prev["pos"][0, 0]) / self.control_dt
        reward = self.alive_bonus + vx - self.ctrl_cost * jnp.sum(action**2)
        if self.min_height is None:
            return reward, jnp.bool_(False)
        lean = jnp.abs(state["theta"][0] - self.upright_offset)
        done = (state["pos"][0, 1] < self.min_height) | (lean > self.max_lean)
        return reward, done

    def _finalize_chain(self, chain: _Chain):
        """Snap init positions to the joint graph and install the chain."""
        chain = dataclasses.replace(chain, init_pos=_solve_init_positions(chain))
        object.__setattr__(self, "chain", chain)

    def reset(self, key: jax.Array):
        state = _init_state(self.chain, key)
        return state, self._obs(state)

    def step(self, state, action):
        return self.step_p(None, state, action)

    def step_p(self, params, state, action):
        """ONE dynamics definition for both forms (see Pendulum.step_p)."""
        chain = self._scenario_chain(params)
        act = jnp.clip(jnp.atleast_1d(action), -1.0, 1.0)

        def body(s, _):
            return _physics_step(chain, s, act), None

        new_state, _ = jax.lax.scan(body, state, None,
                                    length=chain.frame_skip)
        new_state = dict(new_state, t=state["t"] + 1)
        reward, done = self._reward_done(state, new_state, act)
        return new_state, self._obs(new_state), reward, done

    def behavior(self, state, obs) -> jax.Array:
        """BC = final torso (x, y) — where the gait carried the body."""
        return state["pos"][0]

    @property
    def control_dt(self):
        return self.chain.dt * self.chain.frame_skip

    # ---- gait metrics (round-4 verdict weak #4: "walking" must be a
    # measured claim — m/s and %-upright — not a reward-scale one) ----

    # stricter than max_lean (the FALLING threshold, ~57° on the humanoid):
    # a body can average 50° of lean without terminating and is not
    # meaningfully "upright"; 0.35 rad ≈ 20° is a standing/walking posture
    upright_lean: float = 0.35

    @property
    def metric_names(self) -> tuple:
        return ("upright_fraction",)

    def step_metrics(self, state) -> jax.Array:
        """Per-step gait accumulables, summed alive-masked by the rollout
        (envs/rollout.py ``with_env_metrics``)."""
        if self.max_lean is None:
            # horizontal-body runners (swimmer, cheetah) have no upright
            # posture to lose; report 1 so the fraction reads "n/a-upright"
            return jnp.ones((1,), jnp.float32)
        lean = jnp.abs(state["theta"][0] - self.upright_offset)
        return (lean < self.upright_lean).astype(jnp.float32)[None]

    def episode_metrics(self, bc, steps, sums) -> dict:
        """Episode gait summary from the rollout's (bc, steps, metric sums).

        ``forward_velocity_mps`` is displacement-based — (final torso x −
        initial x) / alive time — the quantity that transfers to MuJoCo
        Humanoid's "distance covered" framing, robust to within-episode
        speed variance.  Initial x is deterministic (reset noise perturbs
        angles/velocities only, ``_init_state``)."""
        steps = max(int(steps), 1)
        t = steps * float(self.control_dt)
        x0 = float(self.chain.init_pos[0][0])
        return {
            "upright_fraction": float(sums[0]) / steps,
            "forward_velocity_mps": (float(bc[0]) - x0) / t,
        }


def _joint_angles(chain, state):
    pj = jnp.asarray(chain.parent, jnp.int32)
    cj = jnp.asarray(chain.child, jnp.int32)
    return state["theta"][cj] - state["theta"][pj] - jnp.asarray(chain.rest_angle)


def _joint_rates(chain, state):
    pj = jnp.asarray(chain.parent, jnp.int32)
    cj = jnp.asarray(chain.child, jnp.int32)
    return state["omega"][cj] - state["omega"][pj]


@dataclasses.dataclass(frozen=True)
class Swimmer2D(_PlanarBase):
    """3-link planar swimmer in a viscous medium (MuJoCo Swimmer-class).

    Contact-free, gravity-free: propulsion comes purely from anisotropic
    fluid drag on the undulating chain — the easiest honest locomotion task
    (nothing to fall over), ideal as the device-native default.
    Reward: head forward velocity − control cost.
    """

    n_links: int = 3
    obs_dim: int = 10  # 2·n_links angles/rates + head vel (2) + joint angles
    action_dim: int = 2  # n_links − 1
    default_horizon: int = 500
    bc_dim: int = 2

    def __post_init__(self):
        n = self.n_links
        hl = 0.5
        chain = _Chain(
            mass=(1.0,) * n,
            half_len=(hl,) * n,
            init_pos=tuple((-(2 * hl) * i, 0.0) for i in range(n)),
            init_angle=(0.0,) * n,
            parent=tuple(range(n - 1)),
            child=tuple(range(1, n)),
            parent_end=(-1.0,) * (n - 1),  # tail of parent…
            child_end=(1.0,) * (n - 1),  # …to tip of child
            rest_angle=(0.0,) * (n - 1),
            limit_lo=(-1.75,) * (n - 1),
            limit_hi=(1.75,) * (n - 1),
            gear=(300.0,) * (n - 1),
            gravity=0.0,
            ground=False,
            drag=4.0,
            angular_drag=2.0,
            c_joint=30.0,
            dt=0.002,
            frame_skip=10,
        )
        self._finalize_chain(chain)
        object.__setattr__(self, "obs_dim", 2 * (n - 1) + n + 2)
        object.__setattr__(self, "action_dim", n - 1)

    ctrl_cost = 1e-4

    def _obs(self, state):
        return jnp.concatenate([
            _joint_angles(self.chain, state),
            _joint_rates(self.chain, state) * 0.1,
            state["theta"],  # absolute link angles (heading)
            state["vel"][0] * 0.5,  # head velocity
        ])


@dataclasses.dataclass(frozen=True)
class Hopper2D(_PlanarBase):
    """Planar one-legged hopper (MuJoCo Hopper-class): torso–thigh–shin–foot.

    Ground contact + gravity; terminates when the torso falls.  Reward:
    alive bonus + forward velocity − control cost (the MuJoCo shaping).
    """

    obs_dim: int = 11
    action_dim: int = 3
    default_horizon: int = 500
    bc_dim: int = 2

    def __post_init__(self):
        # bodies: 0 torso (upright rod), 1 thigh, 2 shin, 3 foot (horizontal)
        chain = _Chain(
            mass=(3.5, 1.0, 1.0, 0.6),
            half_len=(0.2, 0.2, 0.25, 0.13),
            init_pos=((0.0, 1.05), (0.0, 0.65), (0.0, 0.2), (0.06, -0.05)),
            init_angle=(jnp.pi / 2, jnp.pi / 2, jnp.pi / 2, 0.0),
            parent=(0, 1, 2),
            child=(1, 2, 3),
            parent_end=(-1.0, -1.0, -1.0),
            child_end=(1.0, 1.0, -1.0),
            rest_angle=(0.0, 0.0, -jnp.pi / 2),
            limit_lo=(-0.3, -1.5, -0.6),
            limit_hi=(1.5, 0.1, 0.6),
            gear=(800.0, 800.0, 500.0),
            gravity=-9.81,
            ground=True,
            dt=0.002,
            frame_skip=8,
        )
        self._finalize_chain(chain)

    upright_offset = jnp.pi / 2
    alive_bonus = 1.0
    min_height = 0.6
    max_lean = 0.7


@dataclasses.dataclass(frozen=True)
class Walker2D(_PlanarBase):
    """Planar biped walker (MuJoCo Walker2d-class): torso + two hopper legs.

    The nearest in-tree step toward the Humanoid north star: the policy
    must BALANCE on two legs (terminates when the torso falls, unlike the
    cheetah whose torso rides on four attachment points) and coordinate an
    alternating gait.  7 bodies, 6 actuated joints.  Reward: alive bonus +
    forward velocity − control cost (the MuJoCo shaping).  Legs start with
    slightly asymmetric knee/hip bends so the symmetric do-nothing policy
    is unstable enough to explore away from.
    """

    obs_dim: int = 17
    action_dim: int = 6
    default_horizon: int = 500
    bc_dim: int = 2

    def __post_init__(self):
        # bodies: 0 torso (upright); 1-3 left thigh/shin/foot; 4-6 right.
        # Both hips share the torso's lower anchor, like Walker2d's pelvis.
        chain = _Chain(
            mass=(3.5, 1.0, 1.0, 0.6, 1.0, 1.0, 0.6),
            half_len=(0.2, 0.2, 0.25, 0.13, 0.2, 0.25, 0.13),
            init_pos=((0.0, 1.05),) + ((0.0, 0.0),) * 6,
            init_angle=(
                jnp.pi / 2,
                jnp.pi / 2 + 0.08, jnp.pi / 2 - 0.16, 0.0,
                jnp.pi / 2 - 0.08, jnp.pi / 2 - 0.02, 0.0,
            ),
            parent=(0, 1, 2, 0, 4, 5),
            child=(1, 2, 3, 4, 5, 6),
            parent_end=(-1.0, -1.0, -1.0, -1.0, -1.0, -1.0),
            child_end=(1.0, 1.0, -1.0, 1.0, 1.0, -1.0),
            rest_angle=(0.0, 0.0, -jnp.pi / 2, 0.0, 0.0, -jnp.pi / 2),
            limit_lo=(-1.0, -1.5, -0.6, -1.0, -1.5, -0.6),
            limit_hi=(1.0, 0.1, 0.6, 1.0, 0.1, 0.6),
            gear=(800.0, 800.0, 500.0, 800.0, 800.0, 500.0),
            gravity=-9.81,
            ground=True,
            dt=0.002,
            frame_skip=8,
        )
        self._finalize_chain(chain)

    upright_offset = jnp.pi / 2
    alive_bonus = 1.0
    min_height = 0.7
    max_lean = 1.0


@dataclasses.dataclass(frozen=True)
class Humanoid2D(_PlanarBase):
    """Planar humanoid (Humanoid-class stand-in): 11 bodies, 10 joints.

    Pelvis root with two walker legs (thigh–shin–foot), an abdomen joint
    to the torso, a neck to the head, and two arms hanging from the
    shoulders — the arms are free counterweights the policy can swing for
    balance, which is what separates humanoid balance from the walker's.
    The hardest in-tree task and the device-native stand-in for the
    reference users' Humanoid configs (BASELINE config 3 runs MuJoCo
    Humanoid on the host/pooled paths; this one compiles the physics into
    the generation program).  Terminates when the pelvis drops or the
    body leans past ~57°.  Reward: alive + forward velocity − control
    cost.
    """

    obs_dim: int = 25
    action_dim: int = 10
    default_horizon: int = 500
    bc_dim: int = 2

    def __post_init__(self):
        # bodies: 0 pelvis, 1 torso, 2 head, 3 larm, 4 rarm,
        #         5 lthigh, 6 lshin, 7 lfoot, 8 rthigh, 9 rshin, 10 rfoot
        chain = _Chain(
            mass=(3.0, 3.0, 0.8, 0.8, 0.8, 1.0, 1.0, 0.6, 1.0, 1.0, 0.6),
            half_len=(0.15, 0.2, 0.08, 0.18, 0.18,
                      0.2, 0.25, 0.13, 0.2, 0.25, 0.13),
            init_pos=((0.0, 1.0),) + ((0.0, 0.0),) * 10,
            init_angle=(
                jnp.pi / 2, jnp.pi / 2, jnp.pi / 2,            # column
                jnp.pi / 2 + 0.1, jnp.pi / 2 - 0.1,            # arms
                jnp.pi / 2 + 0.08, jnp.pi / 2 - 0.16, 0.0,     # left leg
                jnp.pi / 2 - 0.08, jnp.pi / 2 - 0.02, 0.0,     # right leg
            ),
            #        abdomen neck  lshld rshld lhip  lknee lankl rhip rknee rankl
            parent=(0, 1, 1, 1, 0, 5, 6, 0, 8, 9),
            child=(1, 2, 3, 4, 5, 6, 7, 8, 9, 10),
            parent_end=(1.0, 1.0, 1.0, 1.0, -1.0,
                        -1.0, -1.0, -1.0, -1.0, -1.0),
            child_end=(-1.0, -1.0, 1.0, 1.0, 1.0, 1.0, -1.0, 1.0, 1.0, -1.0),
            rest_angle=(0.0, 0.0, 0.0, 0.0, 0.0, 0.0, -jnp.pi / 2,
                        0.0, 0.0, -jnp.pi / 2),
            limit_lo=(-0.5, -0.5, -1.5, -1.5, -1.0, -1.5, -0.6,
                      -1.0, -1.5, -0.6),
            limit_hi=(0.5, 0.5, 1.5, 1.5, 1.0, 0.1, 0.6, 1.0, 0.1, 0.6),
            gear=(400.0, 100.0, 200.0, 200.0, 800.0, 800.0, 500.0,
                  800.0, 800.0, 500.0),
            gravity=-9.81,
            ground=True,
            dt=0.002,
            frame_skip=8,
        )
        self._finalize_chain(chain)

    upright_offset = jnp.pi / 2
    alive_bonus = 1.0
    min_height = 0.75
    max_lean = 1.0


@dataclasses.dataclass(frozen=True)
class Cheetah2D(_PlanarBase):
    """Planar two-legged runner (MuJoCo HalfCheetah-class): 7 bodies.

    Torso with back leg (thigh–shin) and front leg (thigh–shin) plus a
    head/neck rod for mass distribution.  Never terminates (cheetah-style);
    reward: forward velocity − control cost.
    """

    obs_dim: int = 17
    action_dim: int = 6
    default_horizon: int = 500
    bc_dim: int = 2

    def __post_init__(self):
        # 0 torso (horizontal), 1 bthigh, 2 bshin, 3 bfoot, 4 fthigh,
        # 5 fshin, 6 ffoot
        chain = _Chain(
            mass=(6.0, 1.5, 1.2, 0.8, 1.4, 1.1, 0.7),
            half_len=(0.5, 0.15, 0.15, 0.09, 0.13, 0.12, 0.07),
            # only the torso position is trusted; leg positions are solved
            # from the joint graph (θ≈+π/2 + attach-by-tip ⇒ hangs below,
            # the same convention the hopper uses)
            init_pos=((0.0, 0.56),) + ((0.0, 0.0),) * 6,
            init_angle=(
                0.0,
                jnp.pi / 2 + 0.3, jnp.pi / 2 - 0.5, 0.1,
                jnp.pi / 2 - 0.3, jnp.pi / 2 + 0.4, 0.0,
            ),
            parent=(0, 1, 2, 0, 4, 5),
            child=(1, 2, 3, 4, 5, 6),
            parent_end=(-1.0, -1.0, -1.0, 1.0, -1.0, -1.0),
            child_end=(1.0, 1.0, -1.0, 1.0, 1.0, -1.0),
            rest_angle=(jnp.pi / 2 + 0.3, -0.8, 0.6 - jnp.pi / 2,
                        jnp.pi / 2 - 0.3, 0.7, -jnp.pi / 2 - 0.4),
            limit_lo=(-0.6, -0.8, -0.5, -0.8, -0.7, -0.5),
            limit_hi=(1.0, 0.8, 0.5, 0.8, 0.7, 0.5),
            gear=(700.0, 500.0, 300.0, 700.0, 500.0, 300.0),
            gravity=-9.81,
            ground=True,
            dt=0.002,
            frame_skip=8,
        )
        self._finalize_chain(chain)

    ctrl_cost = 0.05


@dataclasses.dataclass(frozen=True)
class PositionOnly:
    """POMDP wrapper for the planar runners: zero every velocity channel
    of the observation (torso velocity, spin, joint rates), keeping the
    positional half (height, lean, joint angles).

    The classic partially observable locomotion setup: balance and gait
    need rate feedback the policy can no longer see, so a memoryless
    policy must infer it from nothing while a recurrent one can estimate
    it from consecutive positions — the locomotion-grade counterpart of
    the RecallEnv memory probe. Dynamics, reward, termination, and BC are
    the wrapped env's, untouched; obs_dim is unchanged (channels are
    zeroed, not dropped) so the same policy shapes fit both variants.
    """

    base: _PlanarBase

    def __post_init__(self):
        # the mask below hard-codes the STANDARD runner layout (_obs:
        # height+lean, joint angles, then velocities); an env overriding
        # _obs (Swimmer2D) would get the wrong channels zeroed silently
        if type(self.base)._obs is not _PlanarBase._obs:
            raise ValueError(
                f"PositionOnly supports the standard runner observation "
                f"layout; {type(self.base).__name__} overrides _obs — "
                "build its POMDP mask explicitly"
            )
        import numpy as _np

        n_joints = len(self.base.chain.parent)
        n_pos = 2 + n_joints  # height+lean, joint angles
        # NumPy, not jnp: envs are static Python data constructed BEFORE
        # any backend choice (envs/base.py contract) — a jnp array here
        # would initialize the default backend at env construction
        mask = _np.zeros((self.base.obs_dim,), _np.float32)
        mask[:n_pos] = 1.0
        object.__setattr__(self, "_mask", mask)

    # static facts forwarded for the engine/rollout machinery
    @property
    def obs_dim(self):
        return self.base.obs_dim

    @property
    def action_dim(self):
        return self.base.action_dim

    @property
    def discrete(self):
        return self.base.discrete

    @property
    def bc_dim(self):
        return self.base.bc_dim

    @property
    def default_horizon(self):
        return self.base.default_horizon

    @property
    def action_bound(self):
        return self.base.action_bound

    def reset(self, key):
        state, obs = self.base.reset(key)
        return state, obs * self._mask

    def step(self, state, action):
        nstate, obs, reward, done = self.base.step(state, action)
        return nstate, obs * self._mask, reward, done

    def behavior(self, state, obs):
        return self.base.behavior(state, obs)


@dataclasses.dataclass(frozen=True)
class DeceptiveValley:
    """Deceptive-reward wrapper for the planar runners: a reward VALLEY
    along the progress axis (round-4 verdict next #5 — a deceptive
    locomotion task where greedy forward reward dead-ends).

    The spatial U-maze of Conti et al. 2018 (PAPERS.md) is not expressible
    in a planar (x, z) world — there is no second ground axis to walk
    around an obstacle — so this is its exact 1-D equivalent, the
    reward-landscape form of deception (Lehman & Stanley's definition: the
    fitness gradient points AWAY from the global optimum):

        φ(x) = x                                  x ≤ x_bait   (the bait)
             = x_bait − valley_slope·(x − x_bait) x ≤ x_valley (the valley)
             = φ(x_valley) + rise_slope·(x − x_valley)  beyond  (the prize)

    Per-step reward is potential-based, ``reward_scale·(φ(x_t) −
    φ(x_{t−1}))`` plus the base env's alive bonus and control cost, so an
    episode's shaped return telescopes to ``reward_scale·(φ(x_T) − φ(x_0))``
    — walking up to the bait and stopping is a true local optimum whose
    basin covers the entire greedy path; every reward-following step past
    it reads as WORSE until the valley is fully crossed.  Novelty search
    over the final-position BC (the wrapped env's, untouched) has no such
    barrier: x past the bait is simply unvisited behavior space.

    Dynamics, observation, termination, and BC are the wrapped env's —
    the agent must genuinely locomote ~``x_valley``+ body lengths to win.
    """

    base: _PlanarBase
    x_bait: float = 1.0
    x_valley: float = 3.0
    valley_slope: float = 1.5
    rise_slope: float = 4.0
    reward_scale: float = 1.0

    def __post_init__(self):
        if not (self.x_bait < self.x_valley):
            raise ValueError(
                f"need x_bait < x_valley, got {self.x_bait} >= {self.x_valley}"
            )
        if self.valley_slope <= 0 or self.rise_slope <= 0:
            raise ValueError("valley_slope and rise_slope must be positive "
                             "(a non-decreasing φ is not deceptive)")

    # static facts forwarded for the engine/rollout machinery
    @property
    def obs_dim(self):
        return self.base.obs_dim

    @property
    def action_dim(self):
        return self.base.action_dim

    @property
    def discrete(self):
        return self.base.discrete

    @property
    def bc_dim(self):
        return self.base.bc_dim

    @property
    def default_horizon(self):
        return self.base.default_horizon

    @property
    def action_bound(self):
        return self.base.action_bound

    @property
    def control_dt(self):
        return self.base.control_dt

    def _phi(self, x):
        phi_valley_end = self.x_bait - self.valley_slope * (
            self.x_valley - self.x_bait
        )
        return jnp.where(
            x <= self.x_bait,
            x,
            jnp.where(
                x <= self.x_valley,
                self.x_bait - self.valley_slope * (x - self.x_bait),
                phi_valley_end + self.rise_slope * (x - self.x_valley),
            ),
        )

    def reset(self, key):
        return self.base.reset(key)

    def step(self, state, action):
        nstate, obs, _, done = self.base.step(state, action)
        act = jnp.clip(jnp.atleast_1d(action), -1.0, 1.0)
        dphi = self._phi(nstate["pos"][0, 0]) - self._phi(state["pos"][0, 0])
        reward = (
            self.base.alive_bonus
            + self.reward_scale * dphi
            - self.base.ctrl_cost * jnp.sum(act**2)
        )
        return nstate, obs, reward, done

    def behavior(self, state, obs):
        return self.base.behavior(state, obs)

    # gait metrics delegate: velocity/upright read dynamics, not reward
    @property
    def metric_names(self):
        return self.base.metric_names

    def step_metrics(self, state):
        return self.base.step_metrics(state)

    def episode_metrics(self, bc, steps, sums):
        return self.base.episode_metrics(bc, steps, sums)
