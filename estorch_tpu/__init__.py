"""estorch_tpu — a TPU-native Evolution Strategies framework.

Re-designs the capabilities of the reference library (goktug97/estorch — ES,
NS-ES, NSR-ES, NSRA-ES, VirtualBatchNorm, distributed population evaluation)
for TPU hardware: one compiled XLA program per generation, shared-noise-table
perturbations vmapped over the population in HBM, and a single ``lax.psum``
over the device mesh in place of MPI gather + master broadcast.

Public API mirrors the reference (SURVEY.md Appendix A); the algorithm
classes are re-exported here as they land:

    from estorch_tpu import ES, NS_ES, NSR_ES, NSRA_ES, VirtualBatchNorm
"""

__version__ = "0.3.0"

from . import (envs, models, obs, ops, parallel, resilience,  # noqa: F401
               scenarios, serve, utils)
from .algo import ES, IW_ES, NS_ES, NSR_ES, NSRA_ES, NoveltyArchive
from .envs.agent import JaxAgent, PooledAgent
from .models import (MLPPolicy, NatureCNN, RecurrentNatureCNN,
                     RecurrentPolicy, VirtualBatchNorm)

__all__ = [
    "ES",
    "IW_ES",
    "NS_ES",
    "NSR_ES",
    "NSRA_ES",
    "NoveltyArchive",
    "JaxAgent",
    "PooledAgent",
    "MLPPolicy",
    "NatureCNN",
    "RecurrentNatureCNN",
    "RecurrentPolicy",
    "VirtualBatchNorm",
    "envs",
    "models",
    "obs",
    "ops",
    "parallel",
    "resilience",
    "scenarios",
    "serve",
    "utils",
    "__version__",
]
