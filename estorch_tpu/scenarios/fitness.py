"""Per-variant fitness accounting: the record["scenarios"] block.

The variant id rides the BC channel (ScenarioEnv.behavior appends it as
the last column), so one O(population) host pass per generation turns
the fitness vector into a per-variant breakdown — the data ``obs
summarize``'s scenarios section and the PBT objective consume.
"""

from __future__ import annotations

import math

import numpy as np


def scenario_fitness_block(fitness, variants, n_variants: int) -> dict:
    """``{"n_variants", "counts", "mean", "best"}`` for one generation.

    ``variants`` is the BC variant column (floats carrying small ints);
    a variant no member drew this generation gets count 0 and NaN stats
    (JSON-legal — the schema treats NaN like a failed generation's
    reward).  NaN FITNESS (failed rollouts) is excluded from mean/best
    but still counted in ``counts`` — coverage is about assignment, not
    success."""
    fitness = np.asarray(fitness, np.float64)
    idx = np.asarray(np.rint(np.asarray(variants, np.float64)), np.int64)
    n_variants = int(n_variants)
    counts = [0] * n_variants
    means: list[float] = [math.nan] * n_variants
    bests: list[float] = [math.nan] * n_variants
    for v in range(n_variants):
        sel = fitness[idx == v]
        counts[v] = int(sel.size)
        finite = sel[np.isfinite(sel)]
        if finite.size:
            means[v] = float(finite.mean())
            bests[v] = float(finite.max())
    return {
        "n_variants": n_variants,
        "counts": counts,
        "mean": means,
        "best": bests,
    }


def merge_scenario_blocks(blocks: list[dict]) -> dict | None:
    """Fold per-generation blocks into one run-level view: count-weighted
    per-variant means, run-best bests, summed counts.  Blocks with
    mismatched ``n_variants`` (a mixed file) fold at the largest width.
    Returns None for an empty list."""
    blocks = [b for b in blocks if isinstance(b, dict)
              and isinstance(b.get("n_variants"), int)]
    if not blocks:
        return None
    width = max(int(b["n_variants"]) for b in blocks)
    counts = np.zeros(width, np.int64)
    wsum = np.zeros(width, np.float64)  # Σ mean·count over finite means
    wcnt = np.zeros(width, np.float64)
    best = np.full(width, -np.inf)
    for b in blocks:
        c = np.asarray(b.get("counts", []), np.float64)
        m = np.asarray(b.get("mean", []), np.float64)
        bb = np.asarray(b.get("best", []), np.float64)
        n = min(width, c.size, m.size, bb.size)
        counts[:n] += c[:n].astype(np.int64)
        ok = np.isfinite(m[:n]) & (c[:n] > 0)
        wsum[:n][ok] += m[:n][ok] * c[:n][ok]
        wcnt[:n][ok] += c[:n][ok]
        okb = np.isfinite(bb[:n])
        best[:n][okb] = np.maximum(best[:n][okb], bb[:n][okb])
    means = np.where(wcnt > 0, wsum / np.maximum(wcnt, 1), np.nan)
    return {
        "n_variants": width,
        "counts": [int(c) for c in counts],
        "mean": [float(m) for m in means],
        "best": [float(b) if np.isfinite(b) else math.nan for b in best],
    }


def worst_variant_callout(block: dict, mad_factor: float = 2.0
                          ) -> dict | None:
    """The laggard diagnosis: the variant whose mean fitness trails the
    family median by more than ``mad_factor`` × the cross-variant MAD
    (None when no variant lags, or when spread is degenerate — a zero
    MAD would call out any noise at all)."""
    means = np.asarray(block.get("mean", []), np.float64)
    finite = means[np.isfinite(means)]
    if finite.size < 3:
        return None
    med = float(np.median(finite))
    mad = float(np.median(np.abs(finite - med)))
    if mad <= 0:
        return None
    worst_v = int(np.nanargmin(np.where(np.isfinite(means), means, np.inf)))
    worst = float(means[worst_v])
    lag = med - worst
    if lag <= mad_factor * mad:
        return None
    return {
        "variant": worst_v,
        "mean": worst,
        "family_median": med,
        "cross_variant_mad": mad,
        "lag_in_mads": float(lag / mad),
    }
