"""ScenarioEnv — a JaxEnv whose physics are a traced per-episode draw.

Wraps any parameterized native family (an env exposing ``step_p(params,
state, action)`` + ``SCENARIO_FIELDS``) so that EVERY episode runs under
a procedurally-drawn variant of the physics, with zero engine changes:

- the variant id and its ScenarioParams ride the env STATE pytree, so
  they enter the jitted rollout as traced operands — never a Python
  closure (esguard R16's contract).  N variants, one XLA program.
- the variant is derived in-program from the episode's reset key: the
  assignment is therefore vmapped across the population axis for free,
  antithetic pairs (which share a rollout key — common random numbers)
  land on the SAME variant so the mirrored gradient fold compares ±ε
  under identical physics, and per-scenario fitness folds into the
  rank-based update through the existing ghost-pad/weighting machinery
  untouched.
- ``behavior`` appends the variant id as one extra BC column — the
  channel through which per-variant fitness reaches the host
  (``record["scenarios"]``, ``obs summarize``) without new engine
  plumbing.
- observation noise (the generic ``obs_noise`` parameter) is applied
  HERE, on reset and every step, from a noise key threaded through the
  state — env dynamics never see it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import ScenarioDistribution
from .params import OBS_NOISE

# passthrough static facts; bc_dim is NOT here (it grows by one)
_STATIC_ATTRS = ("obs_dim", "action_dim", "discrete", "default_horizon")
# optional protocol attrs copied when the base env has them
_OPTIONAL_ATTRS = ("action_bound",)


class ScenarioEnv:
    """JaxEnv over ``(base_state, params, variant, noise_key)`` state."""

    def __init__(self, env, distribution: ScenarioDistribution):
        if not hasattr(env, "step_p"):
            raise ValueError(
                f"{type(env).__name__} has no step_p(params, state, "
                "action) form — only the parameterized native families "
                "support scenario randomization (docs/scenarios.md)")
        distribution.validate_for(env)
        self.base = env
        self.distribution = distribution
        for a in _STATIC_ATTRS:
            setattr(self, a, getattr(env, a))
        for a in _OPTIONAL_ATTRS:
            if hasattr(env, a):
                setattr(self, a, getattr(env, a))
        self.bc_dim = int(env.bc_dim) + 1  # +1: the variant-id column
        self._noisy = OBS_NOISE in distribution.ranges
        if hasattr(env, "step_metrics"):
            self._install_gait()

    @property
    def n_variants(self) -> int:
        return self.distribution.n_variants

    # ---- JaxEnv protocol -------------------------------------------------

    def reset(self, key: jax.Array):
        kv, kb, kn = jax.random.split(key, 3)
        variant = jax.random.randint(
            kv, (), 0, self.distribution.n_variants, jnp.int32)
        params = self.distribution.draw(variant)
        state, obs = self.base.reset(kb)
        if self._noisy:
            kn, sub = jax.random.split(kn)
            obs = obs + params[OBS_NOISE] * jax.random.normal(
                sub, jnp.shape(obs))
        return (state, params, variant, kn), obs

    def step(self, sstate, action):
        state, params, variant, kn = sstate
        nstate, obs, reward, done = self.base.step_p(params, state, action)
        if self._noisy:
            kn, sub = jax.random.split(kn)
            obs = obs + params[OBS_NOISE] * jax.random.normal(
                sub, jnp.shape(obs))
        return (nstate, params, variant, kn), obs, reward, done

    def behavior(self, sstate, obs) -> jax.Array:
        state, _, variant, _ = sstate
        base_bc = jnp.atleast_1d(
            self.base.behavior(state, obs)).astype(jnp.float32)
        return jnp.concatenate(
            [base_bc, variant.astype(jnp.float32)[None]])

    # gait-metrics passthrough (locomotion family) is installed per
    # INSTANCE in _install_gait so ``hasattr(env, "step_metrics")`` — the
    # protocol probe evaluate_policy uses — stays honest for base envs
    # without the protocol (a class-level method would always answer yes)

    def _install_gait(self) -> None:
        base = self.base

        def step_metrics(sstate):
            return base.step_metrics(sstate[0])

        def episode_metrics(bc, steps, sums):
            # the base conversion expects its OWN bc layout; strip the
            # appended variant column before delegating
            import numpy as np

            return base.episode_metrics(np.asarray(bc)[:-1], steps, sums)

        self.metric_names = base.metric_names
        self.step_metrics = step_metrics
        self.episode_metrics = episode_metrics


def variant_of_bc(bc) -> "jnp.ndarray":
    """The variant-id column of a (n, bc_dim) batch of ScenarioEnv BCs
    (the last column, by the ``behavior`` contract above)."""
    import numpy as np

    return np.asarray(bc)[:, -1]
