"""ScenarioParams — the typed pytree of per-scenario physics constants.

The native envs (envs/pendulum.py …) are frozen dataclasses of static
Python floats closed over at trace time — which is exactly right for ONE
scenario and exactly wrong for N: a per-variant closure means a
per-variant XLA program (the recompile smell esguard R16 hunts).  This
module lifts those constants into a pytree whose LEAVES are traced
scalars, so variant count changes values, never program structure: the
whole randomized family costs O(1) compiled programs (the compile ledger
is the proof, ``bench.py --scenario-ab``).

Structure (which names exist) is static aux data; values are leaves.
Two ScenarioParams with the same names are the same pytree type — the
vmap/scan machinery and the done-freeze ``tree_map`` in envs/rollout.py
handle them like any other state leaf.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import jax

# every env family accepts this name on top of its own SCENARIO_FIELDS:
# additive observation-noise scale, applied generically by ScenarioEnv
# (the env's dynamics never see it)
OBS_NOISE = "obs_noise"


@jax.tree_util.register_pytree_node_class
class ScenarioParams(Mapping):
    """Immutable name → traced-scalar mapping, registered as a pytree.

    Keys are the static structure (sorted, hashable aux data — two
    params objects with equal names unify under ``jnp.where``/``vmap``);
    values are the leaves, in sorted-key order.
    """

    __slots__ = ("_values",)

    def __init__(self, values: Mapping):
        self._values = {str(k): values[k] for k in sorted(values)}

    # ---- Mapping protocol ------------------------------------------------

    def __getitem__(self, name: str):
        return self._values[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def get(self, name: str, default=None):
        return self._values.get(name, default)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(self._values)

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v!r}" for k, v in self._values.items())
        return f"ScenarioParams({inner})"

    # ---- pytree protocol -------------------------------------------------

    def tree_flatten(self):
        names = tuple(self._values)
        return tuple(self._values[n] for n in names), names

    @classmethod
    def tree_unflatten(cls, names, leaves):
        obj = object.__new__(cls)
        obj._values = dict(zip(names, leaves))
        return obj


def scenario_field_names(env) -> tuple[str, ...]:
    """The names a distribution may randomize for ``env``: the family's
    declared ``SCENARIO_FIELDS`` plus the generic ``obs_noise``.  Raises
    with a pointer when the env family was never parameterized."""
    fields = getattr(env, "SCENARIO_FIELDS", None)
    if fields is None:
        raise ValueError(
            f"{type(env).__name__} declares no SCENARIO_FIELDS — only the "
            "parameterized native families (Pendulum, CartPole, Acrobot, "
            "MountainCar[Continuous], the locomotion chains) support "
            "scenario randomization (docs/scenarios.md)"
        )
    return tuple(fields) + (OBS_NOISE,)
