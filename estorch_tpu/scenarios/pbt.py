"""PBTController — population-based self-tuning of sigma / learning rate.

K concurrent ES centers share ONE engine and its compiled programs (the
``meta_states`` pattern of the novelty family, algo/nses.py — K centers
cost K states, not K engines).  Every ``explore_every`` generations the
controller ranks centers by recent objective (per-scenario mean fitness
when scenario randomization is on — so a center that only wins easy
variants doesn't look tuned), and the bottom quantile EXPLOITS a top
center (copies its params + optimizer state + hyperparameters) then
EXPLORES by perturbing ``sigma`` — and ``learning_rate``, when the run's
optimizer was built with :func:`tunable_optimizer` — by a random factor.

Every decision is a structured event in a deterministic log (the PR-8
async-scheduler discipline): ``run(..., replay=log)`` re-applies the
recorded decisions instead of re-deciding, and because each generation
step is a deterministic function of state, the replayed run's final
parameters are BIT-EXACTLY the live run's (the tier-1 acceptance test).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

LOG_SCHEMA = 1


def tunable_optimizer(factory=None, **kwargs):
    """An optax transformation whose hyperparameters live in the
    OPTIMIZER STATE (``optax.inject_hyperparams``) — the form under
    which PBT can tune the learning rate per center without rebuilding
    engines.  ``tunable_optimizer(learning_rate=0.01)`` wraps adam."""
    import optax

    if factory is None:
        factory = optax.adam
    return optax.inject_hyperparams(factory)(**kwargs)


def _state_lr(state) -> float | None:
    """The learning rate carried in an inject_hyperparams opt state, or
    None when the optimizer was not built tunable."""
    hp = getattr(state.opt_state, "hyperparams", None)
    if isinstance(hp, dict) and "learning_rate" in hp:
        return float(np.asarray(hp["learning_rate"]))
    return None


def _with_lr(state, lr: float):
    opt = state.opt_state
    hp = dict(opt.hyperparams)
    hp["learning_rate"] = jnp.float32(lr)
    return state._replace(opt_state=opt._replace(hyperparams=hp))


class PBTController:
    """Drive ``es`` as a K-center self-tuning population."""

    def __init__(self, es, n_centers: int = 4, explore_every: int = 5,
                 seed: int = 0, perturb_factors=(0.8, 1.25),
                 exploit_fraction: float = 0.25,
                 sigma_bounds=(1e-4, 2.0), lr_bounds=(1e-5, 1.0),
                 init_spread: float = 2.0):
        if es.backend != "device":
            raise ValueError(
                "PBTController drives the device-path engines (their "
                "init_state(params, key) builds fresh centers); the "
                "host/pooled backends have no cheap multi-center form")
        if getattr(es, "_shard_params", False):
            raise ValueError(
                "PBTController currently drives the replicated device "
                "engine: the sharded engine DONATES its input state, so "
                "an exploited (aliased) center would hand the program "
                "deleted buffers (docs/scenarios.md)")
        if n_centers < 2:
            raise ValueError(f"n_centers must be >= 2, got {n_centers}")
        if explore_every < 1:
            raise ValueError(
                f"explore_every must be >= 1, got {explore_every}")
        if init_spread < 1.0:
            raise ValueError(
                f"init_spread must be >= 1.0, got {init_spread}")
        self.es = es
        self.n_centers = int(n_centers)
        self.explore_every = int(explore_every)
        self.seed = int(seed)
        self.perturb_factors = tuple(float(f) for f in perturb_factors)
        self.exploit_fraction = float(exploit_fraction)
        self.sigma_bounds = (float(sigma_bounds[0]), float(sigma_bounds[1]))
        self.lr_bounds = (float(lr_bounds[0]), float(lr_bounds[1]))
        self.init_spread = float(init_spread)
        self.lr_tunable = _state_lr(es.state) is not None
        self.event_log: dict | None = None

    # ---- hyperparameter plumbing ----------------------------------------

    def _apply_hypers(self, state, sigma: float, lr: float | None):
        state = state._replace(sigma=jnp.float32(sigma))
        if lr is not None and self.lr_tunable:
            state = _with_lr(state, lr)
        return state

    def _clip(self, value: float, bounds) -> float:
        return float(min(max(value, bounds[0]), bounds[1]))

    # ---- objective -------------------------------------------------------

    @staticmethod
    def _objective(record: dict) -> float:
        """Per-scenario mean of means when the run is randomized (a
        balanced score no easy-variant lottery can inflate), else the
        plain generation mean."""
        block = record.get("scenarios")
        if isinstance(block, dict):
            means = np.asarray(block.get("mean", []), np.float64)
            finite = means[np.isfinite(means)]
            if finite.size:
                return float(finite.mean())
        v = float(record.get("reward_mean", np.nan))
        return v if np.isfinite(v) else -np.inf

    # ---- the run ---------------------------------------------------------

    def run(self, n_generations: int,
            log_fn: Callable[[dict], None] | None = None,
            verbose: bool = False, replay: dict | None = None):
        """``n_generations`` generations PER CENTER.  Returns the event
        log (also left on ``self.event_log``); ``es.state`` ends on the
        best-scoring center and ``es.meta_states`` holds all K."""
        es = self.es
        events: list[dict] = []
        meta = {"n_centers": self.n_centers,
                "explore_every": self.explore_every,
                "seed": self.seed, "n_generations": int(n_generations),
                "lr_tunable": self.lr_tunable}
        replay_events: list[dict] | None = None
        if replay is not None:
            if replay.get("schema") != LOG_SCHEMA:
                raise ValueError(
                    f"unknown PBT log schema {replay.get('schema')!r}")
            if replay.get("meta") != meta:
                raise ValueError(
                    "replay log was recorded under a different PBT "
                    f"configuration: {replay.get('meta')} != {meta}")
            replay_events = list(replay.get("events", []))
        rng = np.random.default_rng(self.seed)

        def pop_replay(expected_type: str) -> dict:
            if not replay_events:
                raise ValueError(
                    f"replay log exhausted while expecting a "
                    f"{expected_type!r} event — truncated log?")
            ev = replay_events.pop(0)
            if ev.get("type") != expected_type:
                raise ValueError(
                    f"replay log out of order: expected {expected_type!r}, "
                    f"got {ev.get('type')!r}")
            return ev

        # ---- centers: state 0 is es.state; the rest re-key the SAME
        # initial params (PBT tunes hypers from one start, unlike the
        # novelty family's distinct fresh inits) ----
        import jax

        base_state = es.state
        base_sigma = float(np.asarray(base_state.sigma))
        base_lr = _state_lr(base_state)
        states = [base_state]
        for k in range(1, self.n_centers):
            key = jax.random.fold_in(
                jax.random.PRNGKey(es.seed), 90000 + k)
            states.append(es.engine.init_state(
                jnp.asarray(base_state.params_flat), key))
        hypers: list[tuple[float, float | None]] = []
        for k in range(self.n_centers):
            if replay_events is not None:
                ev = pop_replay("init")
                if ev.get("center") != k:
                    raise ValueError(
                        f"replay init event for center {ev.get('center')} "
                        f"out of order (expected {k})")
                sigma, lr = float(ev["sigma"]), ev.get("lr")
            else:
                # log-uniform ladder around the base hypers, center 0
                # kept at the base as the control arm
                if k == 0:
                    sigma, lr = base_sigma, base_lr
                else:
                    sigma = self._clip(
                        base_sigma * self.init_spread ** rng.uniform(-1, 1),
                        self.sigma_bounds)
                    lr = (self._clip(
                        base_lr * self.init_spread ** rng.uniform(-1, 1),
                        self.lr_bounds) if base_lr is not None else None)
            states[k] = self._apply_hypers(states[k], sigma, lr)
            hypers.append((sigma, lr))
            ev = {"type": "init", "center": k, "sigma": sigma, "lr": lr}
            events.append(ev)
            es.obs.event("pbt_init", **ev)
        scores: list[list[float]] = [[] for _ in range(self.n_centers)]

        n_bottom = max(1, int(round(self.n_centers
                                    * self.exploit_fraction)))
        n_bottom = min(n_bottom, self.n_centers - 1)

        for g in range(int(n_generations)):
            for k in range(self.n_centers):
                es.state = states[k]

                def annotate(rec, _k=k):
                    rec["pbt_center"] = _k
                    if log_fn is not None:
                        log_fn(rec)

                es.train(1, log_fn=annotate, verbose=verbose)
                states[k] = es.state
                scores[k].append(self._objective(es.history[-1]))
            es.meta_states = list(states)

            last_round = g == int(n_generations) - 1
            if (g + 1) % self.explore_every != 0 or last_round:
                continue

            # ---- exploit / explore --------------------------------------
            window = self.explore_every
            recent = [float(np.mean(s[-window:])) for s in scores]
            order = sorted(range(self.n_centers),
                           key=lambda i: recent[i], reverse=True)
            top = order[:max(1, n_bottom)]
            bottom = order[-n_bottom:]
            rnd = (g + 1) // self.explore_every
            for dst in bottom:
                if replay_events is not None:
                    ev = pop_replay("exploit")
                    src = int(ev["src"])
                    if int(ev["dst"]) != dst:
                        # the ranking is deterministic, so a mismatched
                        # dst means the log belongs to another run
                        raise ValueError(
                            f"replay exploit event targets center "
                            f"{ev['dst']}, live ranking chose {dst}")
                    sigma, lr = float(ev["sigma"]), ev.get("lr")
                else:
                    src = int(top[rng.integers(0, len(top))])
                    src_sigma = float(np.asarray(states[src].sigma))
                    src_lr = _state_lr(states[src])
                    factor = float(
                        self.perturb_factors[
                            rng.integers(0, len(self.perturb_factors))])
                    sigma = self._clip(src_sigma * factor,
                                       self.sigma_bounds)
                    if src_lr is not None:
                        lf = float(self.perturb_factors[
                            rng.integers(0, len(self.perturb_factors))])
                        lr = self._clip(src_lr * lf, self.lr_bounds)
                    else:
                        lr = None
                # copy the src center wholesale (params, optimizer
                # moments, obs stats) but keep dst's OWN key so center
                # noise streams stay decorrelated after the copy
                states[dst] = self._apply_hypers(
                    states[src]._replace(key=states[dst].key), sigma, lr)
                scores[dst] = list(scores[src])
                hypers[dst] = (sigma, lr)
                ev = {"type": "exploit", "round": rnd, "dst": int(dst),
                      "src": int(src), "sigma": sigma, "lr": lr,
                      "score_src": recent[src], "score_dst": recent[dst]}
                events.append(ev)
                es.obs.event("pbt_exploit", **ev)
            es.meta_states = list(states)

        if replay_events:
            raise ValueError(
                f"replay log has {len(replay_events)} unconsumed events")
        final_scores = [float(np.mean(s[-self.explore_every:]))
                        for s in scores]
        best = int(np.argmax(final_scores))
        es.state = states[best]
        es.meta_states = list(states)
        self.event_log = {"schema": LOG_SCHEMA, "meta": meta,
                          "events": events,
                          "final": {"best_center": best,
                                    "scores": final_scores,
                                    "hypers": [list(h) for h in hypers]}}
        return self.event_log
