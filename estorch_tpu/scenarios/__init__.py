"""estorch_tpu.scenarios — in-program domain randomization + PBT.

The scenario suite makes diversity first-class (docs/scenarios.md):

* :class:`ScenarioParams` — physics constants as a typed pytree of
  traced scalars (params.py);
* :class:`ScenarioDistribution` / :func:`default_distribution` — seeded
  procedural randomization, deterministic in ``(seed, variant)``
  (distribution.py);
* :class:`ScenarioEnv` — any parameterized native env family rolled out
  under a per-episode drawn variant, params entering the jitted rollout
  as traced operands (env.py);
* per-variant fitness accounting for ``record["scenarios"]`` and
  ``obs summarize`` (fitness.py);
* :class:`PBTController` / :func:`tunable_optimizer` — population-based
  self-tuning of sigma / learning rate with a deterministic,
  bit-exactly-replayable event log (pbt.py).

Wiring: ``ES(scenarios=<distribution>)`` (algo/es.py).
"""

from .distribution import (LogRange, Range, ScenarioDistribution,
                           default_distribution)
from .env import ScenarioEnv, variant_of_bc
from .fitness import (merge_scenario_blocks, scenario_fitness_block,
                      worst_variant_callout)
from .params import OBS_NOISE, ScenarioParams, scenario_field_names
from .pbt import PBTController, tunable_optimizer

__all__ = [
    "LogRange",
    "OBS_NOISE",
    "PBTController",
    "Range",
    "ScenarioDistribution",
    "ScenarioEnv",
    "ScenarioParams",
    "default_distribution",
    "merge_scenario_blocks",
    "scenario_field_names",
    "scenario_fitness_block",
    "tunable_optimizer",
    "variant_of_bc",
    "worst_variant_callout",
]
