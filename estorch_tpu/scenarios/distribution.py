"""ScenarioDistribution — seeded, declarative domain randomization.

A distribution is a dict of per-parameter ranges (uniform or log-uniform)
plus ``(n_variants, seed)``.  Variant ``v``'s parameters are drawn from
the ``(seed, variant)`` stream (ops/noise.py ``scenario_variant_key``) —
deterministic across generations, members, processes, and mesh shapes,
so a scenario is a NAME a run's manifest can carry and a replay can
reproduce, not an ephemeral sample.

``draw(variant)`` is trace-safe (``variant`` may be a traced int32):
the in-program assignment path draws each member's scenario inside the
jitted rollout — N variants never become N programs.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ..ops.noise import scenario_variant_key
from .params import OBS_NOISE, ScenarioParams, scenario_field_names

SPEC_SCHEMA = 1


@dataclasses.dataclass(frozen=True)
class Range:
    """Uniform (or, with ``log=True``, log-uniform) draw in [lo, hi]."""

    lo: float
    hi: float
    log: bool = False

    def __post_init__(self):
        if not (math.isfinite(self.lo) and math.isfinite(self.hi)):
            raise ValueError(f"range bounds must be finite, got {self}")
        if self.lo > self.hi:
            raise ValueError(f"need lo <= hi, got {self}")
        if self.log and self.lo <= 0:
            raise ValueError(
                f"log-uniform needs lo > 0, got {self} — use a linear "
                "Range for parameters that may reach zero")

    def draw(self, key: jax.Array) -> jax.Array:
        u = jax.random.uniform(key, (), jnp.float32)
        if self.log:
            llo, lhi = math.log(self.lo), math.log(self.hi)
            return jnp.exp(llo + u * (lhi - llo))
        return self.lo + u * (self.hi - self.lo)


def LogRange(lo: float, hi: float) -> Range:
    """Log-uniform range — the right prior for scale-like constants
    (masses, gains) whose plausible values span octaves."""
    return Range(lo, hi, log=True)


def _as_range(name: str, r) -> Range:
    if isinstance(r, Range):
        return r
    if isinstance(r, (tuple, list)) and len(r) == 2:
        return Range(float(r[0]), float(r[1]))
    raise TypeError(
        f"range for {name!r} must be a Range/LogRange or a (lo, hi) "
        f"pair, got {r!r}")


class ScenarioDistribution:
    """≥1 procedurally-drawn variants of one env family's constants."""

    def __init__(self, ranges: dict, n_variants: int = 10, seed: int = 0):
        if not ranges:
            raise ValueError("a ScenarioDistribution needs at least one "
                             "parameter range")
        if int(n_variants) < 1:
            raise ValueError(f"n_variants must be >= 1, got {n_variants}")
        self.ranges: dict[str, Range] = {
            str(k): _as_range(str(k), v) for k, v in ranges.items()}
        self.n_variants = int(n_variants)
        self.seed = int(seed)
        self.names: tuple[str, ...] = tuple(sorted(self.ranges))

    # ---- validation ------------------------------------------------------

    def validate_for(self, env) -> None:
        """Every randomized name must be one the env family declared (or
        the generic ``obs_noise``) — a typo'd constant silently drawing
        into nowhere would be a scenario that never happens."""
        allowed = set(scenario_field_names(env))
        unknown = [n for n in self.names if n not in allowed]
        if unknown:
            raise ValueError(
                f"{type(env).__name__} has no scenario parameter(s) "
                f"{unknown}; it declares {sorted(allowed)}")

    # ---- draws -----------------------------------------------------------

    def draw(self, variant) -> ScenarioParams:
        """Variant ``variant``'s parameters — trace-safe, deterministic
        in ``(seed, variant)`` only."""
        base = scenario_variant_key(self.seed, variant)
        values = {
            name: self.ranges[name].draw(jax.random.fold_in(base, i))
            for i, name in enumerate(self.names)
        }
        return ScenarioParams(values)

    def draw_all(self) -> ScenarioParams:
        """All variants stacked: each leaf gains a leading
        ``(n_variants,)`` axis (host-side inspection / tests)."""
        return jax.vmap(self.draw)(jnp.arange(self.n_variants))

    def draw_concrete(self, variant: int) -> dict[str, float]:
        """Host-side Python floats for one variant — the sequential
        bench leg and manifests instantiate concrete envs from these."""
        import numpy as np

        p = self.draw(int(variant))
        return {n: float(np.asarray(p[n])) for n in self.names}

    # ---- provenance ------------------------------------------------------

    def spec_json(self) -> dict:
        """The manifest-ready spec: distribution schema + draw seed — a
        bundle carrying this names the scenarios it was trained under,
        exactly (the draw is deterministic in this spec alone)."""
        return {
            "schema": SPEC_SCHEMA,
            "n_variants": self.n_variants,
            "seed": self.seed,
            "ranges": {
                n: {"lo": r.lo, "hi": r.hi, "log": r.log}
                for n, r in self.ranges.items()
            },
        }

    @classmethod
    def from_json(cls, spec: dict) -> "ScenarioDistribution":
        if spec.get("schema") != SPEC_SCHEMA:
            raise ValueError(
                f"unknown scenario spec schema {spec.get('schema')!r}")
        ranges = {
            n: Range(float(r["lo"]), float(r["hi"]), bool(r.get("log")))
            for n, r in spec["ranges"].items()
        }
        return cls(ranges, n_variants=int(spec["n_variants"]),
                   seed=int(spec["seed"]))

    def __repr__(self) -> str:
        return (f"ScenarioDistribution(n_variants={self.n_variants}, "
                f"seed={self.seed}, names={list(self.names)})")


def default_distribution(env, n_variants: int = 10, spread: float = 0.3,
                         obs_noise: float = 0.0, seed: int = 0
                         ) -> ScenarioDistribution:
    """±``spread`` uniform ranges around every declared constant of
    ``env`` (scale families randomize around 1.0), plus an optional
    additive observation-noise scale in [0, ``obs_noise``]."""
    if not 0.0 < spread < 1.0:
        raise ValueError(f"spread must be in (0, 1), got {spread}")
    scenario_field_names(env)  # the families-without-SCENARIO_FIELDS error
    defaults = env.scenario_defaults()
    ranges: dict[str, Range] = {}
    for name, d in defaults.items():
        lo, hi = d * (1.0 - spread), d * (1.0 + spread)
        ranges[name] = Range(min(lo, hi), max(lo, hi))
    if obs_noise > 0.0:
        ranges[OBS_NOISE] = Range(0.0, float(obs_noise))
    dist = ScenarioDistribution(ranges, n_variants=n_variants, seed=seed)
    dist.validate_for(env)
    return dist
