"""Resilience subsystem: supervised auto-resume, deterministic chaos
injection, and per-generation fault containment (docs/resilience.md).

The primitives (utils/fault.py NaN-drop renormalization,
utils/checkpoint.py exact-state resume, obs/recorder.py heartbeat) exist
elsewhere; this package is the layer that *uses* them under real
failure:

* :func:`run_resilient` — catch/rollback/re-run a faulted generation
  in-process.
* :class:`Supervisor` — child-process training with heartbeat watchdog
  and restart-from-latest-checkpoint.
* :class:`ChaosPlan` / ``ESTORCH_CHAOS`` — deterministic fault schedule
  so every recovery path above is exercised reproducibly.
"""

from .chaos import CHAOS_ENV, ChaosError, ChaosPlan
from .supervisor import Supervisor, run_resilient

__all__ = [
    "CHAOS_ENV",
    "ChaosError",
    "ChaosPlan",
    "Supervisor",
    "run_resilient",
]
