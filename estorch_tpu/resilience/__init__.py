"""Resilience subsystem: supervised auto-resume, deterministic chaos
injection, and per-generation fault containment (docs/resilience.md).

The primitives (utils/fault.py NaN-drop renormalization,
utils/checkpoint.py exact-state resume, obs/recorder.py heartbeat) exist
elsewhere; this package is the layer that *uses* them under real
failure:

* :func:`run_resilient` — catch/rollback/re-run a faulted generation
  in-process.
* :class:`Supervisor` — child-process training with heartbeat watchdog
  and restart-from-latest-checkpoint.
* :class:`ChaosPlan` / ``ESTORCH_CHAOS`` — deterministic fault schedule
  so every recovery path above is exercised reproducibly.
* :class:`Interleaver` / :func:`run_interleaved` — seeded forced-yield
  thread scheduler that turns the data races esguard's lockset rules
  (R18–R22) point at into bit-identical, replayable failures.
"""

from .chaos import CHAOS_ENV, ChaosError, ChaosPlan
from .interleave import (CoopLock, DeadlockError, InterleaveResult,
                         Interleaver, run_interleaved)
from .supervisor import Supervisor, run_resilient

__all__ = [
    "CHAOS_ENV",
    "ChaosError",
    "ChaosPlan",
    "CoopLock",
    "DeadlockError",
    "InterleaveResult",
    "Interleaver",
    "Supervisor",
    "run_interleaved",
    "run_resilient",
]
