"""Supervised auto-resume: the layer that keeps a run alive end to end.

Two granularities, composable:

* :func:`run_resilient` — **in-process** per-generation fault
  containment.  Wraps ``es.train(1)`` in a snapshot/restore loop: a
  generation that raises (dead env, checkpoint-write crash, injected
  chaos) is rolled back completely — state, generation counter, history,
  best-snapshot, meta-population/archive — counted
  (``generations_skipped``), and re-run.  Because the noise stream is
  derived from ``(key, generation)``, the re-run of a transient fault is
  bit-identical to a run that never faulted.  Bounded: persistent faults
  re-raise after ``max_consecutive_skips``.

* :class:`Supervisor` — **cross-process** restart-from-checkpoint.  The
  training loop runs in a child process (``spawn``: a fresh interpreter,
  so a parent's initialized JAX/torch runtime is never forked into the
  child); the parent watches child liveness two ways — exit status, and
  the heartbeat file (``ESTORCH_OBS_HEARTBEAT`` protocol,
  obs/recorder.py) for the silent-wedge case where the process is alive
  but stopped making progress.  On death or staleness it restarts the
  child with exponential backoff; the child resumes from
  ``PeriodicCheckpointer.latest()`` (the newest *finalized* payload — a
  crash mid-write cannot shadow the last good checkpoint).  Restart
  provenance (reason, exit code, last heartbeat, per-child counters)
  lands in the run manifest's ``resilience`` section, which
  ``python -m estorch_tpu.obs summarize`` surfaces.

The reference hangs forever when one worker dies mid-gather (SURVEY.md
§5); this module is the opposite contract: SIGKILL the whole run at any
point and the supervisor drives it to the same final parameters.
"""

from __future__ import annotations

import importlib
import json
import multiprocessing as mp
import os
import time

from ..obs.recorder import HEARTBEAT_ENV, STALE_AFTER_S, read_heartbeat
from . import chaos as _chaos


# ---------------------------------------------------------------------
# in-process: per-generation containment
# ---------------------------------------------------------------------

def _snapshot(es) -> dict:
    """Everything ``es.train(1)`` may mutate, cheap to capture (states are
    immutable NamedTuples; lists are shallow-copied).

    Param-sharded exception: the sharded engine DONATES its state, so a
    by-reference snapshot would hold buffers the very next generation
    deletes — the restore would hand back corpses ("buffer has been
    deleted or donated") instead of resuming.  Those states are deep-
    copied device-side (`.copy()` preserves each leaf's sharding); one
    extra state copy per generation is the price of rollback on the
    donated path, paid only under run_resilient.
    """
    state = es.state
    if getattr(es, "_shard_params", False):
        import jax

        state = jax.tree_util.tree_map(
            lambda x: x.copy() if hasattr(x, "copy") else x, state)
    snap = {
        "state": state,
        "generation": es.generation,
        "history_len": len(es.history),
        "best_reward": es.best_reward,
        "best_flat": es._best_flat,
    }
    if hasattr(es, "meta_states"):
        snap["meta_states"] = list(es.meta_states)
        snap["center_bc"] = list(es._center_bc)
    if hasattr(es, "archive"):
        snap["archive"] = es.archive.state_dict()
    if hasattr(es, "weight"):  # NSRA schedule
        snap["nsra"] = (es.weight, es._stagnation)
    return snap


def _restore(es, snap: dict) -> None:
    es.state = snap["state"]
    es.generation = snap["generation"]
    del es.history[snap["history_len"]:]
    es.best_reward = snap["best_reward"]
    es._best_flat = snap["best_flat"]
    if "meta_states" in snap:
        es.meta_states = list(snap["meta_states"])
        es._center_bc = list(snap["center_bc"])
    if "archive" in snap:
        from ..algo.archive import NoveltyArchive

        es.archive = NoveltyArchive.from_state_dict(snap["archive"])
    if "nsra" in snap:
        es.weight, es._stagnation = snap["nsra"]
    es.obs.discard_phases()  # partial spans of the aborted generation


def run_resilient(
    es,
    n_steps: int,
    n_proc: int = 1,
    log_fn=None,
    verbose: bool = False,
    checkpointer=None,
    max_skips: int = 16,
    max_consecutive_skips: int = 4,
):
    """Train ``n_steps`` generations, skipping (and re-running) any
    generation that raises instead of dying.

    ``checkpointer`` (a ``PeriodicCheckpointer``) is composed into the
    per-record callback, so a crash *inside a checkpoint save* rolls the
    just-finished generation back too — it re-runs deterministically and
    re-saves.  Returns ``es``.  Up to ``max_consecutive_skips``
    consecutive (and ``max_skips`` total) failed attempts are tolerated;
    one more re-raises — resilience must not become an infinite loop on
    a dead env.
    """
    target = es.generation + int(n_steps)
    consec = skips = 0

    def _log(record):
        if checkpointer is not None:
            checkpointer.on_record(record)
        if log_fn is not None:
            log_fn(record)

    while es.generation < target:
        # chaos process-level events key on the NEXT generation to run
        _chaos.process_wedge(es.generation)
        _chaos.process_kill(es.generation)
        snap = _snapshot(es)
        try:
            es.train(1, n_proc=n_proc, log_fn=_log, verbose=verbose)
        except Exception as e:  # noqa: BLE001 — containment IS the feature;
            # every skip is counted, recorded, and bounded below
            _restore(es, snap)
            skips += 1
            consec += 1
            es.obs.counters.inc("generations_skipped")
            es.obs.event("generation_skipped", gen=snap["generation"],
                         error=repr(e)[:200])
            if consec > max_consecutive_skips or skips > max_skips:
                raise
            continue
        consec = 0
    return es


# ---------------------------------------------------------------------
# cross-process: supervised restart from checkpoint
# ---------------------------------------------------------------------

def _resolve_factory(es_factory):
    """Accept a picklable callable or a ``"module:attr"`` spec string."""
    if isinstance(es_factory, str):
        mod, _, attr = es_factory.partition(":")
        if not attr:
            raise ValueError(
                f"factory spec {es_factory!r} must be 'module:attr'"
            )
        return getattr(importlib.import_module(mod), attr)
    return es_factory


def _generic_child_main(child_spec, child_args: tuple, root: str) -> None:
    """Child body for a generic supervised process (``child_target``):
    point the heartbeat into the supervision root, resolve the target in
    the CHILD (spec strings avoid pickling), run it.  The target owns its
    own platform policy — this runs in a spawned, fresh interpreter."""
    os.environ[HEARTBEAT_ENV] = os.path.join(root, "heartbeat.json")
    _resolve_factory(child_spec)(root, *child_args)


def _child_main(es_factory, root: str, target_generation: int, every: int,
                n_proc: int, verbose: bool) -> None:
    """Runs in the spawned child: build → resume from latest checkpoint →
    train resiliently to the target → final checkpoint."""
    # before the factory runs: ES reads the heartbeat path from the env at
    # construction, and the supervisor watches exactly this file
    os.environ[HEARTBEAT_ENV] = os.path.join(root, "heartbeat.json")
    es = _resolve_factory(es_factory)()

    from ..obs.sinks import JsonlSink
    from ..utils.checkpoint import PeriodicCheckpointer, restore_checkpoint

    # beat through the setup stretch: restore/manifest IO can take seconds
    # (orbax import, git-sha subprocess) and the staleness watchdog must
    # see progress, not a silent gap after the construction beat
    es.obs.note("supervisor_setup")
    ck = PeriodicCheckpointer(es, root, every=every)
    latest = ck.latest()
    if latest is not None:
        es.obs.note("supervisor_restore")
        restore_checkpoint(es, latest)
        es.obs.counters.inc("supervisor_resumes")
        es.obs.event("resumed_from_checkpoint", path=latest,
                     gen=es.generation)
    manifest_path = os.path.join(root, "manifest.json")
    if not os.path.exists(manifest_path):
        es.obs.note("supervisor_manifest")
        es.write_manifest(manifest_path)
    sink = JsonlSink(os.path.join(root, "run.jsonl"))
    try:
        if es.generation < target_generation:
            run_resilient(es, target_generation - es.generation,
                          n_proc=n_proc, log_fn=sink, verbose=verbose,
                          checkpointer=ck)
        if es.generation > 0:
            # final checkpoint regardless of `every` alignment (idempotent:
            # an existing directory for this generation is overwritten with
            # identical state)
            ck.save(es.generation - 1)
        ck.close()
    finally:
        sink.close()
        if hasattr(es.engine, "close"):
            es.engine.close()


class Supervisor:
    """Run training to ``target_generation`` with automatic restart.

    ``es_factory`` must be a picklable zero-arg callable (module-level
    function) or a ``"module:attr"`` spec — the child is *spawned* (fresh
    interpreter), never forked, so an initialized parent JAX runtime is
    not inherited mid-state.  The factory is also where platform policy
    belongs (e.g. ``force_cpu_backend`` before building the ES).

    The checkpoint directory ``ckpt_root`` is the unit of resumability:
    heartbeat, run JSONL, manifest, published counter totals
    (``counters.json``, scraped by the obs metrics sidecar), and
    ``gen_*`` checkpoints all live there, so a run's post-mortem is one
    directory.
    """

    def __init__(
        self,
        es_factory=None,
        ckpt_root: str = "",
        target_generation: int = 0,
        *,
        every: int = 5,
        n_proc: int = 1,
        max_restarts: int = 5,
        backoff_s: float = 0.5,
        backoff_max_s: float = 30.0,
        stale_after_s: float = STALE_AFTER_S,
        startup_grace_s: float = 120.0,
        poll_s: float = 0.5,
        verbose: bool = False,
        child_target=None,
        child_args: tuple = (),
    ):
        if (es_factory is None) == (child_target is None):
            raise ValueError(
                "pass exactly one of es_factory (training child) or "
                "child_target (generic supervised child)"
            )
        if not ckpt_root:
            raise ValueError("ckpt_root is required")
        self.es_factory = es_factory
        self.child_target = child_target
        self.child_args = tuple(child_args)
        self.ckpt_root = os.path.abspath(ckpt_root)
        self.target_generation = int(target_generation)
        self.every = int(every)
        self.n_proc = int(n_proc)
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.stale_after_s = float(stale_after_s)
        self.startup_grace_s = float(startup_grace_s)
        self.poll_s = float(poll_s)
        self.verbose = bool(verbose)
        self.restarts: list[dict] = []
        self._counters_total: dict[str, float] = {}
        self._hists_total: dict[str, dict] = {}
        self._counters_through_ts = 0.0
        self._publish_error: str | None = None
        self._child = None
        self._stop_requested = False
        self._stop_signaled = False
        os.makedirs(self.ckpt_root, exist_ok=True)

    # ------------------------------------------------------------- paths

    @property
    def heartbeat_path(self) -> str:
        return os.path.join(self.ckpt_root, "heartbeat.json")

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.ckpt_root, "manifest.json")

    def latest_checkpoint(self) -> str | None:
        from ..utils.checkpoint import latest_checkpoint

        return latest_checkpoint(self.ckpt_root)

    # --------------------------------------------------------------- run

    def run(self) -> dict:
        """Drive the run to completion; returns
        ``{"ok", "restarts", "checkpoint", "reason"}``."""
        import signal as _signal

        ctx = mp.get_context("spawn")
        attempt = 0
        ok = False
        reason = None
        while True:
            if self._stop_requested:
                # stop arrived during backoff: don't spawn a child only
                # to terminate it immediately
                ok = True
                break
            started = time.time()
            if self.child_target is not None:
                child = ctx.Process(
                    target=_generic_child_main,
                    args=(self.child_target, self.child_args,
                          self.ckpt_root),
                )
            else:
                child = ctx.Process(
                    target=_child_main,
                    args=(self.es_factory, self.ckpt_root,
                          self.target_generation, self.every, self.n_proc,
                          self.verbose),
                )
            child.start()
            self._child = child
            failure = self._watch(child, started)
            if failure is not None and not self._stop_requested:
                # record the restart BEFORE folding+publishing counters,
                # so the sidecar's restart_count gauge counts this death
                # for the whole next child's lifetime, not one publish late
                self.restarts.append({
                    "ts": time.time(),
                    "attempt": attempt,
                    "reason": failure,
                    "exitcode": child.exitcode,
                    "heartbeat": read_heartbeat(self.heartbeat_path),
                })
            self._accumulate_counters(started)
            if failure is None:
                ok = True
                break
            if self._stop_requested:
                # an operator stop is completion, not a crash to restart.
                # Clean when the child honored the forwarded SIGTERM
                # (exit 0 after drain) OR died BY that SIGTERM's default
                # disposition — a stop during startup lands before the
                # child installs its handler (several seconds of
                # jax import / bundle load), and that is still a normal
                # operator stop, not a crash to report
                ok = child.exitcode == 0 or (
                    self._stop_signaled
                    and child.exitcode == -int(_signal.SIGTERM))
                reason = None if ok else failure
                break
            attempt += 1
            if attempt > self.max_restarts:
                reason = failure
                break
            # exponential backoff: give a flapping environment (OOM killer,
            # tunnel outage) room to recover instead of hammering it
            time.sleep(min(self.backoff_s * (2 ** (attempt - 1)),
                           self.backoff_max_s))
        self._write_provenance(ok)
        return {
            "ok": ok,
            "restarts": list(self.restarts),
            "checkpoint": self.latest_checkpoint(),
            "reason": reason,
        }

    def request_stop(self, signum: int | None = None) -> None:
        """Operator stop (signal-handler safe): forward SIGTERM to the
        running child so it can drain, and stop restarting.  The serving
        stack routes its own SIGTERM here (serve/server.py).

        The forward is sent EXACTLY ONCE (handlers and ``_watch`` both
        run on the main thread, so the flag needs no lock): a second
        SIGTERM can land after the child's drain, during interpreter
        finalization when its handler is already torn down — killing a
        cleanly-drained child with the default disposition (-15)."""
        del signum
        self._stop_requested = True
        child = self._child
        if child is not None and child.is_alive() and not self._stop_signaled:
            self._stop_signaled = True
            child.terminate()  # SIGTERM — graceful drain, not kill

    def _watch(self, child, started: float) -> str | None:
        """Block until the child exits or is killed for staleness.
        Returns None on clean (exit 0) completion, else a reason string."""
        while True:
            child.join(timeout=self.poll_s)
            if child.exitcode is not None:
                if child.exitcode == 0:
                    return None
                return (f"child died with exit code {child.exitcode}"
                        + (" (signal)" if child.exitcode < 0 else ""))
            if self._stop_requested and not self._stop_signaled:
                # stop raced past request_stop's terminate (child was
                # between start() and _child assignment): forward it here
                self._stop_signaled = True
                child.terminate()
            hb = read_heartbeat(self.heartbeat_path)
            if hb is not None and float(hb.get("ts", 0.0)) >= started:
                # this child has beaten at least once: staleness watchdog
                if hb["age_s"] > self.stale_after_s:
                    child.kill()
                    child.join(timeout=10)
                    return (f"heartbeat stale ({hb['age_s']:.0f}s > "
                            f"{self.stale_after_s:.0f}s) — killed wedged "
                            f"child (last phase={hb.get('phase')!r} "
                            f"gen={hb.get('generation')})")
            elif time.time() - started > self.startup_grace_s:
                # never beat: wedged in import/init (the known device
                # bring-up failure mode doctor.py documents)
                child.kill()
                child.join(timeout=10)
                return (f"no heartbeat within {self.startup_grace_s:.0f}s "
                        "of start — child wedged before init finished")

    def _accumulate_counters(self, started: float) -> None:
        """Fold the (just-exited) child's last-heartbeat counters into the
        cross-restart totals.  Per-child counters start at zero, so the
        sum over children is the run's true total — this is how a
        SIGKILLed child's ``generations_rejected`` survives its death.
        A beat older than this child's start is a PREVIOUS child's file
        (the child died before beating) — counting it again would
        double-count that child's totals.

        The totals are also PUBLISHED atomically (``counters.json`` in
        the run dir, obs/export/sidecar.py) so the metrics sidecar can
        keep answering scrapes with monotone totals across the restart:
        the published ``through_ts`` tells the sidecar which heartbeat
        is already folded in, so a dead child's final beat is never
        counted twice."""
        hb = read_heartbeat(self.heartbeat_path)
        if hb is not None and float(hb.get("ts", 0.0)) >= started:
            for name, val in (hb.get("counters") or {}).items():
                if isinstance(val, (int, float)):
                    self._counters_total[name] = (
                        self._counters_total.get(name, 0) + val
                    )
            if isinstance(hb.get("hists"), dict):
                # latency DISTRIBUTIONS survive the child the same way
                # its sums do: bucket-wise fold (obs/hist.py)
                from ..obs.hist import merge_snapshots

                self._hists_total = merge_snapshots(
                    self._hists_total, hb["hists"])
            self._counters_through_ts = float(hb.get("ts", 0.0))
        # publish even when this child never beat (wedged import killed
        # by the startup grace): the restart_count the sidecar scrapes
        # must count that death too, not wait for a later child's beat
        self._publish_counters(through_ts=self._counters_through_ts)

    def _publish_counters(self, through_ts: float,
                          completed: bool | None = None) -> None:
        from ..obs.export.sidecar import publish_counters

        extra: dict = {"restart_count": len(self.restarts)}
        if completed is not None:
            extra["completed"] = completed
        try:
            publish_counters(self.ckpt_root, self._counters_total,
                             through_ts, extra=extra,
                             hists=self._hists_total or None)
            self._publish_error = None
        except OSError as e:
            # best-effort observability: a full disk must not become a
            # supervision failure — but the evidence rides the manifest
            self._publish_error = repr(e)

    def _write_provenance(self, ok: bool) -> None:
        """Merge restart provenance into the run manifest (atomic write —
        readers racing a restart never see a partial file)."""
        # final published snapshot FIRST: scrapes after the run ends get
        # the full cross-restart totals + completion verdict, and a
        # publish failure here still lands in the manifest written below
        # ("the evidence rides the manifest" — it can't if the manifest
        # is already closed)
        self._publish_counters(through_ts=self._counters_through_ts,
                               completed=ok)
        data: dict = {}
        try:
            with open(self.manifest_path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}  # child died before writing one: provenance-only file
        data["resilience"] = {
            "target_generation": self.target_generation,
            "completed": ok,
            "restart_count": len(self.restarts),
            "restarts": self.restarts,
            "counters": dict(self._counters_total),
        }
        if self._publish_error:
            data["resilience"]["counters_publish_error"] = \
                self._publish_error
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, default=float)
        os.replace(tmp, self.manifest_path)
