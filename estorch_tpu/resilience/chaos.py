"""Deterministic chaos injection — the reproducible fault harness.

Every recovery path in this framework (worker respawn, same-generation
slice retry, update rejection, supervised restart from checkpoint) exists
because of a fault that is, in the wild, rare and unreproducible.  This
module makes each fault a *scheduled event*: a :class:`ChaosPlan` pins
faults to exact ``(generation, member/worker)`` points, so a test can
assert "a run that loses a worker at generation 5 ends bit-identical to
one that never did" instead of hoping a race fires.

Plan format (the ``ESTORCH_CHAOS`` environment variable carries it as
JSON, so forked/spawned children inherit the same plan):

    {"events": [
        {"kind": "kill_worker", "gen": 5, "worker": 0},
        {"kind": "nan_fitness", "gen": 9, "member": "all"},
        {"kind": "rollout_exc", "gen": 3, "member": [1, 4]},
        {"kind": "straggler",   "gen": 4, "member": 2, "sleep_s": 2.0,
         "jitter_s": 0.5},
        {"kind": "ckpt_crash",  "gen": 8},
        {"kind": "nan_update",  "gen": 2},
        {"kind": "die",         "gen": 12},
        {"kind": "wedge",       "gen": 2, "sleep_s": 300.0},
        {"kind": "straggle_host", "gen": 3, "host": 1, "sleep_s": 0.5,
         "jitter_s": 0.2},
        {"kind": "kill_host",     "gen": 6, "host": 1},
        {"kind": "kill_replica",  "at_s": 2.0, "replica": 1},
        {"kind": "wedge_replica", "at_s": 4.0, "replica": 0}
     ],
     "ledger": "/tmp/run/chaos_ledger"}

Event kinds and their injection points:

==============  =====================================================
kind            fires where
==============  =====================================================
rollout_exc     inside the member rollout (host thread + fork workers)
straggler       same place, as a ``sleep_s`` stall; an optional
                ``jitter_s`` adds a deterministic per-event spread in
                [0, jitter_s) (seeded by the event id — the same plan
                always stalls by the same amounts), so a plan can model
                a slow-tail DISTRIBUTION instead of one fixed delay
nan_fitness     on the gathered fitness vector (host/pooled engines)
kill_worker     SIGKILL of a ProcessPool worker at the generation start
nan_update      poisons the update direction (host engine) — exercises
                the post-update anomaly guard
ckpt_crash      raises mid-``save_checkpoint``, after the sidecar files
                but before the Orbax payload finalizes
die             SIGKILL of the WHOLE process (resilience.run_resilient
                loop head) — exercises the Supervisor restart path
wedge           a long un-heartbeated sleep at the same point —
                exercises the Supervisor's staleness watchdog
straggle_host   in an elastic multi-host run (parallel/elastic.py):
                host ``host`` sleeps before evaluating the dispatch
                whose id equals ``gen`` — the whole HOST is slow, the
                hazard the async host fold exists to absorb; sleep_s/
                jitter_s as for ``straggler``
kill_host       SIGKILL of elastic host ``host`` at dispatch ``gen``
                (in a thread-simulated host the worker dies abruptly
                instead) — exercises loss accounting + membership
                leave + the coordinator's replacement dispatches
kill_replica    SIGKILL of serving replica ``replica`` (fleet monitor,
                serve/fleet.py) — exercises router failover + respawn
wedge_replica   SIGSTOP of serving replica ``replica`` — alive process,
                silent socket: exercises breaker-open-on-timeout and
                the fleet's wedge-kill escalation
==============  =====================================================

Training events key on ``gen`` (generation-granular determinism); the
two serving events key on ``at_s`` — seconds since the fleet armed the
plan — because a serving process has no generation clock.  Both share
the same once-semantics ledger, so a respawned fleet does not replay
the kill forever.

Events fire **once**.  In-process that is an in-memory set; across
process restarts (the Supervisor respawning a SIGKILLed child must not
replay the kill forever) the plan's optional ``ledger`` file records
fired event ids append-only, so a resumed run skips them.  The hook
functions below are no-ops costing one environment lookup when
``ESTORCH_CHAOS`` is unset — they are safe on hot paths.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

CHAOS_ENV = "ESTORCH_CHAOS"

KINDS = (
    "rollout_exc",
    "straggler",
    "nan_fitness",
    "kill_worker",
    "nan_update",
    "ckpt_crash",
    "die",
    "wedge",
    "straggle_host",
    "kill_host",
    "kill_replica",
    "wedge_replica",
)

# serving-fleet events are wall-clock scheduled ("at_s" from plan arming)
# instead of generation-keyed — a serving process has no generation clock
SERVE_KINDS = ("kill_replica", "wedge_replica")


class ChaosError(RuntimeError):
    """An injected fault (rollout exception, checkpoint-write crash)."""


class ChaosPlan:
    """A deterministic, replayable schedule of faults.

    ``events`` is a list of dicts (see module docstring for the schema);
    each gets a stable ``id`` (its index) used for once-semantics.
    """

    def __init__(self, events, ledger: str | None = None):
        self._events: list[dict] = []
        self._by_gen: dict[int, list[dict]] = {}
        self._serve_events: list[dict] = []
        for i, ev in enumerate(events):
            kind = ev.get("kind")
            if kind not in KINDS:
                raise ValueError(
                    f"unknown chaos event kind {kind!r} (event {i}); "
                    f"known: {', '.join(KINDS)}"
                )
            ev = dict(ev, id=i)
            if kind in SERVE_KINDS:
                if "at_s" not in ev:
                    raise ValueError(
                        f"chaos event {i} ({kind}) has no 'at_s' — serve "
                        "events are wall-clock scheduled")
                self._serve_events.append(ev)
            else:
                if "gen" not in ev:
                    raise ValueError(
                        f"chaos event {i} ({kind}) has no 'gen'")
                self._by_gen.setdefault(int(ev["gen"]), []).append(ev)
            self._events.append(ev)
        self.ledger = ledger
        self._fired: set[int] = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------ construct

    @classmethod
    def parse(cls, text: str) -> "ChaosPlan":
        data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError("chaos plan must be a JSON object")
        return cls(data.get("events", []), ledger=data.get("ledger"))

    @classmethod
    def from_env(cls) -> "ChaosPlan | None":
        text = os.environ.get(CHAOS_ENV)
        return cls.parse(text) if text else None

    @classmethod
    def generate(
        cls,
        seed: int,
        n_generations: int,
        ledger: str | None = None,
        kill_every: int = 0,
        n_workers: int = 1,
        p_rollout_exc: float = 0.0,
        p_nan_burst: float = 0.0,
        population_size: int = 1,
        straggler_every: int = 0,
        straggler_sleep_s: float = 1.0,
        straggler_jitter_s: float = 0.0,
        straggle_host_every: int = 0,
        straggle_host: int = 0,
        straggle_host_sleep_s: float = 1.0,
        straggle_host_jitter_s: float = 0.0,
    ) -> "ChaosPlan":
        """Seeded random plan — deterministic in ``seed``: the same seed
        always schedules the same faults at the same points.

        ``straggler_every`` schedules one straggler stall every K
        generations on a random member, sleeping ``straggler_sleep_s``
        plus a deterministic jitter in [0, ``straggler_jitter_s``) —
        the slow-tail workload the async scheduler's A/B (``bench.py
        --async-ab``) and the mixed straggler+kill chaos plan exercise.
        """
        import numpy as np

        rng = np.random.default_rng(seed)
        events: list[dict] = []
        for g in range(1, n_generations + 1):
            if kill_every and g % kill_every == 0:
                events.append(
                    {"kind": "kill_worker", "gen": g,
                     "worker": int(rng.integers(n_workers))}
                )
            if straggler_every and g % straggler_every == 0:
                ev = {"kind": "straggler", "gen": g,
                      "member": int(rng.integers(population_size)),
                      "sleep_s": float(straggler_sleep_s)}
                if straggler_jitter_s > 0.0:
                    ev["jitter_s"] = float(straggler_jitter_s)
                events.append(ev)
            if straggle_host_every and g % straggle_host_every == 0:
                # one declared slow HOST (elastic multi-host / sync
                # multihost A/B — bench.py --elastic-ab): the same plan
                # stalls the same host by the same amounts in both legs
                ev = {"kind": "straggle_host", "gen": g,
                      "host": int(straggle_host),
                      "sleep_s": float(straggle_host_sleep_s)}
                if straggle_host_jitter_s > 0.0:
                    ev["jitter_s"] = float(straggle_host_jitter_s)
                events.append(ev)
            if p_rollout_exc and rng.random() < p_rollout_exc:
                events.append(
                    {"kind": "rollout_exc", "gen": g,
                     "member": int(rng.integers(population_size))}
                )
            if p_nan_burst and rng.random() < p_nan_burst:
                events.append({"kind": "nan_fitness", "gen": g,
                               "member": "all"})
        return cls(events, ledger=ledger)

    # -------------------------------------------------------------- inspect

    @property
    def events(self) -> list[dict]:
        return [dict(ev) for ev in self._events]

    def to_json(self) -> str:
        """The env-var form (``os.environ[CHAOS_ENV] = plan.to_json()``)."""
        stripped = [{k: v for k, v in ev.items() if k != "id"}
                    for ev in self._events]
        data: dict = {"events": stripped}
        if self.ledger:
            data["ledger"] = self.ledger
        return json.dumps(data)

    def events_at(self, generation: int, kind: str | None = None) -> list[dict]:
        evs = self._by_gen.get(int(generation), [])
        return [ev for ev in evs if kind is None or ev["kind"] == kind]

    def serve_events_due(self, elapsed_s: float) -> list[dict]:
        """Serve events (``kill_replica``/``wedge_replica``) whose
        ``at_s`` has passed, each CLAIMED through :meth:`fire` (once per
        event id across every process sharing the ledger).  The caller
        (the fleet monitor) owns the actual kill/SIGSTOP — this module
        holds no process table."""
        due = []
        for ev in self._serve_events:
            if float(ev["at_s"]) <= float(elapsed_s) and self.fire(ev):
                due.append(dict(ev))
        return due

    # ---------------------------------------------------------------- fire

    def fire(self, event: dict) -> bool:
        """Claim ``event``: True exactly once per event id, across every
        process sharing the plan's ledger file (best-effort: the append
        happens-before the fault's observable effect, so a retry or a
        restarted process that reads the ledger sees it)."""
        eid = int(event["id"])
        with self._lock:
            if eid in self._fired:
                return False
            if self.ledger:
                fired = self._read_ledger()
                self._fired |= fired
                if eid in fired:
                    return False
                # O_APPEND keeps small same-file writes from interleaving
                with open(self.ledger, "a") as f:
                    f.write(f"{eid}\n")
                    f.flush()
            self._fired.add(eid)
            return True

    def _read_ledger(self) -> set[int]:
        try:
            with open(self.ledger) as f:
                return {int(line) for line in f if line.strip()}
        except (OSError, ValueError):
            return set()


# ---------------------------------------------------------------------
# process-wide plan (env-driven, inherited by forked/spawned children)
# ---------------------------------------------------------------------

_cache_text: str | None = None
_cache_plan: ChaosPlan | None = None


def active_plan() -> ChaosPlan | None:
    """The ``ESTORCH_CHAOS`` plan, parsed once per distinct env value.
    None (the overwhelmingly common case) costs one dict lookup."""
    global _cache_text, _cache_plan
    text = os.environ.get(CHAOS_ENV)
    if not text:
        return None
    if text != _cache_text:
        _cache_text, _cache_plan = text, ChaosPlan.parse(text)
    return _cache_plan


def reset_cache() -> None:
    """Drop the cached plan (tests that reuse identical plan text)."""
    global _cache_text, _cache_plan
    _cache_text = _cache_plan = None


def _matches_member(ev: dict, member: int) -> bool:
    m = ev.get("member", "all")
    if m == "all":
        return True
    if isinstance(m, (list, tuple)):
        return int(member) in [int(x) for x in m]
    return int(m) == int(member)


def straggler_sleep_s(ev: dict) -> float:
    """A straggler event's total stall: ``sleep_s`` plus a deterministic
    jitter drawn uniformly from [0, jitter_s) and seeded by the event id
    — the same plan always produces the same slow-tail spread, in every
    process that fires it (the async scheduler's A/B depends on the two
    legs seeing identical stalls)."""
    base = float(ev.get("sleep_s", 1.0))
    jitter = float(ev.get("jitter_s", 0.0))
    if jitter <= 0.0:
        return base
    import random

    return base + random.Random(int(ev["id"])).uniform(0.0, jitter)


# ------------------------------------------------------------------ hooks

def member_fault(generation, member: int) -> None:
    """Rollout-level faults for one (generation, member): ``straggler``
    sleeps, ``rollout_exc`` raises :class:`ChaosError` (the caller's
    normal failed-rollout handling marks the member NaN)."""
    plan = active_plan()
    if plan is None:
        return
    gen = int(generation)
    for ev in plan.events_at(gen, "straggler"):
        if _matches_member(ev, member) and plan.fire(ev):
            time.sleep(straggler_sleep_s(ev))
    for ev in plan.events_at(gen, "rollout_exc"):
        if _matches_member(ev, member) and plan.fire(ev):
            raise ChaosError(
                f"injected rollout exception (gen {gen}, member {member})"
            )


def _matches_host(ev: dict, host: int) -> bool:
    h = ev.get("host", "all")
    if h == "all":
        return True
    if isinstance(h, (list, tuple)):
        return int(host) in [int(x) for x in h]
    return int(h) == int(host)


def host_fault(dispatch, host: int) -> bool:
    """Host-granular faults for one (dispatch, host) in an elastic
    multi-host run (parallel/elastic.py) — and, symmetrically, for one
    (generation, process) in the synchronous multihost loop, where the
    SPMD barrier makes one host's stall everyone's stall (that contrast
    is exactly what ``bench.py --elastic-ab`` measures).

    ``straggle_host`` sleeps (sleep_s + the deterministic event-id-seeded
    jitter, like ``straggler``); returns True when a ``kill_host`` event
    fired — the CALLER owns the death (a subprocess host SIGKILLs itself,
    a thread-simulated host drops its coordinator connection), because
    only it knows what dying means in its medium."""
    plan = active_plan()
    if plan is None:
        return False
    gen = int(dispatch)
    for ev in plan.events_at(gen, "straggle_host"):
        if _matches_host(ev, host) and plan.fire(ev):
            time.sleep(straggler_sleep_s(ev))
    return any(
        plan.fire(ev) for ev in plan.events_at(gen, "kill_host")
        if _matches_host(ev, host)
    )


def mutate_fitness(generation, fitness):
    """``nan_fitness`` bursts: returns ``fitness`` with the event's
    members NaN'd (a copy — the input is never modified), or the input
    unchanged when no event fires."""
    plan = active_plan()
    if plan is None:
        return fitness
    import numpy as np

    out = fitness
    for ev in plan.events_at(int(generation), "nan_fitness"):
        if plan.fire(ev):
            out = np.array(out, np.float32, copy=True)
            m = ev.get("member", "all")
            if m == "all":
                out[:] = np.nan
            else:
                idx = np.asarray(m if isinstance(m, (list, tuple)) else [m],
                                 np.intp)
                out[idx] = np.nan
    return out


def kill_workers(generation, pids) -> list[int]:
    """``kill_worker``: SIGKILL the scheduled worker(s); returns the pids
    actually killed (the caller counts them)."""
    plan = active_plan()
    if plan is None:
        return []
    killed: list[int] = []
    for ev in plan.events_at(int(generation), "kill_worker"):
        w = int(ev.get("worker", 0))
        if 0 <= w < len(pids) and plan.fire(ev):
            os.kill(pids[w], signal.SIGKILL)
            killed.append(pids[w])
    return killed


def poison_update(generation) -> bool:
    """``nan_update``: True when this generation's update direction should
    be poisoned (exercises the post-update anomaly guard)."""
    plan = active_plan()
    if plan is None:
        return False
    return any(
        plan.fire(ev) for ev in plan.events_at(int(generation), "nan_update")
    )


def crash_checkpoint(generation) -> None:
    """``ckpt_crash``: raise mid-checkpoint-write (the caller has written
    the sidecar files but not finalized the state payload)."""
    plan = active_plan()
    if plan is None:
        return
    for ev in plan.events_at(int(generation), "ckpt_crash"):
        if plan.fire(ev):
            raise ChaosError(
                f"injected checkpoint-write crash (gen {int(generation)})"
            )


def process_kill(generation) -> None:
    """``die``: SIGKILL this whole process.  The ledger entry is written
    by ``fire`` BEFORE the kill, so a supervisor-restarted replay of the
    same generation does not die again."""
    plan = active_plan()
    if plan is None:
        return
    for ev in plan.events_at(int(generation), "die"):
        if plan.fire(ev):
            os.kill(os.getpid(), signal.SIGKILL)


def serve_faults(elapsed_s: float) -> list[dict]:
    """Due serving-fleet faults (``ESTORCH_CHAOS`` hook, one env lookup
    when unset).  Returns the claimed events; the fleet monitor maps
    ``replica`` indices to live processes and delivers the SIGKILL /
    SIGSTOP itself — declaring serving chaos in the same plan (and the
    same once-semantics ledger) as training chaos."""
    plan = active_plan()
    if plan is None:
        return []
    return plan.serve_events_due(elapsed_s)


def process_wedge(generation) -> None:
    """``wedge``: sleep without heartbeating — the supervisor's staleness
    watchdog must detect and kill this process."""
    plan = active_plan()
    if plan is None:
        return
    for ev in plan.events_at(int(generation), "wedge"):
        if plan.fire(ev):
            time.sleep(float(ev.get("sleep_s", 3600.0)))
