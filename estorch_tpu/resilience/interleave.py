"""Deterministic thread-interleaving harness: replayable race exposure.

esguard's lockset rules (R18–R22) say *where* a race may live; this
module is the other half of the loop — it makes the race *happen*, on
demand, the same way every time.  Real threads hit a data race once per
thousand runs and never under a debugger; here the OS scheduler is
taken out of the equation entirely:

* every worker runs as a real ``threading.Thread``, but a baton (one
  ``threading.Event`` per worker) ensures exactly ONE is ever runnable;
* a ``sys.settrace`` hook counts line events in the worker's own code
  and, on a schedule drawn from a seeded ``random.Random``, parks the
  current worker and hands the baton to another;
* because execution is fully serialized, the single shared RNG is only
  ever consumed by the baton holder — the decision sequence, and
  therefore the entire interleaving, is a pure function of the seed.

Same seed -> bit-identical schedule -> identical final state.  A seed
that loses an update is a *reproducer*: attach it to the bug report,
fix the lock, and the seed becomes a regression test
(``tests/test_resilience.py`` does exactly this).

:class:`CoopLock` is the fix side: a context-manager lock that blocks
by yielding through the scheduler instead of through the OS, so guarded
code stays deterministic AND correct under every seed.
"""

from __future__ import annotations

import random
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence


class DeadlockError(RuntimeError):
    """No runnable worker can make progress (all parked or spinning)."""


@dataclass(frozen=True)
class InterleaveResult:
    values: tuple[Any, ...]  # per-worker return values, in worker order
    schedule: tuple[int, ...]  # worker index at every baton handoff
    seed: int
    switches: int

    def replays(self, other: "InterleaveResult") -> bool:
        """Bit-identical replay: same seed produced the same handoffs."""
        return (self.seed == other.seed
                and self.schedule == other.schedule)


@dataclass
class _Worker:
    index: int
    fn: Callable[[], Any]
    baton: threading.Event = field(default_factory=threading.Event)
    thread: threading.Thread | None = None
    value: Any = None
    error: BaseException | None = None
    done: bool = False


class Interleaver:
    """Run ``fns`` as serialized threads under a seeded forced-yield
    scheduler.  ``granularity`` bounds how many traced lines a worker
    may run between handoff decisions (the RNG draws 1..granularity);
    ``max_steps`` bounds total handoffs so a livelock fails fast
    instead of hanging the test suite."""

    def __init__(self, fns: Sequence[Callable[[], Any]], seed: int = 0,
                 granularity: int = 3, max_steps: int = 100_000,
                 timeout: float = 30.0):
        if not fns:
            raise ValueError("need at least one worker")
        self._workers = [_Worker(i, fn) for i, fn in enumerate(fns)]
        self._seed = seed
        self._rng = random.Random(seed)
        self._granularity = max(1, granularity)
        self._max_steps = max_steps
        self._timeout = timeout
        self._schedule: list[int] = []
        self._countdown = 0
        self._local = threading.local()
        # frames from these files are scheduler/runtime plumbing, not
        # worker code — tracing them would make the schedule depend on
        # stdlib internals instead of the code under test
        self._skip_files = {__file__, threading.__file__, random.__file__}

    # -- scheduling core ----------------------------------------------

    def _runnable(self, exclude: int | None = None) -> list[_Worker]:
        return [w for w in self._workers
                if not w.done and w.index != exclude]

    def _handoff(self, me: _Worker, exclude_self: bool) -> None:
        """Park ``me`` and wake an RNG-chosen runnable worker.  Called
        only while holding the baton, so RNG access is serialized."""
        if len(self._schedule) >= self._max_steps:
            raise DeadlockError(
                f"no progress after {self._max_steps} handoffs "
                f"(seed={self._seed}) — livelock or runaway loop")
        candidates = self._runnable(me.index if exclude_self else None)
        if not candidates:
            if exclude_self:
                raise DeadlockError(
                    f"worker {me.index} is blocked and no other worker "
                    f"is runnable (seed={self._seed})")
            return  # alone: keep running
        target = self._rng.choice(candidates)
        self._schedule.append(target.index)
        me.baton.clear()
        target.baton.set()
        if not me.baton.wait(self._timeout):
            raise DeadlockError(
                f"worker {me.index} never got the baton back within "
                f"{self._timeout}s (seed={self._seed})")

    def _maybe_switch(self, me: _Worker) -> None:
        self._countdown -= 1
        if self._countdown <= 0:
            self._countdown = self._rng.randint(1, self._granularity)
            self._handoff(me, exclude_self=False)

    def yield_now(self) -> None:
        """Give the baton away unconditionally (CoopLock's spin step)."""
        self._handoff(self._me(), exclude_self=True)

    def _me(self) -> _Worker:
        return self._local.worker

    # -- tracing ------------------------------------------------------

    def _trace(self, frame, event, arg):
        if frame.f_code.co_filename in self._skip_files:
            return None
        return self._trace_lines

    def _trace_lines(self, frame, event, arg):
        if event == "line":
            self._maybe_switch(self._me())
        return self._trace_lines

    # -- worker lifecycle ---------------------------------------------

    def _run_worker(self, w: _Worker) -> None:
        self._local.worker = w
        w.baton.wait(self._timeout)
        sys.settrace(self._trace)
        try:
            w.value = w.fn()
        except BaseException as e:  # re-raised in run()
            w.error = e
        finally:
            sys.settrace(None)
            w.done = True
            # pass the baton on without expecting it back
            candidates = self._runnable()
            if candidates:
                target = self._rng.choice(candidates)
                self._schedule.append(target.index)
                target.baton.set()

    def run(self) -> InterleaveResult:
        for w in self._workers:
            w.thread = threading.Thread(
                target=self._run_worker, args=(w,),
                name=f"interleave-{w.index}", daemon=True)
            w.thread.start()
        self._countdown = self._rng.randint(1, self._granularity)
        self._workers[0].baton.set()
        for w in self._workers:
            w.thread.join(self._timeout)
            if w.thread.is_alive():
                raise DeadlockError(
                    f"worker {w.index} still running after "
                    f"{self._timeout}s (seed={self._seed})")
        for w in self._workers:
            if w.error is not None:
                raise w.error
        return InterleaveResult(
            values=tuple(w.value for w in self._workers),
            schedule=tuple(self._schedule), seed=self._seed,
            switches=len(self._schedule))


class CoopLock:
    """Mutual exclusion that cooperates with the interleaver: a blocked
    acquire yields through the scheduler (staying deterministic) rather
    than parking in the OS.  Usable only inside interleaved workers —
    which is the point: it exists so a racy fixture can be re-run with
    the SAME seed after adding locking and observe the race gone."""

    def __init__(self, interleaver: Interleaver):
        self._interleaver = interleaver
        self._owner: int | None = None

    def acquire(self) -> None:
        me = self._interleaver._me().index
        while self._owner is not None:
            self._interleaver.yield_now()
        self._owner = me

    def release(self) -> None:
        me = self._interleaver._me().index
        if self._owner != me:
            raise RuntimeError(
                f"worker {me} releasing a lock owned by {self._owner}")
        self._owner = None

    def __enter__(self) -> "CoopLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def run_interleaved(fns: Sequence[Callable[[], Any]], seed: int = 0,
                    granularity: int = 3,
                    max_steps: int = 100_000) -> InterleaveResult:
    """One-shot helper: schedule ``fns`` under ``seed`` and return the
    result.  Build the workers fresh per call — shared state captured in
    their closures is exactly what the harness is for."""
    return Interleaver(fns, seed=seed, granularity=granularity,
                       max_steps=max_steps).run()
