"""Environment doctor: diagnose the accelerator and runtime before training.

The device backend behind JAX can WEDGE (observed repeatedly with the
tunneled single-chip setup this framework is developed against): every
device-touching call — sometimes including bare ``jax.devices()`` — hangs
indefinitely, with no exception to catch.  A user whose training script
"does nothing" has no way to tell a slow first compile from a dead
accelerator.  This module probes the backend from a SUBPROCESS with a hard
timeout (the only reliable wedge detector: an in-process call cannot be
timed out once it enters the runtime), then reports everything else that
commonly decides whether a config can run: the C++ env pool, optional
sim/rollout dependencies, and the virtual-CPU-mesh fallback.

Reference has no counterpart (estorch is pure CPU python); this is the
aux-subsystem "failure detection" obligation (SURVEY.md §5) applied to the
accelerator itself.

Use:  python -m estorch_tpu.doctor [--timeout S] [--run-dir DIR]
      [--resilience-probe]
"""

from __future__ import annotations

import importlib.util
import json
import subprocess
import sys

_PROBE = """
import jax
ds = jax.devices()
print("PROBE_OK", ds[0].platform, len(ds))
"""


def probe_device(timeout_s: float = 45.0) -> dict:
    """Probe the default JAX backend in a child process with a hard timeout.

    Returns {"status": "healthy"|"wedged"|"error", ...detail}.  "wedged"
    means the child neither finished nor failed within ``timeout_s`` —
    the signature of a hung device runtime (vs a clean init error, which
    returns fast with stderr).
    """
    import tempfile

    # capture into FILES, not pipes: whatever the child wrote before
    # hanging must survive the kill (PIPE partials are lost on timeout),
    # and a file needs no reader thread that could itself block
    with tempfile.TemporaryFile("w+") as fo, \
            tempfile.TemporaryFile("w+") as fe:
        proc = subprocess.Popen([sys.executable, "-c", _PROBE],
                                stdout=fo, stderr=fe, text=True)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            unreapable = False
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                # child stuck in uninterruptible sleep (D state — a wedged
                # device driver can do this): SIGKILL cannot reap it, and
                # the doctor must not hang on the very wedge it detects —
                # report the un-reapable child, it is itself a finding
                unreapable = True
            fe.seek(0)
            out = {"status": "wedged", "timeout_s": timeout_s,
                   "stderr_tail": fe.read()[-500:]}
            if unreapable:
                out["unreapable_child"] = True
            return out
        fo.seek(0), fe.seek(0)
        out, err = fo.read(), fe.read()
    for line in out.splitlines():
        if line.startswith("PROBE_OK"):
            _, platform, n = line.split()
            return {"status": "healthy", "platform": platform,
                    "n_devices": int(n)}
    return {"status": "error", "returncode": proc.returncode,
            "stderr_tail": err[-500:]}


# staged probe: each marker proves one layer of the device path alive,
# so a timeout's LAST marker names the layer that wedged.  flush=True on
# every print — the parent reads the file after killing the child, and
# an unflushed marker would misclassify the hang one stage early.
_STAGED_PROBE = """
import sys
print("PROBE_START", flush=True)
import jax
print("PROBE_JAX_OK", flush=True)
ds = jax.devices()
print("PROBE_DEVICES_OK", ds[0].platform, len(ds), flush=True)
import jax.numpy as jnp
fn = jax.jit(lambda x: (x @ x).sum())
x = jnp.ones((128, 128), jnp.float32)
compiled = fn.lower(x).compile()
print("PROBE_COMPILE_OK", flush=True)
compiled(x).block_until_ready()
print("PROBE_EXEC_OK", flush=True)
"""

# ordered (marker, hang-reason-when-absent) pairs: the first missing
# marker after a timeout names the stage that wedged
_PROBE_STAGES = (
    ("PROBE_JAX_OK", "init-hang"),
    ("PROBE_DEVICES_OK", "init-hang"),
    ("PROBE_COMPILE_OK", "compile-hang"),
    ("PROBE_EXEC_OK", "exec-hang"),
)


def classify_device_probe(out: str, timed_out: bool, returncode
                          ) -> tuple[str, str | None]:
    """(status, reason) from a staged probe's output — pure so the
    reason-code taxonomy is unit-testable without wedging anything.

    Reasons (docs/observability.md "Profiling"): ``no-device`` (the
    runtime answered fast: no such backend), ``init-hang`` /
    ``compile-hang`` / ``exec-hang`` (the layer that went silent),
    ``error`` (failed fast after device init — not a wedge, read the
    stderr)."""
    markers = {ln.split()[0] for ln in out.splitlines() if ln.strip()}
    if "PROBE_EXEC_OK" in markers and not timed_out and returncode == 0:
        return "ok", None
    if timed_out:
        for marker, reason in _PROBE_STAGES:
            if marker not in markers:
                return "failed", reason
        return "failed", "exec-hang"  # all markers but the child lived on
    if "PROBE_DEVICES_OK" not in markers:
        # failed fast before any device existed: the backend said no
        # (missing runtime, no chip, refused platform) — not a wedge
        return "failed", "no-device"
    return "failed", "error"


def _run_staged_probe(script: str, timeout_s: float, env: dict) -> dict:
    """Run a marker-printing probe script in a killed-on-timeout child.

    The ONE subprocess harness every staged probe shares (device + mesh):
    file-captured stdout/stderr (a pipe's partials die with the kill; a
    file needs no reader thread that could itself block), hard timeout,
    SIGKILL + bounded reap with the un-reapable (D-state) child reported
    as a finding of its own.  Returns {out, err, timed_out, returncode,
    unreapable, elapsed_s} for the caller's classifier to shape.
    """
    import tempfile
    import time

    t0 = time.perf_counter()
    with tempfile.TemporaryFile("w+") as fo, \
            tempfile.TemporaryFile("w+") as fe:
        proc = subprocess.Popen([sys.executable, "-c", script],
                                stdout=fo, stderr=fe, text=True, env=env)
        timed_out = False
        unreapable = False
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            timed_out = True
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                unreapable = True  # D-state child: itself a finding
        fo.seek(0), fe.seek(0)
        out_text, err_text = fo.read(), fe.read()
    return {
        "out": out_text, "err": err_text, "timed_out": timed_out,
        "returncode": proc.returncode, "unreapable": unreapable,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }


def check_device(timeout_s: float = 20.0,
                 platform: str | None = None) -> dict:
    """Prove the device path alive-or-wedged in SECONDS with a typed
    reason, replacing the old discover-by-480s-stage-timeout: a staged
    subprocess runs import → device init → XLA compile → execute, each
    stage leaving a marker, and a hang is classified by the first marker
    missing when the timeout kills it.

    ``platform`` pins ``JAX_PLATFORMS`` in the child (``"tpu"`` asks
    "is the CHIP path alive" even where the default backend would
    quietly fall back).  Deliberately stdlib-only at module scope so
    bench.py can file-load this module jax-free (the stage-protocol
    discipline).
    """
    import os

    env = dict(os.environ)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    run = _run_staged_probe(_STAGED_PROBE, timeout_s, env)
    status, reason = classify_device_probe(run["out"], run["timed_out"],
                                           run["returncode"])
    result: dict = {
        "status": status,
        "elapsed_s": run["elapsed_s"],
        "timeout_s": timeout_s,
    }
    if platform is not None:
        result["requested_platform"] = platform
    for ln in run["out"].splitlines():
        if ln.startswith("PROBE_DEVICES_OK"):
            _, plat, n = ln.split()
            result["platform"] = plat
            result["n_devices"] = int(n)
    if reason is not None:
        result["reason"] = reason
        result["stderr_tail"] = run["err"][-500:]
    if run["unreapable"]:
        result["unreapable_child"] = True
    return result


# mesh probe: proves the param-sharded path (parallel/sharded.py,
# docs/sharding.md) can run on THIS host's virtual CPU mesh — 2-D mesh
# build, partition-rule resolution over a dummy tree, and one sharded
# dummy program (donated params operand, explicit out_shardings)
# compiled AND executed.  Forced onto the CPU backend in the child so
# the probe cannot touch (or wedge on) a real device runtime.
_MESH_PROBE = """
import sys
print("MESH_START", flush=True)
from estorch_tpu.utils import force_cpu_backend
force_cpu_backend(8)
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from estorch_tpu.parallel.mesh import (DEFAULT_PARTITION_RULES,
                                       hyperscale_mesh,
                                       match_partition_rules)
mesh = hyperscale_mesh(2, 4)
print("MESH_BUILD_OK", mesh.devices.size, flush=True)
tree = {"dense": {"kernel": jnp.zeros((8, 16)), "bias": jnp.zeros((16,))}}
sh = match_partition_rules(DEFAULT_PARTITION_RULES, tree, mesh)
params = jax.device_put(tree, sh)
print("MESH_RULES_OK", flush=True)
fn = jax.jit(
    lambda p: jax.tree_util.tree_map(lambda x: x * 2.0, p),
    donate_argnums=(0,), in_shardings=(sh,), out_shardings=sh)
compiled = fn.lower(params).compile()
print("MESH_COMPILE_OK", flush=True)
out = compiled(params)
jax.block_until_ready(out)
print("MESH_EXEC_OK", flush=True)
"""

_MESH_STAGES = (
    ("MESH_BUILD_OK", "mesh-build"),
    ("MESH_RULES_OK", "partition-rules"),
    ("MESH_COMPILE_OK", "sharded-compile"),
    ("MESH_EXEC_OK", "sharded-exec"),
)


def classify_mesh_probe(out: str, timed_out: bool, returncode
                        ) -> tuple[str, str | None]:
    """(status, failed-stage) from the mesh probe's markers — pure, so
    the taxonomy is unit-testable without a mesh."""
    markers = {ln.split()[0] for ln in out.splitlines() if ln.strip()}
    if "MESH_EXEC_OK" in markers and not timed_out and returncode == 0:
        return "ok", None
    for marker, stage in _MESH_STAGES:
        if marker not in markers:
            return "failed", stage
    return "failed", "sharded-exec"


def check_mesh(timeout_s: float = 90.0) -> dict:
    """Can the param-sharded engine run here?  A staged subprocess builds
    the 2-D virtual-CPU mesh, resolves the default partition rules, and
    compiles+executes one donated sharded program — the first missing
    marker names the failing layer (jax too old for NamedSharding jit,
    broken virtual-device config, GSPMD lowering failure, ...)."""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    run = _run_staged_probe(_MESH_PROBE, timeout_s, env)
    status, stage = classify_mesh_probe(run["out"], run["timed_out"],
                                        run["returncode"])
    result: dict = {
        "status": status,
        "elapsed_s": run["elapsed_s"],
        "timeout_s": timeout_s,
    }
    if status != "ok":
        result["failed_stage"] = stage
        result["timed_out"] = run["timed_out"]
        result["stderr_tail"] = run["err"][-500:]
    if run["unreapable"]:
        result["unreapable_child"] = True
    return result


# scenario probe: proves the scenario suite (estorch_tpu/scenarios,
# docs/scenarios.md) works here — (1) the distribution draw is
# deterministic in (seed, variant) and stacks host-side, (2) one tiny
# jitted rollout evaluates episodes across 3 variants with the drawn
# constants as TRACED OPERANDS (finite fitness, variant ids in range).
# Forced onto the CPU backend in the child so the probe cannot touch
# (or wedge on) a real device runtime.
_SCENARIO_PROBE = """
import sys
print("SCEN_START", flush=True)
from estorch_tpu.utils import force_cpu_backend
force_cpu_backend(2)
import jax
import jax.numpy as jnp
import numpy as np
from estorch_tpu.envs.pendulum import Pendulum
from estorch_tpu.envs.rollout import make_rollout
from estorch_tpu.scenarios import ScenarioEnv, default_distribution
dist = default_distribution(Pendulum(), n_variants=3, spread=0.2, seed=0)
a = dist.draw_concrete(1)
b = dist.draw_concrete(1)
assert a == b, ("non-deterministic draw", a, b)
stacked = dist.draw_all()
for name in dist.names:
    assert np.asarray(stacked[name]).shape == (3,), name
print("SCEN_DRAW_OK", flush=True)
env = ScenarioEnv(Pendulum(), dist)
rollout = jax.jit(jax.vmap(
    make_rollout(env, lambda p, obs: jnp.tanh(obs @ p), 5),
    in_axes=(None, 0)))
res = rollout(jnp.zeros((3, 1)),
              jax.random.split(jax.random.PRNGKey(0), 6))
f = np.asarray(res.total_reward)
v = np.rint(np.asarray(res.bc)[:, -1]).astype(int)
assert np.isfinite(f).all(), f
assert set(v) <= {0, 1, 2}, v
print("SCEN_ROLLOUT_OK", flush=True)
"""

_SCENARIO_STAGES = (
    ("SCEN_DRAW_OK", "draw-determinism"),
    ("SCEN_ROLLOUT_OK", "traced-rollout"),
)


def classify_scenario_probe(out: str, timed_out: bool, returncode
                            ) -> tuple[str, str | None]:
    """(status, failed-stage) from the scenario probe's markers — pure,
    so the taxonomy is unit-testable without running the probe."""
    markers = {ln.split()[0] for ln in out.splitlines() if ln.strip()}
    if "SCEN_ROLLOUT_OK" in markers and not timed_out and returncode == 0:
        return "ok", None
    for marker, stage in _SCENARIO_STAGES:
        if marker not in markers:
            return "failed", stage
    return "failed", "traced-rollout"


def check_scenarios(timeout_s: float = 90.0) -> dict:
    """Can the scenario suite run here?  Findings, never tracebacks: a
    failure names the stage (draw-determinism vs traced-rollout) with a
    stderr tail, and a hung child is killed at the timeout."""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    run = _run_staged_probe(_SCENARIO_PROBE, timeout_s, env)
    status, stage = classify_scenario_probe(run["out"], run["timed_out"],
                                            run["returncode"])
    result: dict = {
        "status": status,
        "elapsed_s": run["elapsed_s"],
        "timeout_s": timeout_s,
    }
    if status != "ok":
        result["failed_stage"] = stage
        result["timed_out"] = run["timed_out"]
        result["stderr_tail"] = run["err"][-500:]
    if run["unreapable"]:
        result["unreapable_child"] = True
    return result


# elastic probe: proves the multi-host layers (parallel/multihost.py +
# parallel/elastic.py, docs/multihost.md) can run here — staged:
# (1) jax.distributed bring-up of TWO real OS processes over loopback
#     (Gloo CPU collectives, timed barrier),
# (2) the global population mesh spanning both processes' devices,
# (3) one cross-process psum through that mesh,
# (4) the elastic coordinator's TCP round-trip (join → sync → center →
#     dispatch → result), which is deliberately jax-free.
# The parent orchestrates, prints one marker per stage, and bounds every
# wait; the first missing marker names the failing layer.
_ELASTIC_WORKER = """
import sys
pid, port, out_path = int(sys.argv[1]), sys.argv[2], sys.argv[3]
from estorch_tpu.utils.backend import force_cpu_backend
force_cpu_backend(2)
import estorch_tpu.parallel.multihost as mh
f = open(out_path, "w", buffering=1)
mh.initialize("127.0.0.1:" + port, 2, pid, timeout_s=45,
              cpu_collectives=True)
print("WINIT", file=f)
import jax
mesh = mh.global_population_mesh()
print("WMESH", mesh.devices.size, file=f)
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from estorch_tpu.utils.backend import shard_map
fn = jax.jit(shard_map(lambda x: jax.lax.psum(x, "pop"), mesh,
                       (P(),), P(), check_vma=False))
out = fn(jnp.ones(4))
print("WPSUM", float(out[0]), file=f)
"""

_ELASTIC_PROBE = """
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

print("ELASTIC_START", flush=True)
workdir = tempfile.mkdtemp(prefix="estorch_elastic_probe_")
worker_py = os.path.join(workdir, "worker.py")
with open(worker_py, "w") as f:
    f.write(%r)
with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
marks = [os.path.join(workdir, "w%%d.txt" %% i) for i in range(2)]
env = dict(os.environ, JAX_PLATFORMS="cpu")
procs = [subprocess.Popen([sys.executable, worker_py, str(i), str(port),
                           marks[i]], env=env,
                          stdout=subprocess.DEVNULL,
                          stderr=subprocess.PIPE, text=True)
         for i in range(2)]

def both_have(marker, deadline):
    while time.monotonic() < deadline:
        got = 0
        for m in marks:
            try:
                with open(m) as f:
                    if any(ln.startswith(marker) for ln in f):
                        got += 1
            except OSError:
                pass
        if got == 2:
            return True
        if any(p.poll() not in (None, 0) for p in procs):
            return False
        time.sleep(0.1)
    return False

deadline = time.monotonic() + 70
try:
    if not both_have("WINIT", deadline):
        raise SystemExit(3)
    print("ELASTIC_INIT_OK", flush=True)
    if not both_have("WMESH", deadline):
        raise SystemExit(3)
    print("ELASTIC_MESH_OK", flush=True)
    if not both_have("WPSUM", deadline):
        raise SystemExit(3)
    print("ELASTIC_PSUM_OK", flush=True)
finally:
    for p in procs:
        if p.poll() is None:
            p.kill()
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        if p.returncode not in (None, 0):
            sys.stderr.write((p.stderr.read() or "")[-800:])

# stage 4: coordinator round-trip — jax-free by construction
import numpy as np
from estorch_tpu.parallel.elastic import (ElasticCoordinator, recv_msg,
                                          send_msg)
coord = ElasticCoordinator(join_grace_s=5.0)
cl = socket.create_connection(coord.address, timeout=5)
cl.settimeout(0.05)
send_msg(cl, {"t": "join", "host": 0})
deadline = time.monotonic() + 10

def next_msg():
    while time.monotonic() < deadline:
        got = recv_msg(cl, 0.05)
        if got is not None:
            return got
    raise SystemExit(4)

header, arrays = next_msg()
assert header["t"] == "sync", header
coord.push_center(0, np.arange(4, dtype=np.float32), 0.1)
assert coord.dispatch(0, 0) == 0
seen = set()
while {"center", "dispatch"} - seen:
    header, arrays = next_msg()
    seen.add(header["t"])
    if header["t"] == "center":
        assert arrays["center"].tolist() == [0.0, 1.0, 2.0, 3.0]
send_msg(cl, {"t": "result", "dispatch": 0, "steps": 3, "eval_s": 0.01},
         {"fitness": np.ones(4, np.float32)})
got = ([], [], [])
while not got[0] and time.monotonic() < deadline:
    got = coord.poll(0.2)
assert got[0] and got[0][0]["dispatch"] == 0, got
coord.close()
cl.close()
print("ELASTIC_COORD_OK", flush=True)
""" % (_ELASTIC_WORKER,)

_ELASTIC_STAGES = (
    ("ELASTIC_INIT_OK", "distributed-init"),
    ("ELASTIC_MESH_OK", "mesh-build"),
    ("ELASTIC_PSUM_OK", "cross-process-psum"),
    ("ELASTIC_COORD_OK", "coordinator-roundtrip"),
)


def classify_elastic_probe(out: str, timed_out: bool, returncode
                           ) -> tuple[str, str | None]:
    """(status, failed-stage) from the elastic probe's markers — pure,
    so the taxonomy is unit-testable without spawning a fleet."""
    markers = {ln.split()[0] for ln in out.splitlines() if ln.strip()}
    if "ELASTIC_COORD_OK" in markers and not timed_out and returncode == 0:
        return "ok", None
    for marker, stage in _ELASTIC_STAGES:
        if marker not in markers:
            return "failed", stage
    return "failed", "coordinator-roundtrip"


def check_elastic(timeout_s: float = 120.0) -> dict:
    """Can the elastic multi-host path run here?  Findings, never
    tracebacks: a staged subprocess brings up a REAL 2-process
    ``jax.distributed`` job over loopback, builds the cross-process
    mesh, runs one cross-process psum, then round-trips the elastic
    coordinator protocol — the first missing marker names the failing
    layer (no Gloo, broken loopback, protocol regression, ...)."""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the probe writes worker.py into a tempdir and runs it as a script,
    # so the worker's sys.path[0] is that tempdir — from a source
    # checkout (package not pip-installed) estorch_tpu is only
    # importable if we forward our own package root explicitly
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = (root + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else root)
    run = _run_staged_probe(_ELASTIC_PROBE, timeout_s, env)
    status, stage = classify_elastic_probe(run["out"], run["timed_out"],
                                           run["returncode"])
    result: dict = {
        "status": status,
        "elapsed_s": run["elapsed_s"],
        "timeout_s": timeout_s,
    }
    if status != "ok":
        result["failed_stage"] = stage
        result["timed_out"] = run["timed_out"]
        result["stderr_tail"] = run["err"][-500:]
    if run["unreapable"]:
        result["unreapable_child"] = True
    return result


def check_native_pool() -> dict:
    """Is the C++ env pool built/loadable, or will pools fall back to NumPy?"""
    try:
        from .envs import native_pool

        lib = native_pool._load_library()
        return {"cpp_pool": lib is not None}
    except Exception as e:  # diagnostic tool: never crash the report
        return {"cpp_pool": False, "error": repr(e)}


def check_optional_deps() -> dict:
    """Presence of the optional simulators/ROM stacks configs gate on."""
    out = {}
    for mod, why in (
        ("mujoco", "host/pooled MuJoCo configs"),
        ("mujoco.mjx", "device-native MuJoCo physics (in-tree fallback: envs/locomotion.py)"),
        ("ale_py", "real Atari (atari_frostbite); pong84 needs nothing"),
        ("gymnasium", "host/pooled gym envs"),
    ):
        try:
            found = importlib.util.find_spec(mod) is not None
        except Exception:
            # find_spec("pkg.sub") IMPORTS pkg first: a missing parent
            # raises ModuleNotFoundError, a broken native install can
            # raise ImportError/OSError — never crash the report (this is
            # the exact machine the doctor exists to diagnose)
            found = False
        out[mod] = {"available": found, "needed_for": why}
    return out


def check_host() -> dict:
    """Host-side facts that decide what parallelism can actually help:
    worker threads/processes cannot speed up a 1-core box (they time-slice
    it), and the persistent compile cache is what makes fresh processes
    cheap."""
    import os

    import jax

    from .utils.backend import default_compilation_cache_dir

    # report the LIVE cache dir when one is configured, else the default
    # enable_compilation_cache() would use
    cache_dir = (
        jax.config.jax_compilation_cache_dir
        or default_compilation_cache_dir()
    )
    cached = (
        len(os.listdir(cache_dir)) if os.path.isdir(cache_dir) else 0
    )
    return {
        "cpu_count": os.cpu_count(),
        "note": (
            "1 CPU: host worker threads/processes and virtual devices "
            "time-slice one core — correctness yes, speedup no"
            if (os.cpu_count() or 1) == 1 else
            f"{os.cpu_count()} CPUs available for host workers / env pools"
        ),
        "compile_cache_dir": cache_dir,
        "compile_cache_entries": cached,
        "compile_cache_hint": (
            "utils.enable_compilation_cache() makes every later process "
            "load compiled programs from disk (<1s) instead of paying the "
            "20-40s XLA compile"
        ),
    }


def check_obs(run_dir: str | None = None) -> dict:
    """Observability plumbing health (estorch_tpu/obs/):

    - is the trace/telemetry directory writable (JSONL sinks, jax
      profiler traces, heartbeat files all land there)?
    - is TensorBoard importable (TensorBoardSink), or is JsonlSink the
      only option?
    - export probe: spin up the Prometheus metrics sidecar
      (obs/export/sidecar.py) over a synthetic temp run-dir, scrape it
      over loopback, and validate the exposition PARSES — all stdlib, no
      jax touch, so "can this host be scraped" is answerable even from a
      wedged-runtime machine;
    - given a run dir: heartbeat freshness — the liveness verdict for a
      run that stopped printing ("wedged or dead" vs "slow but beating").
    """
    import os
    import tempfile

    from .obs.recorder import STALE_AFTER_S, read_heartbeat

    trace_dir = os.environ.get("ESTORCH_OBS_DIR") or tempfile.gettempdir()
    try:
        probe = os.path.join(trace_dir, f".obs_write_probe_{os.getpid()}")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
        writable = True
        err = None
    except OSError as e:  # diagnostic tool: never crash the report
        writable, err = False, repr(e)
    out: dict = {
        "trace_dir": {"path": trace_dir, "writable": writable,
                      **({"error": err} if err else {})},
    }
    try:
        tb = importlib.util.find_spec("torch.utils.tensorboard") is not None
    except Exception:
        tb = False
    out["tensorboard"] = {
        "available": tb,
        "needed_for": "obs.TensorBoardSink (obs.JsonlSink needs nothing)",
    }
    out["export"] = _export_probe()
    if run_dir is not None:
        hb_path = os.path.join(run_dir, "heartbeat.json")
        hb = read_heartbeat(hb_path)
        if hb is None:
            out["heartbeat"] = {
                "path": hb_path, "found": False,
                "hint": "no heartbeat — run never started telemetry, "
                        "finished long ago, or this is the wrong dir",
            }
        else:
            out["heartbeat"] = {
                "path": hb_path, "found": True,
                "age_s": round(hb["age_s"], 1),
                "stale": hb["age_s"] > STALE_AFTER_S,
                "phase": hb.get("phase"),
                "generation": hb.get("generation"),
            }
    return out


def _export_probe() -> dict:
    """Loopback-scrape the metrics sidecar against a synthetic temp
    run-dir and validate the exposition parses (obs/export/): the
    end-to-end proof that a supervised run on THIS host would be
    scrapeable.  Stdlib only — never touches jax or a device runtime."""
    import json as _json
    import os
    import tempfile
    import time as _time
    import urllib.request

    try:
        from .obs.export.prometheus import (parse_exposition,
                                            samples_by_name,
                                            validate_histogram_series)
        from .obs.export.sidecar import MetricsSidecar, publish_counters
        from .obs.hist import Histogram

        probe_hist = Histogram()
        probe_hist.observe(0.002)
        with tempfile.TemporaryDirectory() as d:
            hb_ts = _time.time()
            with open(os.path.join(d, "heartbeat.json"), "w") as f:
                _json.dump({"ts": hb_ts, "pid": os.getpid(),
                            "phase": "doctor_probe", "generation": 1,
                            "counters": {"env_steps": 1},
                            "hists": {"probe_s": probe_hist.to_dict()}}, f)
            # published totals + a NEWER live beat: the scrape must
            # compose both (the cross-restart monotonicity contract) —
            # for the flat counters AND the histogram buckets
            publish_counters(d, {"env_steps": 2}, through_ts=hb_ts - 1.0,
                             extra={"restart_count": 1},
                             hists={"probe_s": probe_hist.to_dict()})
            sidecar = MetricsSidecar(d, port=0)
            sidecar.start_background()
            try:
                with urllib.request.urlopen(
                        f"http://{sidecar.host}:{sidecar.port}/metrics",
                        timeout=10) as resp:
                    body = resp.read().decode()
            finally:
                sidecar.close()
        samples = parse_exposition(body)  # ValueError on malformed lines
        vals = samples_by_name(samples)
        problems = []
        if vals.get("estorch_env_steps") != 3:
            problems.append(
                f"published+live composition broke: env_steps="
                f"{vals.get('estorch_env_steps')} (want 3)")
        if vals.get("estorch_up") != 1:
            problems.append("fresh heartbeat did not read as up")
        problems.extend(validate_histogram_series(samples))
        if vals.get("estorch_probe_s_count") != 2:
            problems.append(
                f"published+live HISTOGRAM composition broke: probe_s "
                f"count={vals.get('estorch_probe_s_count')} (want 2)")
        return {
            "ok": not problems,
            "samples": len(samples),
            **({"problems": problems} if problems else {}),
        }
    except Exception as e:  # diagnostic tool: never crash the report
        return {"ok": False, "error": repr(e)}


# tiny host-backend ES save/restore round trip, run in a SUBPROCESS with a
# hard timeout (the orbax/jax import chain inits a backend — on a wedged
# machine that hang must not take the doctor down with it).  __ROOT__ is
# substituted (plain replace — str.format would trip on the dict braces)
# with the repr of the checkpoint root under test.
_RESILIENCE_PROBE = """
import os, shutil
import numpy as np
import torch
from estorch_tpu.utils import force_cpu_backend
force_cpu_backend(1)
from estorch_tpu import ES
from estorch_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint

class P(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.l = torch.nn.Linear(2, 1)
    def forward(self, x):
        return self.l(x)

class A:
    def rollout(self, policy):
        with torch.no_grad():
            v = torch.nn.utils.parameters_to_vector(policy.parameters())
        return -float((v ** 2).sum())

def make():
    return ES(P, A, torch.optim.Adam, population_size=4, sigma=0.1, seed=0,
              optimizer_kwargs={"lr": 1e-2}, table_size=1 << 10,
              telemetry=False)

root = os.path.join(__ROOT__, "doctor_resilience_probe_%d" % os.getpid())
try:
    es = make()
    es.train(1, verbose=False)
    save_checkpoint(es, root)
    es2 = make()
    restore_checkpoint(es2, root)
    assert es2.generation == 1, es2.generation
    np.testing.assert_array_equal(np.asarray(es.state.params_flat),
                                  np.asarray(es2.state.params_flat))
finally:
    shutil.rmtree(root, ignore_errors=True)
print("RESILIENCE_PROBE_OK")
"""


def _roundtrip_probe(root: str, timeout_s: float = 180.0) -> dict:
    """Save/restore a tiny ES under ``root`` in a timed-out subprocess."""
    import tempfile

    with tempfile.TemporaryFile("w+") as fo, \
            tempfile.TemporaryFile("w+") as fe:
        proc = subprocess.Popen(
            [sys.executable, "-c",
             _RESILIENCE_PROBE.replace("__ROOT__", repr(root))],
            stdout=fo, stderr=fe, text=True)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                fe.seek(0)
                return {"status": "wedged", "timeout_s": timeout_s,
                        "unreapable_child": True,
                        "stderr_tail": fe.read()[-500:]}
            fe.seek(0)
            return {"status": "wedged", "timeout_s": timeout_s,
                    "stderr_tail": fe.read()[-500:]}
        fo.seek(0), fe.seek(0)
        out, err = fo.read(), fe.read()
    if "RESILIENCE_PROBE_OK" in out:
        return {"status": "ok"}
    return {"status": "error", "returncode": proc.returncode,
            "stderr_tail": err[-500:]}


def check_resilience(ckpt_root: str | None = None,
                     probe: bool = False,
                     probe_timeout_s: float = 180.0) -> dict:
    """Can a run here actually survive faults?  (docs/resilience.md)

    - is the checkpoint root (``ESTORCH_CKPT_ROOT`` or tempdir) writable
      — without it the Supervisor has nothing to resume from;
    - ``probe=True``: a full save/restore round trip on a tiny host ES
      in a timed-out subprocess — the end-to-end proof that resume works
      on THIS machine's orbax/torch/jax install;
    - is fork available — worker respawn (host/procpool.py) needs it;
    - heartbeat-watchdog config sanity: a heartbeat path with telemetry
      disabled means a supervisor would see no beats and kill healthy
      runs.
    """
    import os
    import tempfile

    from .obs.recorder import HEARTBEAT_ENV, STALE_AFTER_S
    from .obs.spans import OBS_DISABLE_ENV

    root = (ckpt_root or os.environ.get("ESTORCH_CKPT_ROOT")
            or tempfile.gettempdir())
    try:
        probe_file = os.path.join(root, f".ckpt_write_probe_{os.getpid()}")
        with open(probe_file, "w") as f:
            f.write("ok")
        os.remove(probe_file)
        writable, err = True, None
    except OSError as e:  # diagnostic tool: never crash the report
        writable, err = False, repr(e)
    out: dict = {
        "ckpt_root": {"path": root, "writable": writable,
                      **({"error": err} if err else {})},
    }
    if probe and writable:
        out["roundtrip"] = _roundtrip_probe(root, probe_timeout_s)
    import multiprocessing as mp

    out["fork"] = {
        "available": os.name == "posix" and "fork" in mp.get_all_start_methods(),
        "needed_for": "host process workers + respawn (host/procpool.py)",
    }
    hb_path = os.environ.get(HEARTBEAT_ENV)
    obs_enabled = os.environ.get(OBS_DISABLE_ENV, "1") != "0"
    watchdog: dict = {
        "heartbeat_env_set": bool(hb_path),
        "telemetry_enabled": obs_enabled,
        "stale_after_s": STALE_AFTER_S,
    }
    if hb_path and not obs_enabled:
        watchdog["warning"] = (
            f"{HEARTBEAT_ENV} is set but {OBS_DISABLE_ENV}=0 disables "
            "telemetry — a staleness watchdog would see no beats and kill "
            "healthy runs"
        )
    if hb_path:
        hb_dir = os.path.dirname(os.path.abspath(hb_path)) or "."
        watchdog["heartbeat_dir_writable"] = os.access(hb_dir, os.W_OK)
    out["heartbeat_watchdog"] = watchdog
    return out


def check_serve(bundle: str | None = None) -> dict:
    """Serving readiness (estorch_tpu/serve, docs/serving.md):

    - can this host bind a loopback listening socket (the server's one
      OS-level requirement beyond python)?
    - does the dynamic batcher round-trip requests (coalescing, bucket
      padding, recompile accounting) — exercised with a plain-numpy
      batch fn, so this check never touches jax or a device runtime;
    - given ``bundle``: structural validation of the artifact (manifest
      schema, payload checksum, param count) via
      ``serve.bundle.validate_bundle`` — again without importing jax, so
      a corrupt bundle is diagnosable from a wedged-runtime machine.
    """
    import socket

    out: dict = {}
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        out["loopback"] = {"bindable": True, "probe_port": port}
    except OSError as e:  # diagnostic tool: never crash the report
        out["loopback"] = {"bindable": False, "error": repr(e)}

    try:
        import numpy as np

        from .obs.spans import Telemetry
        from .serve.batcher import DynamicBatcher

        tel = Telemetry(enabled=True)
        b = DynamicBatcher(lambda arr: arr * 2.0, (3,), max_batch=4,
                           max_wait_ms=1.0, telemetry=tel)
        got = b.predict([1.0, 2.0, 3.0], timeout=10.0)
        b.close()
        ok = np.allclose(got, [2.0, 4.0, 6.0])
        out["batcher"] = {
            "ok": bool(ok),
            "recompiles": int(tel.counters.get("recompiles")),
            "buckets": list(b.buckets),
        }
    except Exception as e:
        out["batcher"] = {"ok": False, "error": repr(e)}

    if bundle is not None:
        from .serve.bundle import BundleError, validate_bundle

        try:
            man = validate_bundle(bundle)
            out["bundle"] = {
                "path": bundle, "valid": True,
                "version": man["version"],
                "param_dim": man["param_dim"],
                "module": man["module"]["import"],
                "obs_norm": bool(man.get("obs_norm")),
                "recurrent": bool(man.get("recurrent")),
                "warm": _probe_bundle_warmth(man),
            }
        except (BundleError, OSError) as e:
            out["bundle"] = {"path": bundle, "valid": False,
                             "error": str(e)}
    return out


def _probe_bundle_warmth(manifest: dict) -> dict:
    """The warm-bundle probe (serve/warm.py, docs/serving.md "Cold start
    & quantized serving"), jax-free like the rest of check_serve:
    validate_bundle already proved the packed warmth structurally sound
    (entries present, checksummed, ladder complete), so what is left is
    the COMPATIBILITY finding — warmth built under a different jax
    version than this host's install can never hit and will be ignored
    at load; an operator should re-export rather than wonder why the
    replica still pays the JIT storm.  The installed jax version comes
    from package metadata, so a wedged runtime can still be probed."""
    warm = manifest.get("warm")
    if not isinstance(warm, dict):
        return {"present": False}
    out = {
        "present": True,
        "format": warm.get("format"),
        "entries": len(warm.get("entries") or {}),
        "buckets": warm.get("buckets"),
        "dtypes": warm.get("dtypes"),
        "jax_version": warm.get("jax_version"),
        "platform": warm.get("platform"),
    }
    try:
        from importlib.metadata import version

        installed = version("jax")
    except Exception:
        installed = None
    out["installed_jax"] = installed
    if installed is None:
        out["compatible"] = None
        out["finding"] = ("jax is not importable as package metadata on "
                          "this host — warmth compatibility unknown")
    elif installed != warm.get("jax_version"):
        out["compatible"] = False
        out["finding"] = (
            f"warmth was built under jax {warm.get('jax_version')} but "
            f"this host has jax {installed} — cache keys cannot match, "
            "the warmth will be ignored at load; re-export the bundle "
            "with warm=True under the serving jax version")
    else:
        out["compatible"] = True
    return out


def check_router() -> dict:
    """Can this host run the fleet front router?  (serve/router.py,
    docs/serving.md "Fleet")

    Loopback end-to-end probe, jax-free: spin a 2-replica TOY fleet
    (stdlib HTTP servers answering the /predict //healthz //stats
    shapes), route through a real :class:`Router`, then kill one
    replica and assert the next requests still answer (failover within
    the retry budget) and that the router's ``/metrics`` parses through
    the validating parser.  Never crashes the report: any failure comes
    back as ``{"ok": False, ...}``."""
    import json as _json
    import threading
    import urllib.request
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    try:
        from .obs.export.prometheus import parse_exposition
        from .serve.router import Router

        def make_replica():
            class Toy(BaseHTTPRequestHandler):
                protocol_version = "HTTP/1.1"

                def log_message(self, *a):
                    pass

                def _j(self, obj):
                    body = _json.dumps(obj).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):
                    if self.path == "/healthz":
                        self._j({"ok": True, "draining": False,
                                 "queue_depth": 0})
                    else:
                        self._j({"queue_depth": 0,
                                 "request_ms": {"p99": 1.0}})

                def do_POST(self):
                    n = int(self.headers.get("Content-Length", 0))
                    data = _json.loads(self.rfile.read(n))
                    self._j({"action": [v * 2.0 for v in data["obs"]]})

            srv = ThreadingHTTPServer(("127.0.0.1", 0), Toy)
            threading.Thread(target=srv.serve_forever,
                             daemon=True).start()
            return srv

        problems = []
        a, b = make_replica(), make_replica()
        router = Router(
            [("ra", f"127.0.0.1:{a.server_address[1]}"),
             ("rb", f"127.0.0.1:{b.server_address[1]}")],
            port=0, poll_interval_s=30.0,  # stale health: exercise RETRY
            upstream_timeout_s=5.0)
        router.start_background()
        try:
            url = f"http://{router.host}:{router.port}"

            def predict(obs):
                req = urllib.request.Request(
                    url + "/predict",
                    _json.dumps({"obs": obs}).encode(),
                    {"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return _json.loads(r.read())

            if predict([1.0])["action"] != [2.0]:
                problems.append("routed predict answered wrong")
            a.shutdown()
            a.server_close()
            for i in range(4):  # must fail over to rb, zero errors
                got = predict([float(i)])["action"]
                if got != [2.0 * i]:
                    problems.append(f"failover answer wrong: {got}")
            st = router.stats()
            retries = st["counters"].get("router_retries_total", 0)
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=10) as r:
                body = r.read().decode()
            parse_exposition(body)
            if "estorch_router_breaker_state" not in body:
                problems.append("per-replica breaker gauge missing "
                                "from /metrics")
            return {"ok": not problems, "retries": int(retries),
                    "breakers": {x["name"]: x["breaker"]
                                 for x in st["replicas"]},
                    **({"problems": problems} if problems else {})}
        finally:
            router.shutdown(drain=False)
            b.shutdown()
            b.server_close()
    except Exception as e:  # diagnostic tool: never crash the report
        return {"ok": False, "error": repr(e)}


def check_tracing() -> dict:
    """Can this host assemble a CROSS-PROCESS distributed trace?
    (obs/tracing.py + obs/agg/traces.py, docs/observability.md
    "Distributed tracing")

    Loopback end-to-end probe, jax-free: a real :class:`Router` with a
    run dir routes one forced-sampled request (``X-Trace-Sampled: 1``)
    to a toy stdlib replica that keeps its OWN :class:`ProcessTracer`
    and records a ``request`` segment parented on the router's
    forwarded ``X-Parent-Span``.  Both processes' tracers flush, then
    assembly (``obs trace --fleet``'s engine) must join the trace
    across both, with at least one cross-process parent→child hop, and
    the Perfetto export must validate.  Never crashes the report: any
    failure comes back as ``{"ok": False, ...}``."""
    import json as _json
    import os
    import tempfile
    import threading
    import time as _time
    import urllib.request
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    try:
        from .obs.agg import traces as traces_agg
        from .obs.export.traceevent import validate_trace
        from .obs.tracing import (PARENT_SPAN_HEADER, SAMPLED_HEADER,
                                  TRACE_HEADER, TRACES_FILENAME,
                                  ProcessTracer, make_segment)
        from .serve.router import Router

        problems: list[str] = []
        trace_id = "doctor-trace-1"
        with tempfile.TemporaryDirectory() as td:
            replica_dir = os.path.join(td, "replica")
            os.makedirs(replica_dir)
            tracer = ProcessTracer(
                "replica", head_every=1,
                path=os.path.join(replica_dir, TRACES_FILENAME))

            class Toy(BaseHTTPRequestHandler):
                protocol_version = "HTTP/1.1"

                def log_message(self, *a):
                    pass

                def _j(self, obj):
                    body = _json.dumps(obj).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):
                    if self.path == "/healthz":
                        self._j({"ok": True, "draining": False,
                                 "queue_depth": 0})
                    else:
                        self._j({"queue_depth": 0,
                                 "request_ms": {"p99": 1.0}})

                def do_POST(self):
                    t0 = _time.monotonic()
                    trace = self.headers.get(TRACE_HEADER) or ""
                    parent = self.headers.get(PARENT_SPAN_HEADER) or None
                    forced = self.headers.get(SAMPLED_HEADER) == "1"
                    n = int(self.headers.get("Content-Length", 0))
                    data = _json.loads(self.rfile.read(n))
                    self._j({"action": [v * 2.0 for v in data["obs"]]})
                    if trace:
                        dt = _time.monotonic() - t0
                        tracer.add(make_segment(
                            trace, tracer.span_id(), parent, "replica",
                            "request", t0, dt, {"status": 200}))
                        tracer.finish(trace, dt, forced=forced)

            srv = ThreadingHTTPServer(("127.0.0.1", 0), Toy)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            router_dir = os.path.join(td, "router")
            router = Router(
                [("ra", f"127.0.0.1:{srv.server_address[1]}")],
                port=0, poll_interval_s=30.0, upstream_timeout_s=5.0,
                run_dir=router_dir)
            router.start_background()
            try:
                req = urllib.request.Request(
                    f"http://{router.host}:{router.port}/predict",
                    _json.dumps({"obs": [1.0]}).encode(),
                    {"Content-Type": "application/json",
                     TRACE_HEADER: trace_id, SAMPLED_HEADER: "1"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    got = _json.loads(r.read())
                    echoed = r.headers.get(TRACE_HEADER)
                if got.get("action") != [2.0]:
                    problems.append(f"routed predict answered wrong: {got}")
                if echoed != trace_id:
                    problems.append(
                        f"router did not echo {TRACE_HEADER}: {echoed!r}")
            finally:
                router.shutdown(drain=False)
                srv.shutdown()
                srv.server_close()
            tracer.flush()

            segs = traces_agg.load_segments(traces_agg.trace_files([td]))
            asm = traces_agg.assemble(segs)
            trace = asm.get(trace_id)
            if trace is None:
                problems.append(
                    f"trace {trace_id!r} did not assemble "
                    f"(got {sorted(asm)})")
                return {"ok": False, "problems": problems}
            if len(trace["procs"]) < 2:
                problems.append(
                    f"trace did not cross processes: {trace['procs']}")
            hops = traces_agg.cross_process_edges(trace)
            if not hops:
                problems.append("no cross-process parent->child hop — "
                                "X-Parent-Span not propagated")
            export = traces_agg.export_fleet_trace([trace])
            errs = validate_trace(export)
            if errs:
                problems.append(f"perfetto export invalid: {errs[:3]}")
            return {"ok": not problems, "procs": trace["procs"],
                    "segments": len(trace["segments"]),
                    "cross_hops": len(hops),
                    "sampled": trace.get("sampled"),
                    **({"problems": problems} if problems else {})}
    except Exception as e:  # diagnostic tool: never crash the report
        return {"ok": False, "error": repr(e)}


def check_collector() -> dict:
    """Can this host run the fleet-aggregation plane?  (obs/agg/,
    docs/observability.md "Fleet aggregation")

    Loopback end-to-end probe: spin a synthetic target (the metrics
    sidecar over a temp run dir with a fresh heartbeat), point a
    collector with an absence rule at it PLUS a dead port, run one
    collection tick, and assert the full chain — sample stored in the
    time-series store, rules evaluated (the dead target's absence rule
    fires, the live one's does not), and the collector's ``/alerts`` and
    ``/metrics`` parse over loopback.  Stdlib only, never touches jax,
    and never crashes the report: a refused port or any other failure
    comes back as ``{"ok": False, "error"/"problems": ...}``."""
    import json as _json
    import os
    import socket
    import tempfile
    import time as _time
    import urllib.request

    try:
        from .obs.agg.collector import Collector, Target
        from .obs.agg.rules import RulesEngine
        from .obs.agg.store import SeriesStore
        from .obs.export.prometheus import parse_exposition
        from .obs.export.sidecar import MetricsSidecar

        problems = []
        with tempfile.TemporaryDirectory() as d:
            run_dir = os.path.join(d, "run")
            os.makedirs(run_dir)
            with open(os.path.join(run_dir, "heartbeat.json"), "w") as f:
                _json.dump({"ts": _time.time(), "pid": os.getpid(),
                            "phase": "doctor_probe", "generation": 1,
                            "counters": {"env_steps": 3}}, f)
            sidecar = MetricsSidecar(run_dir, port=0)
            sidecar.start_background()
            # bound-but-not-listening: connects get RST for the whole
            # probe (closing it would race the port back to the
            # allocator, which could hand it to the collector itself)
            dead_sock = socket.socket()
            dead_sock.bind(("127.0.0.1", 0))
            dead_port = dead_sock.getsockname()[1]
            col = None
            try:
                store = SeriesStore(os.path.join(d, "store"))
                rules = RulesEngine([
                    {"name": "replica-down", "kind": "absence",
                     "metric": "estorch_up", "for_s": 0, "window_s": 30},
                ])
                col = Collector(
                    [Target("probe-run",
                            url=f"http://{sidecar.host}:{sidecar.port}"
                                "/metrics", timeout_s=5.0),
                     Target("probe-dead",
                            url=f"http://127.0.0.1:{dead_port}/metrics",
                            timeout_s=0.5)],
                    store, rules, port=0)
                col.start_background()
                now = _time.time()
                tick = col.tick(now)
                if not tick["targets"]["probe-run"]["ok"]:
                    problems.append(
                        f"live target scrape failed: {tick}")
                stored = store.latest("estorch_env_steps",
                                      {"target": "probe-run"},
                                      window_s=60, now=now)
                if not stored:
                    problems.append("scraped sample not found in store")
                fired = {(t["rule"], t["target"])
                         for t in tick["transitions"]
                         if t["event"] == "firing"}
                if ("replica-down", "probe-dead") not in fired:
                    problems.append(
                        f"absence rule did not fire for the dead "
                        f"target: {fired}")
                if ("replica-down", "probe-run") in fired:
                    problems.append("absence rule fired for the live "
                                    "target")
                base = f"http://{col.host}:{col.port}"
                with urllib.request.urlopen(base + "/alerts",
                                            timeout=10) as resp:
                    alerts = _json.loads(resp.read().decode())
                if not any(a["rule"] == "replica-down"
                           and a["target"] == "probe-dead"
                           for a in alerts["active"]):
                    problems.append(f"/alerts missing the active "
                                    f"absence alert: {alerts}")
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=10) as resp:
                    parse_exposition(resp.read().decode())
            finally:
                if col is not None:
                    col.close()
                dead_sock.close()
                sidecar.close()
        return {"ok": not problems,
                **({"problems": problems} if problems else {})}
    except Exception as e:  # diagnostic tool: never crash the report
        return {"ok": False, "error": repr(e)}


def check_autoscaler() -> dict:
    """Can this host close the serving control loop?  (obs/agg/
    autoscale.py, docs/serving.md "Autoscaling")

    Loopback decision dry-run: seed a synthetic store with a demand
    ramp, write a matching capacity artifact, and run one control cycle
    with ``dry_run`` — the decision must be a scale-up, logged to the
    append-only decision log, and the log must replay bit-exactly.  A
    mismatched capacity model (wrong bundle sha) must be REFUSED.
    Stdlib only, never touches jax, never crashes the report."""
    import json as _json
    import os
    import tempfile

    try:
        from .obs.agg import autoscale as _az
        from .obs.agg.store import SeriesStore

        problems = []
        with tempfile.TemporaryDirectory() as d:
            store = SeriesStore(os.path.join(d, "store"))
            t0 = 1_000_000.0
            for ts, total in ((t0, 0.0), (t0 + 10, 100.0)):
                store.append([
                    {"name": "estorch_router_requests_total",
                     "labels": {"target": "probe"}, "value": total},
                    {"name": "estorch_router_replica_up",
                     "labels": {"target": "probe", "replica": "r0"},
                     "value": 1.0},
                ], ts=ts)
            cap_path = os.path.join(d, "capacity.json")
            capacity = {"schema": _az.CAPACITY_SCHEMA, "kind": "capacity",
                        "created_ts": t0, "slo_ms": 50.0,
                        "quantile": "p99", "max_rps_at_slo": 5.0,
                        "saturated": False,
                        "rungs": [{"offered_rps": 5.0, "ok": True}],
                        "bundle_sha": "ab" * 32, "bundle_version": 1,
                        "platform": "cpu"}
            with open(cap_path, "w") as f:
                _json.dump(capacity, f)
            bad = _az.validate_capacity(capacity)
            if bad:
                problems.append(f"capacity artifact rejected: {bad}")
            az = _az.Autoscaler(
                os.path.join(d, "store"), capacity=cap_path,
                fleet_identity={"bundle_sha": "ab" * 32,
                                "platform": "cpu"},
                policy={"min_replicas": 1, "max_replicas": 8,
                        "window_s": 10.0}, dry_run=True)
            # 10 rps against 5 rps/replica: the only sane verdict is up
            ev = az.tick(now=t0 + 10)
            if ev is None or ev["verdict"]["action"] != "up":
                problems.append(f"dry-run decision not a scale-up: "
                                f"{ev and ev['verdict']}")
            elif ev["actuation"] != {"attempted": False,
                                     "dry_run": True}:
                problems.append(f"dry-run actuated: {ev['actuation']}")
            rep = _az.replay(az.log_path)
            if not rep["ok"]:
                problems.append(f"decision log replay mismatch: "
                                f"{rep['mismatches'][:2]}")
            try:
                _az.Autoscaler(
                    os.path.join(d, "store"), capacity=cap_path,
                    fleet_identity={"bundle_sha": "cd" * 32,
                                    "platform": "cpu"},
                    dry_run=True)
                problems.append("mismatched capacity model accepted")
            except _az.AutoscaleError as e:
                # the refusal IS the pass; gate that it names both shas
                if "cd" * 6 not in str(e):
                    problems.append(
                        f"mismatch refusal names neither sha: {e}")
        return {"ok": not problems,
                **({"problems": problems} if problems else {})}
    except Exception as e:  # diagnostic tool: never crash the report
        return {"ok": False, "error": repr(e)}


def report(timeout_s: float = 45.0, run_dir: str | None = None,
           resilience_probe: bool = False,
           serve_bundle: str | None = None) -> dict:
    # ONE staged probe serves both rows: the typed verdict (the row
    # bench.py's platform decision reads — no-device / init-hang /
    # compile-hang / exec-hang, docs/observability.md "Profiling") and
    # the legacy healthy/wedged/error summary derived from it, so a
    # wedged host costs one timeout, not two serial ones.  The caller's
    # timeout_s (--timeout) rules: capping it here would classify a
    # slow-but-healthy host as wedged, the exact false alarm a larger
    # --timeout is passed to avoid.  probe_device remains available for
    # callers that want the bare wedge check.
    probe = check_device(timeout_s=timeout_s)
    if probe["status"] == "ok":
        dev = {"status": "healthy", "platform": probe["platform"],
               "n_devices": probe["n_devices"]}
    elif str(probe.get("reason", "")).endswith("-hang"):
        dev = {"status": "wedged", "timeout_s": probe["timeout_s"],
               "stderr_tail": probe.get("stderr_tail", "")}
        if probe.get("unreapable_child"):
            dev["unreapable_child"] = True
    else:
        dev = {"status": "error",
               "stderr_tail": probe.get("stderr_tail", "")}
    rep = {
        "device": dev,
        "device_probe": probe,
        "native": check_native_pool(),
        "mesh": check_mesh(),
        "elastic": check_elastic(),
        "scenarios": check_scenarios(),
        "optional": check_optional_deps(),
        "host": check_host(),
        "obs": check_obs(run_dir),
        "collector": check_collector(),
        "resilience": check_resilience(probe=resilience_probe),
        "serve": check_serve(bundle=serve_bundle),
        "router": check_router(),
        "tracing": check_tracing(),
        "autoscaler": check_autoscaler(),
    }
    cpu_recipe = (
        "run on the virtual CPU mesh instead — jax.config.update("
        "'jax_platforms', 'cpu') + jax.config.update('jax_num_cpu_devices', "
        "8) BEFORE first device use (env vars may be ignored if a site hook "
        "pins the platform)"
    )
    if dev["status"] == "wedged":
        rep["hint"] = (
            "device runtime is hung (not merely compiling): " + cpu_recipe +
            " — or retry later; wedges have been observed to outlive whole "
            "sessions"
        )
    elif dev["status"] == "error":
        rep["hint"] = (
            "backend failed fast (see stderr_tail) — a clean init error, "
            "not a wedge; " + cpu_recipe
        )
    return rep


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--timeout", type=float, default=45.0,
                   help="device probe timeout in seconds")
    p.add_argument("--run-dir", default=None, metavar="DIR",
                   help="training run directory: report heartbeat "
                        "freshness for a run that stopped answering")
    p.add_argument("--resilience-probe", action="store_true",
                   help="also run the checkpoint save/restore round-trip "
                        "probe (a tiny ES in a timed-out subprocess)")
    p.add_argument("--bundle", default=None, metavar="DIR",
                   help="policy bundle to validate (manifest schema + "
                        "payload checksum, no jax import)")
    args = p.parse_args(argv)
    rep = report(args.timeout, run_dir=args.run_dir,
                 resilience_probe=args.resilience_probe,
                 serve_bundle=args.bundle)
    print(json.dumps(rep, indent=2))
    return 0 if rep["device"]["status"] == "healthy" else 1


if __name__ == "__main__":
    sys.exit(main())
