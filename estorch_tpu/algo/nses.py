"""NS-ES / NSR-ES / NSRA-ES — the novelty-search family (Conti et al. 2018).

Reference classes ``NS_ES``, ``NSR_ES(NS_ES)``, ``NSRA_ES(NSR_ES)`` in
``estorch/estorch.py`` (SURVEY.md §2 items 3-5, call stack §3.4):

- a meta-population of M policies; each generation picks ONE to update, with
  probability proportional to the novelty of its center behavior;
- rollouts return (reward, bc); member novelty = mean k-NN distance of its
  BC to the archive;
- update direction: NS = novelty ranks only; NSR = ½(reward + novelty
  ranks); NSRA = w·reward + (1−w)·novelty ranks with adaptive w (w rises on
  improvement, decays toward novelty after ``stagnation_patience``
  generations without a new best);
- after the update, the (unperturbed) center's BC is appended to the archive.

TPU-native split: the population evaluation and the rank-weighted update are
the engine's compiled programs (parallel/engine.py evaluate/apply_weights);
the archive, k-NN, meta-selection, and w schedule run host-side on O(pop)
floats — exactly the split BASELINE.json's north star prescribes.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.ranks import centered_rank_np
from .archive import NoveltyArchive
from .es import ES


class NS_ES(ES):
    """Novelty-Search ES: follows novelty ranks only (pure exploration)."""

    def __init__(
        self,
        policy,
        agent,
        optimizer,
        *,
        k: int = 10,
        meta_population_size: int = 3,
        archive_max_size: int = 0,
        **kwargs,
    ):
        if kwargs.get("scenarios") is not None:
            raise ValueError(
                "scenarios is not wired into the novelty family: the "
                "ScenarioEnv appends the variant id to the BC vector, "
                "which would silently distort archive k-NN novelty "
                "(estorch_tpu/scenarios; use plain ES or PBTController)"
            )
        super().__init__(policy, agent, optimizer, **kwargs)
        self.k = k
        self.meta_population_size = int(meta_population_size)
        bc_dim = getattr(self.engine, "bc_dim", None) or None
        self.archive = NoveltyArchive(
            k=k, bc_dim=bc_dim, max_size=archive_max_size
        )

        # meta-population: M independent centers sharing one engine/noise table.
        # state[0] reuses the base-class init; the rest start from fresh
        # policy initializations so the centers are distinct.
        self.meta_states = [self.state]
        for m in range(1, self.meta_population_size):
            self.meta_states.append(self._new_center_state(m))
        # center BC per meta-individual (seeds the archive, reference
        # behavior: the initial centers' BCs are the first archive entries)
        self._center_bc = []
        for st in self.meta_states:
            res = self.engine.evaluate_center(st)
            bc = np.asarray(res.bc)
            self._center_bc.append(bc)
            self.archive.add(bc)
        self._rng = np.random.default_rng(self.seed)

    def _new_center_state(self, m: int):
        """Fresh meta-individual center: re-initialized policy + own RNG stream."""
        if self.backend == "host":
            fresh = self.engine.policy_factory()
            import torch

            with torch.no_grad():
                flat = (
                    torch.nn.utils.parameters_to_vector(fresh.parameters())
                    .cpu()
                    .numpy()
                )
            return self.engine.init_state(flat, key=self.seed + 7919 * m)
        vs = self._module_init(
            jax.random.fold_in(jax.random.PRNGKey(self.seed), 1000 + m)
        )
        flat = self._spec.flatten(vs["params"])
        return self.engine.init_state(
            flat, jax.random.fold_in(jax.random.PRNGKey(self.seed), 2000 + m)
        )

    # ---- variant-specific weighting -------------------------------------

    def _combine_weights(self, fitness: np.ndarray, novelty: np.ndarray) -> np.ndarray:
        """NS-ES: novelty ranks only (reference NS_ES gradient)."""
        return centered_rank_np(novelty)

    def _weights_with_failures(self, fitness: np.ndarray, novelty: np.ndarray) -> np.ndarray:
        """Variant weights with failed (NaN-fitness) members dropped.

        np.argsort sorts NaN LAST — without this guard a failed member would
        receive the TOP centered rank and dominate the update.  Valid members
        are ranked among themselves; failures are zero-weighted and survivors
        renormalized (utils/fault.py straggler-drop scheme).
        """
        from ..utils.fault import mask_and_renormalize, valid_mask

        valid = valid_mask(fitness)
        if valid.all():
            return self._combine_weights(fitness, novelty)
        w = np.zeros(fitness.shape[0], dtype=np.float32)
        w[valid] = self._combine_weights(fitness[valid], novelty[valid])
        return mask_and_renormalize(w, valid)

    # ---- training loop ---------------------------------------------------

    def _select_meta_index(self) -> int:
        """P(m) ∝ novelty of m's center BC against the archive."""
        nov = self.archive.novelty(np.stack(self._center_bc))
        total = float(nov.sum())
        if total <= 0 or not np.isfinite(total):
            probs = np.full(len(nov), 1.0 / len(nov))
        else:
            probs = nov / total
        return int(self._rng.choice(len(nov), p=probs))

    def _post_update(self, record: dict) -> None:
        """Hook for NSRA's w schedule."""

    def train(
        self,
        n_steps: int,
        n_proc: int = 1,
        log_fn: Callable[[dict], None] | None = None,
        verbose: bool = True,
    ):
        self._setup_n_proc(n_proc)
        obs = self.obs
        obs.discard_phases()  # drop partial spans from an aborted generation
        if self.compile_time_s is None:
            # AOT-compile the split-path programs outside the timed loop,
            # same invariant as ES.train for the primary metric
            obs.note("compile")
            self.compile_time_s = self.engine.compile_split(self.meta_states[0])
        for _ in range(n_steps):
            t0 = time.perf_counter()
            # the split path has REAL host-visible phase boundaries (unlike
            # ES's fused program): each span below ends on a host
            # materialization of its device outputs, so device time lands
            # in the phase that spent it (esguard R07 fencing contract)
            with obs.phase("select"):
                m = self._select_meta_index()
            st = self.meta_states[m]

            with obs.phase("eval"):
                ev = self.engine.evaluate(st)
                fitness = np.asarray(ev.fitness)  # fences the eval program
                bc = np.asarray(ev.bc)
            with obs.phase("novelty_knn"):
                novelty = self.archive.novelty(bc)
                weights = self._weights_with_failures(fitness, novelty)
                if self.backend == "device":
                    weights = jnp.asarray(weights)

            with obs.phase("update"):
                new_st, gnorm = self.engine.apply_weights(st, weights)
                if self.backend != "host":
                    jax.block_until_ready(new_st.params_flat)
            self.meta_states[m] = new_st
            if m == 0:
                self.state = new_st  # keep base-class accessors on meta[0]

            # center of the UPDATED policy: archive entry + meta bookkeeping
            with obs.phase("archive"):
                cres = self.engine.evaluate_center(new_st)
                cbc = np.asarray(cres.bc)
                self.archive.add(cbc)
                self._center_bc[m] = cbc
            dt = time.perf_counter() - t0

            record = self._base_record(
                st, fitness, int(ev.steps), float(np.asarray(gnorm)), dt
            )
            record.update(
                meta_index=m,
                center_reward=float(cres.total_reward),
                novelty_mean=float(novelty.mean()),
                novelty_max=float(novelty.max()),
                archive_size=len(self.archive),
            )
            self._post_update(record)
            self._emit_record(record, log_fn, verbose)
        return self

    def _format_record(self, r: dict) -> str:
        return (
            f"gen {r['generation']:4d}  meta {r['meta_index']}  "
            f"max {r['reward_max']:9.2f}  "
            f"nov {r['novelty_mean']:7.3f}  "
            f"archive {r['archive_size']:4d}  "
            f"steps/s {r['env_steps_per_sec']:,.0f}"
        )


class NSR_ES(NS_ES):
    """Novelty+Reward ES: equal mix of reward and novelty ranks."""

    def _combine_weights(self, fitness: np.ndarray, novelty: np.ndarray) -> np.ndarray:
        return 0.5 * centered_rank_np(fitness) + 0.5 * centered_rank_np(novelty)


class NSRA_ES(NSR_ES):
    """Adaptive NSR-ES: w·reward + (1−w)·novelty with w adapted on progress.

    Reference ctor extras (SURVEY.md Appendix A): initial ``weight``,
    ``weight_delta`` (step), ``stagnation_patience`` (generations without a
    new best before w decays toward novelty).
    """

    def __init__(
        self,
        policy,
        agent,
        optimizer,
        *,
        weight: float = 1.0,
        weight_delta: float = 0.05,
        stagnation_patience: int = 10,
        **kwargs,
    ):
        self.weight = float(weight)
        self.weight_delta = float(weight_delta)
        self.stagnation_patience = int(stagnation_patience)
        self._stagnation = 0
        super().__init__(policy, agent, optimizer, **kwargs)

    def _combine_weights(self, fitness: np.ndarray, novelty: np.ndarray) -> np.ndarray:
        w = self.weight
        return w * centered_rank_np(fitness) + (1.0 - w) * centered_rank_np(novelty)

    def _post_update(self, record: dict) -> None:
        # ``improved_best`` comes from the shared best tracking in
        # ES._base_record — no separate best mirror to drift from it
        if record["improved_best"]:
            self.weight = min(1.0, self.weight + self.weight_delta)
            self._stagnation = 0
        else:
            self._stagnation += 1
            if self._stagnation >= self.stagnation_patience:
                self.weight = max(0.0, self.weight - self.weight_delta)
                self._stagnation = 0
        record["nsra_weight"] = self.weight
