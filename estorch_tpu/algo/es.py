"""ES — the user-facing algorithm class, API-parity with the reference.

Reference surface (SURVEY.md Appendix A, ``estorch/estorch.py`` class ``ES``):

    es = ES(policy, agent, optimizer, population_size=..., sigma=...,
            device=..., policy_kwargs={}, agent_kwargs={}, optimizer_kwargs={})
    es.train(n_steps, n_proc=1)
    es.policy; es.best_policy; es.best_reward

estorch_tpu keeps that shape.  Differences forced by the TPU-first design:

- ``policy`` is a flax ``nn.Module`` class (or instance); ``agent`` is a
  ``JaxAgent`` naming a device-native env (host Gym agents are served by the
  host backend, envs/host_pool.py).  ``optimizer`` is an optax factory
  (``optax.adam``) or transformation — ``optimizer_kwargs`` go to the
  factory, so ``ES(..., optimizer=optax.adam, optimizer_kwargs={"learning_rate": 1e-2})``
  reads like the reference's ``torch.optim.Adam`` usage.
- ``device`` selects the mesh: ``None`` → all local devices (population DP
  over chips via one psum — the reference's n_proc workers, minus the MPI).
- ``train(n_steps, n_proc)``: ``n_proc`` is accepted for compatibility and
  ignored on the device path (the mesh already parallelizes).

Where the reference's generation is a Python loop + MPI round-trips
(SURVEY.md §3.2), here it is ONE jitted XLA program (parallel/engine.py).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..envs.agent import JaxAgent, collect_reference_batch
from ..models.vbn import capture_reference_stats
from ..obs.spans import resolve_telemetry
from ..ops.noise import DEFAULT_TABLE_SIZE, make_noise_table
from ..ops.params import make_param_spec
from ..parallel.engine import EngineConfig, ESEngine
from ..parallel.mesh import population_mesh


def _as_optax(optimizer, optimizer_kwargs) -> optax.GradientTransformation:
    if isinstance(optimizer, optax.GradientTransformation):
        if optimizer_kwargs:
            raise ValueError(
                "optimizer_kwargs were given alongside an already-constructed "
                f"optax transformation; they would be ignored: {optimizer_kwargs}. "
                "Pass the factory (e.g. optax.adam) with optimizer_kwargs, or "
                "the instance without them."
            )
        return optimizer
    if callable(optimizer):
        return optimizer(**optimizer_kwargs)
    raise TypeError(f"optimizer must be an optax factory or GradientTransformation, got {optimizer!r}")


def _is_jax_env(env) -> bool:
    """A JaxEnv has pure reset/step plus the static-shape attributes of
    envs/base.py — a gym env (which also has reset/step) does not."""
    return env is not None and all(
        hasattr(env, a)
        for a in ("reset", "step", "obs_dim", "action_dim", "discrete", "bc_dim")
    )


def _instantiate(cls_or_obj, kwargs, what: str):
    if isinstance(cls_or_obj, type):
        return cls_or_obj(**kwargs)
    if kwargs:
        raise ValueError(
            f"{what}_kwargs were given alongside an already-constructed "
            f"{what} instance; they would be ignored: {kwargs}. Pass the "
            f"class with {what}_kwargs, or the instance without them."
        )
    return cls_or_obj


class ES:
    """Vanilla OpenAI-ES (Salimans et al. 2017) on the TPU-native engine."""

    def __init__(
        self,
        policy,
        agent,
        optimizer,
        population_size: int = 256,
        sigma: float = 0.02,
        device=None,
        policy_kwargs: dict | None = None,
        agent_kwargs: dict | None = None,
        optimizer_kwargs: dict | None = None,
        seed: int = 0,
        table_size: int = DEFAULT_TABLE_SIZE,
        eval_chunk: int = 0,
        grad_chunk: int = 256,
        weight_decay: float = 0.0,
        mesh=None,
        vbn_batch: int = 128,
        compute_dtype: str = "float32",
        sigma_decay: float = 1.0,
        sigma_min: float = 0.0,
        mirrored: bool = True,
        episodes_per_member: int = 1,
        worker_mode: str = "thread",
        decomposed: bool = False,
        noise_kernel: bool = False,
        streamed: bool = False,
        low_rank: int = 0,
        obs_norm: bool = False,
        obs_clip: float = 5.0,
        obs_probe_episodes: int = 1,
        obs_warmup_episodes: int = 0,
        telemetry=None,
        shard_params: bool = False,
        model_shards: int | None = None,
        partition_rules=None,
        noise_mode: str = "auto",
        scenarios=None,
    ):
        # telemetry first: every backend-init path below runs with spans/
        # counters available.  None → default-on honoring ESTORCH_OBS /
        # ESTORCH_OBS_HEARTBEAT env vars; bool forces; or pass a Telemetry
        self.obs = resolve_telemetry(telemetry)
        # first beat BEFORE backend init: device bring-up is a known wedge
        # point, and "last phase=init" beats "no heartbeat written"
        self.obs.note("init")
        self.population_size = population_size
        self.sigma = sigma
        self.seed = seed
        if compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be float32 or bfloat16, got {compute_dtype!r}"
            )
        self._compute_dtype = compute_dtype
        self._sigma_decay = float(sigma_decay)
        self._sigma_min = float(sigma_min)
        self._mirrored = bool(mirrored)
        self._episodes_per_member = int(episodes_per_member)
        self._decomposed = bool(decomposed)
        self._noise_kernel = bool(noise_kernel)
        self._streamed = bool(streamed)
        self._low_rank = int(low_rank)
        self._obs_norm = bool(obs_norm)
        self._obs_clip = float(obs_clip)
        self._obs_probe_episodes = int(obs_probe_episodes)
        self._obs_warmup_episodes = int(obs_warmup_episodes)
        if self._obs_warmup_episodes and not self._obs_norm:
            raise ValueError(
                "obs_warmup_episodes warm-starts the running obs stats; "
                "it requires obs_norm=True"
            )
        # hyperscale param sharding (parallel/sharded.py, docs/sharding.md):
        # params + optimizer state sharded over a (pop, model) mesh per
        # regex partition rules, ε generated in-program, generation_step
        # donated — for policies too big to replicate per device
        self._shard_params = bool(shard_params)
        self._model_shards = model_shards
        self._partition_rules = partition_rules
        if noise_mode not in ("auto", "program", "table"):
            raise ValueError(
                f"noise_mode must be auto|program|table, got {noise_mode!r}")
        self._noise_mode = (
            "program" if noise_mode == "auto" else noise_mode)
        if not shard_params and (model_shards is not None
                                 or partition_rules is not None
                                 or noise_mode != "auto"):
            raise ValueError(
                "model_shards/partition_rules/noise_mode configure the "
                "param-sharded engine; pass shard_params=True"
            )

        # scenario suite (estorch_tpu/scenarios, docs/scenarios.md):
        # domain randomization over the native env families — the env is
        # wrapped in a ScenarioEnv below, device paths only (host/pooled
        # agents step their envs host-side, where per-episode traced
        # physics constants have no representation)
        self._scenarios = scenarios
        if scenarios is not None:
            from ..scenarios import ScenarioDistribution

            if not isinstance(scenarios, ScenarioDistribution):
                raise TypeError(
                    "scenarios must be a ScenarioDistribution "
                    "(estorch_tpu.scenarios; e.g. "
                    "default_distribution(env, n_variants=10)), got "
                    f"{scenarios!r}")

        self._policy_arg = policy
        self._policy_kwargs = dict(policy_kwargs or {})
        self._agent_arg = agent
        self._agent_kwargs = dict(agent_kwargs or {})

        self.agent = _instantiate(agent, dict(agent_kwargs or {}), "agent")
        # Dispatch order matters: a reference-style Agent usually holds a
        # `self.env` (a *gym* env) AND a rollout() — the rollout contract is
        # the host marker, so it is checked first; `env` only routes to the
        # device path when it is a JaxEnv (pure reset/step + static dims).
        if hasattr(self.agent, "rollout"):
            if shard_params:
                raise ValueError(
                    "shard_params is a device-path option "
                    "(parallel/sharded.py); host torch agents replicate"
                )
            if compute_dtype != "float32":
                raise ValueError(
                    "compute_dtype is a device/pooled-path option; the host "
                    "backend runs torch policies in their native dtype"
                )
            if episodes_per_member != 1:
                raise ValueError(
                    "episodes_per_member is a device-path option; host agents "
                    "control their own rollout count inside rollout()"
                )
            if decomposed:
                raise ValueError(
                    "decomposed is a device-path option (models/decomposed.py)"
                )
            if noise_kernel:
                raise ValueError(
                    "noise_kernel is a device/pooled-path option "
                    "(ops/pallas_noise.py streams from the device table)"
                )
            if streamed:
                raise ValueError(
                    "streamed is a device-path option (ops/pallas_noise.py)"
                )
            if low_rank:
                raise ValueError(
                    "low_rank is a device-path option (ops/lowrank.py)"
                )
            if obs_norm:
                raise ValueError(
                    "obs_norm is a device/pooled-path option (running stats "
                    "ride the training state); host agents own their "
                    "rollouts — use models.TorchRunningObsNorm there"
                )
            if scenarios is not None:
                raise ValueError(
                    "scenarios is a device-path option: randomized physics "
                    "constants enter the jitted rollout as traced operands "
                    "(estorch_tpu/scenarios); host agents step their envs "
                    "in Python"
                )
            self.backend = "host"
            self._init_host(
                optimizer, dict(optimizer_kwargs or {}), table_size, device,
                weight_decay, worker_mode,
            )
            self._post_engine_init()
            return
        if worker_mode != "thread":
            raise ValueError(
                "worker_mode is a host-path option (thread|process); device/"
                "pooled paths parallelize on the mesh"
            )
        if _is_jax_env(getattr(self.agent, "env", None)):
            self.backend = "device"
        elif hasattr(self.agent, "env_name"):
            # pooled path: C++ envpool stepping + device-batched inference
            if shard_params:
                raise ValueError(
                    "shard_params needs device-native rollouts: the pooled "
                    "path materializes per-member thetas host-side, the "
                    "exact replicate the sharded engine exists to avoid"
                )
            if self._obs_warmup_episodes:
                raise ValueError(
                    "obs_warmup_episodes is a device-path option; the "
                    "pooled path's stats are fed by every member's "
                    "observations from generation 0, so its init "
                    "transient is one generation long already"
                )
            if scenarios is not None:
                raise ValueError(
                    "scenarios needs device-native rollouts (traced "
                    "physics constants); the pooled path steps C++ envs "
                    "host-side with compiled-in constants "
                    "(estorch_tpu/scenarios, docs/scenarios.md)"
                )
            self.backend = "pooled"
            self._init_pooled(
                policy, dict(policy_kwargs or {}), optimizer,
                dict(optimizer_kwargs or {}), table_size, eval_chunk,
                grad_chunk, weight_decay, mesh, device, vbn_batch,
            )
            self._post_engine_init()
            return
        else:
            raise TypeError(
                "agent must be a JaxAgent wrapping a JaxEnv (device path), a "
                "PooledAgent naming a native envpool env (pooled path), or a "
                "reference-style agent exposing rollout(policy) (host path)"
            )
        self.env = self.agent.env
        if scenarios is not None:
            # ONE wrapper serves every device engine (replicated fused,
            # split-path, sharded): ScenarioEnv implements the JaxEnv
            # protocol with the drawn params riding the env state as
            # traced operands, so engines compile exactly one program
            # regardless of variant count (compile-ledger proof in
            # bench.py --scenario-ab)
            from ..scenarios import ScenarioEnv

            self.env = ScenarioEnv(self.env, scenarios)
        _, obs0 = self.env.reset(jax.random.PRNGKey(0))

        def vbn_ref(vbn_key):
            return collect_reference_batch(self.env, vbn_key, n_steps=vbn_batch)

        if self._shard_params and mesh is None:
            from ..parallel.mesh import hyperscale_mesh

            devs = (
                [device] if device is not None
                and not isinstance(device, (list, tuple)) else device
            )
            mesh = hyperscale_mesh(model_shards=self._model_shards,
                                   devices=devs)
        flat, state_key = self._init_flax_common(
            policy, dict(policy_kwargs or {}), optimizer,
            dict(optimizer_kwargs or {}), obs0, self.agent.rollout_horizon,
            vbn_ref, table_size, eval_chunk, grad_chunk, weight_decay,
            mesh, device,
        )
        if self._shard_params:
            from ..parallel.sharded import ShardedESEngine

            if self._recurrent:
                raise ValueError(
                    "shard_params currently supports feedforward policies; "
                    "recurrent carries stay on the replicated engine "
                    "(docs/sharding.md)"
                )
            self.engine = ShardedESEngine(
                self.env, self._policy_apply, self._spec, self.table,
                self.optimizer, self.config, self.mesh,
                partition_rules=self._partition_rules,
                noise_mode=self._noise_mode,
            )
            self.state = self.engine.init_state(flat, state_key)
            self._post_engine_init()
            return
        dec_apply = None
        if self._decomposed:
            from ..models.decomposed import mlp_decomposed_apply, supports_decomposed

            if not supports_decomposed(self.module):
                raise ValueError(
                    "decomposed=True currently supports MLPPolicy without VBN "
                    "(models/decomposed.py); got "
                    f"{type(self.module).__name__}"
                )
            module = self.module

            def dec_apply(shared, noise, c, obs):
                return mlp_decomposed_apply(module, shared, noise, c, obs)

        str_apply = None
        if self._streamed:
            from ..models.decomposed import supports_decomposed
            from ..ops.pallas_noise import flat_layer_offsets, mlp_streamed_apply

            if not supports_decomposed(self.module):
                raise ValueError(
                    "streamed=True currently supports MLPPolicy without VBN "
                    f"(ops/pallas_noise.py); got {type(self.module).__name__}"
                )
            layer_offs = flat_layer_offsets(self._spec.unravel(flat))
            module = self.module
            table_data = self.table.data

            def str_apply(shared, offs, c, obs):
                return mlp_streamed_apply(
                    module, shared, table_data, offs, c, obs, layer_offs
                )

        lr_apply, lr_spec = None, None
        if self._low_rank:
            from ..models.decomposed import mlp_lowrank_apply, supports_decomposed
            from ..ops.lowrank import make_lowrank_spec, make_lowrank_tree_spec

            if self._recurrent:
                # recurrent form (round-4 verdict next #7): the generic
                # tree spec — factored noise for every 2-D kernel (trunk,
                # cell gates, head), per-episode materialization in the
                # engine, standard carry-threaded rollout.  No per-step
                # factored apply needed.
                lr_spec = make_lowrank_tree_spec(
                    self._spec.unravel(flat), self._low_rank
                )
            elif not supports_decomposed(self.module):
                raise ValueError(
                    "low_rank supports MLPPolicy without VBN "
                    "(ops/lowrank.py) and recurrent policies (tree form); "
                    f"got {type(self.module).__name__}"
                )
            else:
                lr_spec = make_lowrank_spec(
                    self._spec.unravel(flat), self._low_rank
                )
                module = self.module

                def lr_apply(shared, lrn, c, obs):
                    return mlp_lowrank_apply(module, shared, lrn, c, obs)

        self.engine = ESEngine(
            self.env, self._policy_apply, self._spec, self.table,
            self.optimizer, self.config, self.mesh,
            decomposed_apply=dec_apply,
            streamed_apply=str_apply,
            lowrank_apply=lr_apply,
            lowrank_spec=lr_spec,
            carry_init=self.module.carry_init if self._recurrent else None,
        )
        self.state = self.engine.init_state(flat, state_key)
        self._post_engine_init()

    def _init_flax_common(
        self, policy, policy_kwargs, optimizer, optimizer_kwargs, obs0,
        horizon, vbn_ref_fn, table_size, eval_chunk, grad_chunk,
        weight_decay, mesh, device,
    ):
        """Shared flax-path construction (device + pooled backends): module
        init from a real observation, frozen-collection split, VBN reference
        capture, param spec, noise table, optax, mesh, EngineConfig."""
        self.module = _instantiate(policy, policy_kwargs, "policy")
        self._recurrent = bool(getattr(self.module, "is_recurrent", False))
        init_key, state_key, vbn_key = jax.random.split(
            jax.random.PRNGKey(self.seed), 3
        )
        self._obs0 = obs0
        variables = self._module_init(init_key)
        params = variables["params"]
        self._frozen = {k: v for k, v in variables.items() if k != "params"}

        # VirtualBatchNorm: freeze reference-batch statistics once
        if "vbn_stats" in variables:
            if self._recurrent:
                raise ValueError(
                    "VirtualBatchNorm + recurrent policies is unsupported: "
                    "the reference-batch capture applies the module "
                    "statelessly (models/vbn.py)"
                )
            if self._obs_norm:
                raise ValueError(
                    "VirtualBatchNorm + obs_norm is unsupported: the VBN "
                    "reference batch is captured in RAW observation space "
                    "at init, so its frozen stats would mis-calibrate "
                    "against normalized rollout inputs — pick one input-"
                    "normalization scheme"
                )
            self._frozen["vbn_stats"] = capture_reference_stats(
                self.module, variables, vbn_ref_fn(vbn_key)
            )

        frozen = self._frozen

        if self._recurrent:

            def policy_apply(p, obs, h):
                return self.module.apply({"params": p, **frozen}, obs, h)

        else:

            def policy_apply(p, obs):
                return self.module.apply({"params": p, **frozen}, obs)

        self._policy_apply = policy_apply
        flat, self._spec = make_param_spec(params)
        # sharded program-mode noise never touches a table — don't spend
        # 4·table_size bytes of HBM on one (the whole point of in-program ε)
        self.table = (
            None if (self._shard_params and self._noise_mode != "table")
            else make_noise_table(table_size, seed=self.seed)
        )
        self.optimizer = _as_optax(optimizer, optimizer_kwargs)
        self.mesh = mesh if mesh is not None else population_mesh(
            [device] if device is not None and not isinstance(device, (list, tuple)) else device
        )
        self.config = EngineConfig(
            population_size=self.population_size,
            sigma=self.sigma,
            horizon=int(horizon),
            eval_chunk=eval_chunk,
            grad_chunk=grad_chunk,
            weight_decay=weight_decay,
            compute_dtype=self._compute_dtype,
            sigma_decay=self._sigma_decay,
            sigma_min=self._sigma_min,
            mirrored=self._mirrored,
            episodes_per_member=self._episodes_per_member,
            decomposed=self._decomposed,
            noise_kernel=self._noise_kernel,
            streamed=self._streamed,
            low_rank=self._low_rank,
            obs_norm=self._obs_norm,
            obs_clip=self._obs_clip,
            obs_probe_episodes=self._obs_probe_episodes,
            obs_warmup_episodes=self._obs_warmup_episodes,
        )
        return flat, state_key

    def _module_init(self, key):
        """Flax module init honoring the policy kind's apply contract —
        the ONE place that knows recurrent modules take a carry (used for
        both the main init and the novelty family's fresh meta-centers,
        so the two can never diverge)."""
        if self._recurrent:
            return self.module.init(key, self._obs0, self.module.carry_init())
        return self.module.init(key, self._obs0)

    def _post_engine_init(self):
        # the engine shares the ES's telemetry hub so sub-generation spans
        # (host sample/eval/update, pooled obsnorm merge, engine compile
        # events) land in the same per-generation accumulator
        self.engine.telemetry = self.obs
        # analytic FLOPs/bytes model of this configuration (obs/profile/):
        # rides the first generation record so `obs profile` can turn the
        # phase spans into achieved rates against a roofline.  Building it
        # unravels the device param tree to host, so skip the whole thing
        # when telemetry is off (set_cost_model would discard it anyway)
        if self.obs.enabled:
            self.obs.set_cost_model(self._build_cost_model())
        self._cost_model_emitted = False
        self.best_reward = -np.inf
        self._best_flat: np.ndarray | None = None
        self._best_policy_host = None
        self.history: list[dict] = []
        self.generation = 0
        self.compile_time_s: float | None = None
        self._eval_policy_fn = None  # lazily-built jitted eval rollout
        self._eval_gait_fn = None  # same, with the env-metrics channel
        self._predict_fn = None  # lazily-built jitted serving-parity predict

    # --------------------------------------------------------- pooled backend

    def _init_pooled(
        self, policy, policy_kwargs, optimizer, optimizer_kwargs,
        table_size, eval_chunk, grad_chunk, weight_decay, mesh, device, vbn_batch,
    ):
        from ..envs.gym_vec_pool import pool_env_spec
        from ..parallel.pooled import PooledEngine

        if getattr(policy, "learned_carry", False) or (
                policy_kwargs or {}).get("learned_carry"):
            raise ValueError(
                "learned_carry is a device-path feature: the pooled "
                "backend initializes episode carries before member params "
                "exist (parallel/pooled.py), so a params-dependent "
                "episode-start carry has no pooled form yet"
            )
        env_kwargs = getattr(self.agent, "env_kwargs", None)
        spec_info = pool_env_spec(self.agent.env_name, env_kwargs)
        prep = getattr(self.agent, "prep", None)
        if prep:
            from ..envs.atari_wrappers import apply_prep_to_spec

            spec_info = apply_prep_to_spec(spec_info, prep["frame_stack"])
        self.env = None
        obs0 = jnp.zeros(spec_info["obs_shape"], jnp.float32)

        def vbn_ref(vbn_key):
            del vbn_key  # pool RNG is numpy-seeded
            return self._pooled_reference_batch(vbn_batch)

        flat, state_key = self._init_flax_common(
            policy, policy_kwargs, optimizer, optimizer_kwargs, obs0,
            self.agent.horizon, vbn_ref, table_size, eval_chunk, grad_chunk,
            weight_decay, mesh, device,
        )
        self.engine = PooledEngine(
            self.agent.env_name, self._policy_apply, self._spec, self.table,
            self.optimizer, self.config, self.mesh,
            n_threads=self.agent.n_threads, seed=self.seed,
            double_buffer=getattr(self.agent, "double_buffer", False),
            prep=prep,
            carry_init=self.module.carry_init if self._recurrent else None,
            env_kwargs=env_kwargs,
            bc_indices=getattr(self.agent, "bc_indices", None),
        )
        self.state = self.engine.init_state(flat, state_key)

    def _pooled_reference_batch(self, n: int):
        """Random-action observations from the pool for VBN statistics,
        reshaped to the policy-facing observation shape (pixels etc.)."""
        from ..envs.gym_vec_pool import make_pool

        pool = make_pool(self.agent.env_name, max(1, n // 4),
                         env_kwargs=getattr(self.agent, "env_kwargs", None))
        prep = getattr(self.agent, "prep", None)
        if prep:
            # VBN statistics must be collected in the policy's actual input
            # distribution — stacked/repeated frames, not raw ones
            from ..envs.atari_wrappers import AtariPreprocessPool

            pool = AtariPreprocessPool(pool, seed=self.seed, **prep)
        rng = np.random.default_rng(self.seed)
        frames = [pool.reset()]
        for _ in range(4):
            if pool.discrete:
                acts = rng.integers(0, pool.n_actions, (pool.n_envs, 1)).astype(
                    np.float32
                )
            else:
                acts = rng.uniform(-1, 1, (pool.n_envs, pool.act_dim)).astype(np.float32)
            obs, _, _ = pool.step(acts)
            frames.append(obs)
        obs_shape = pool.obs_shape
        pool.close()
        batch = np.concatenate(frames, axis=0)[:n]
        return jnp.asarray(batch.reshape((-1,) + tuple(obs_shape)))

    # ----------------------------------------------------------- host backend

    def _init_host(self, optimizer, optimizer_kwargs, table_size, device,
                   weight_decay=0.0, worker_mode="thread"):
        """Reference-parity path: torch policy + host Agent.rollout workers."""
        import copy

        from ..host.engine import HostEngine

        policy_arg, policy_kwargs = self._policy_arg, self._policy_kwargs
        agent_arg, agent_kwargs = self._agent_arg, self._agent_kwargs

        if isinstance(policy_arg, type):
            def policy_factory():
                return policy_arg(**policy_kwargs)
        else:
            if policy_kwargs:
                raise ValueError(
                    "policy_kwargs were given alongside a policy instance; "
                    "pass the class, or the instance without kwargs"
                )
            def policy_factory():
                return copy.deepcopy(policy_arg)

        if isinstance(agent_arg, type):
            def agent_factory():
                return agent_arg(**agent_kwargs)
        else:
            # shared instance: workers would race on it — engine caps at the
            # instances it gets; we pin n_proc to 1 in train() via this flag
            def agent_factory():
                return agent_arg
        self._agent_is_shared_instance = not isinstance(agent_arg, type)

        self.env = None
        self.module = None
        # torch module init draws from torch's global RNG; pin it so two ES
        # constructions with the same seed get identical master policies
        # (the device path gets this for free from jax.random keys)
        import torch

        torch.manual_seed(self.seed)
        self.engine = HostEngine(
            policy_factory=policy_factory,
            agent_factory=agent_factory,
            optimizer_ctor=optimizer,
            optimizer_kwargs=optimizer_kwargs,
            population_size=self.population_size,
            sigma=self.sigma,
            table_size=table_size,
            seed=self.seed,
            n_proc=1,
            device="cpu" if device is None else str(device),
            prototype_agent=self.agent,  # dispatch probe doubles as worker 0
            weight_decay=weight_decay,
            worker_mode=worker_mode,
            sigma_decay=self._sigma_decay,
            sigma_min=self._sigma_min,
            mirrored=self._mirrored,
        )
        self.state = self.engine.init_state()

    # ------------------------------------------------------------------ train

    def train(
        self,
        n_steps: int,
        n_proc: int = 1,
        log_fn: Callable[[dict], None] | None = None,
        verbose: bool = True,
        max_consecutive_rejections: int = 3,
    ):
        """Run ``n_steps`` generations (reference: ``es.train(n_steps, n_proc)``).

        On the device path ``n_proc`` is accepted for API parity only (the
        mesh already parallelizes — SURVEY.md §2 'Parallelism strategies');
        on the host path it sizes the worker pool, exactly like the
        reference's ``train(n_steps, n_proc)``.

        Rejection policy (docs/resilience.md): a generation whose
        population collapsed (<2 valid members) or whose post-update
        parameters/norm came out non-finite is REJECTED — the state is
        restored to the pre-generation snapshot, ``generations_rejected``
        is counted, and the same generation re-runs (the noise stream is
        keyed on ``(key, generation)``, so a transient fault's re-run is
        bit-identical to a run that never faulted).  Up to
        ``max_consecutive_rejections`` consecutive rejections are
        retried; one more marks the fault persistent, not transient, and
        raises — with the pre-fault state intact.
        """
        self._setup_n_proc(n_proc)
        obs = self.obs
        # a previous generation that raised mid-phase (dead env,
        # catch-and-resume) must not leak its partial spans into the
        # first record of this call
        obs.discard_phases()
        if self.compile_time_s is None:
            # AOT-compile outside the timed loop so env_steps_per_sec (the
            # primary metric) never includes XLA trace+compile time
            obs.note("compile")
            self.compile_time_s = self.engine.compile(self.state)
        done = 0
        rejected_streak = 0
        while done < n_steps:
            t0 = time.perf_counter()
            prev_state = self.state
            if self.backend == "device":
                # the fused generation is ONE XLA program — the finest
                # honest split is dispatch (host python + trace lookup) /
                # device (fenced: everything up to the updated params) /
                # host_sync (D2H of the metrics).  sample/eval/update
                # live inside the program; the split-path algorithms
                # (novelty family) and the host/pooled engines emit them
                # as real spans (docs/observability.md span taxonomy)
                with obs.phase("dispatch"):
                    self.state, metrics = self.engine.generation_step(
                        prev_state)
                with obs.phase("device"):
                    if self._shard_params:
                        # donated sharded state: fence on the sharded
                        # leaves — .params_flat would GATHER the full
                        # vector every generation
                        jax.block_until_ready(
                            jax.tree_util.tree_leaves(self.state.params))
                    else:
                        jax.block_until_ready(self.state.params_flat)
                with obs.phase("host_sync"):
                    fitness = np.asarray(metrics["fitness"])
            else:
                # host/pooled engines span their own sample/eval/update
                self.state, metrics = self.engine.generation_step(
                    prev_state)
                fitness = np.asarray(metrics["fitness"])
                if self.backend != "host":
                    jax.block_until_ready(self.state.params_flat)
            dt = time.perf_counter() - t0

            # ---- anomaly guards: reject instead of training on poison ----
            # population collapse (every backend reports n_valid) and the
            # post-update non-finite check (metrics["update_finite"]) both
            # restore the pre-generation state; silently keeping a NaN
            # update would poison every subsequent generation
            n_valid = metrics.get("n_valid")
            reason = self._update_anomaly(metrics)
            if reason is not None:
                if self._shard_params:
                    # the donated program already rolled back in-program
                    # (same generation, params/opt untouched —
                    # parallel/sharded.py); prev_state's buffers are gone
                    pass
                else:
                    self.state = prev_state
                rejected_streak += 1
                obs.counters.inc("generations_rejected")
                obs.event("generation_rejected", reason=reason,
                          n_valid=int(n_valid) if n_valid is not None else -1)
                obs.discard_phases()  # the rejected generation's spans
                if rejected_streak > max_consecutive_rejections:
                    raise RuntimeError(
                        f"{reason}; {rejected_streak} consecutive "
                        "generations rejected — check env/rollout health"
                    )
                continue  # re-run the SAME generation (deterministic noise)
            rejected_streak = 0

            record = self._base_record(
                prev_state, fitness, int(metrics["steps"]),
                float(np.asarray(metrics["grad_norm"])), dt,
                metrics=metrics if self._shard_params else None,
            )
            self._attach_scenarios(record, fitness, metrics)
            self._emit_record(record, log_fn, verbose)
            done += 1
        return self

    def _attach_scenarios(self, record: dict, fitness, metrics) -> None:
        """Per-variant fitness block onto a generation record (and thus
        the obs hub) — the variant id is the BC's last column, the
        ScenarioEnv.behavior contract (docs/scenarios.md).  ONE
        definition shared by the sync loop and the overlap scheduler
        (algo/scheduler.py) so async records carry the same block."""
        if self._scenarios is None or "bc" not in (metrics or {}):
            return
        from ..scenarios import scenario_fitness_block, variant_of_bc

        record["scenarios"] = scenario_fitness_block(
            fitness, variant_of_bc(metrics["bc"]),
            self._scenarios.n_variants)

    def _update_anomaly(self, metrics) -> str | None:
        """The ONE definition of a rejectable generation (shared by
        ``train`` and the async schedulers, algo/scheduler.py): returns
        the rejection reason or None (docs/resilience.md)."""
        n_valid = metrics.get("n_valid")
        if n_valid is not None and int(n_valid) < 2:
            return (
                f"only {int(n_valid)}/{self.population_size} population "
                "members produced valid fitness — cannot form an update"
            )
        if not bool(np.asarray(metrics.get("update_finite", True))):
            return ("non-finite parameters/update norm after the "
                    "optimizer step")
        return None

    # ------------------------------------------------- async generations

    def train_async(
        self,
        n_steps: int,
        n_proc: int = 1,
        log_fn: Callable[[dict], None] | None = None,
        verbose: bool = True,
        max_consecutive_rejections: int = 3,
        strategy: str = "auto",
        max_stale: int = 16,
        iw_clip: float = 2.0,
        replay=None,
    ):
        """Barrier-free generations (docs/async.md, algo/scheduler.py).

        ``strategy``: ``"fold"`` (host backend) runs the event-driven
        scheduler — rollouts are member/slice tasks on worker queues,
        an update fires per population's-worth of ARRIVED results, and
        late results (chaos stragglers, slow pooled workers) fold into
        the current update with clipped importance weights keyed on the
        σ/θ they were sampled under, instead of being waited on.
        ``"overlap"`` (device/pooled/sharded; also valid on host)
        pipelines generation g+1's program dispatch with generation g's
        host-side tail — bit-identical to ``train``.  ``"auto"`` picks
        fold on host, overlap elsewhere.

        ``max_stale``: fold horizon in center versions — older results
        are discarded with evidence (``stale_discarded``).  ``iw_clip``:
        IMPACT-style truncation of the mean-normalized importance
        ratios.  ``replay``: an :class:`~estorch_tpu.algo.scheduler.
        AsyncEventLog` (or its dict form) — re-drive that recorded
        schedule instead of running live; bit-identical parameters.
        The live run's log is left on ``es.async_event_log``.
        """
        from .scheduler import GenerationScheduler, train_overlap

        if strategy not in ("auto", "fold", "overlap"):
            raise ValueError(
                f"strategy must be auto|fold|overlap, got {strategy!r}")
        if strategy == "auto":
            strategy = "fold" if self.backend == "host" else "overlap"
        self._setup_n_proc(n_proc)
        if strategy == "overlap":
            if replay is not None:
                raise ValueError(
                    "replay re-drives a fold-mode event log; the overlap "
                    "scheduler is bit-identical to train() already")
            return train_overlap(
                self, n_steps, log_fn=log_fn, verbose=verbose,
                max_consecutive_rejections=max_consecutive_rejections)
        sched = GenerationScheduler(
            self, max_stale=max_stale, iw_clip=iw_clip,
            max_consecutive_rejections=max_consecutive_rejections)
        if replay is not None:
            return sched.replay(replay, log_fn=log_fn, verbose=verbose,
                                n_steps=n_steps)
        return sched.run(n_steps, log_fn=log_fn, verbose=verbose)

    def train_elastic(
        self,
        n_steps: int,
        fleet=None,
        log_fn: Callable[[dict], None] | None = None,
        verbose: bool = True,
        max_consecutive_rejections: int = 3,
        max_stale: int = 16,
        iw_clip: float = 2.0,
        replay=None,
    ):
        """Elastic multi-host generations (docs/multihost.md,
        parallel/elastic.py): remote hosts evaluate whole-population
        dispatches as async sources, THIS process folds their
        contributions with clipped importance weights and broadcasts
        only the O(dim) center per update.  A slow host costs
        throughput, a dead host costs ``results_lost`` (replaced by
        extra dispatches) — never the fleet.

        ``fleet`` is an :class:`~estorch_tpu.parallel.elastic.
        ElasticCoordinator` hosts have joined / will join (membership is
        elastic — joining mid-run is the point).  ``replay`` re-drives a
        recorded :class:`~estorch_tpu.algo.scheduler.AsyncEventLog` as
        pure math (no fleet needed): bit-identical parameters.  The live
        run's log is left on ``es.async_event_log``."""
        from .scheduler import ElasticScheduler

        if fleet is None and replay is None:
            raise ValueError(
                "train_elastic needs a fleet (ElasticCoordinator) to run "
                "live, or replay= to re-drive a recorded log")
        sched = ElasticScheduler(
            self, fleet, max_stale=max_stale, iw_clip=iw_clip,
            max_consecutive_rejections=max_consecutive_rejections)
        if replay is not None:
            return sched.replay(replay, log_fn=log_fn, verbose=verbose,
                                n_steps=n_steps)
        return sched.run(n_steps, log_fn=log_fn, verbose=verbose)

    @property
    def async_event_log(self):
        """The last ``train_async``/``train_elastic`` fold run's
        deterministic event log (None before any fold-mode run)."""
        return getattr(self, "_async_log", None)

    def _setup_n_proc(self, n_proc: int) -> None:
        if self.backend != "host":
            return
        if getattr(self, "_agent_is_shared_instance", False) and n_proc > 1:
            import warnings

            warnings.warn(
                "agent was passed as a shared instance; host workers would "
                "race on it — running with n_proc=1. Pass the agent CLASS "
                "(with agent_kwargs) to parallelize.",
                stacklevel=3,
            )
            n_proc = 1
        self.engine.set_n_proc(n_proc)

    def _build_cost_model(self) -> dict | None:
        """Analytic per-phase FLOPs/bytes for THIS configuration
        (obs/profile/costmodel.py): policy matmul shapes from the live
        parameter tree, population/horizon/noise-representation from the
        config.  Diagnostic only — returns None rather than ever failing
        construction (an exotic policy without 2-D kernels has no matmul
        model, and that is a note in ``obs profile``, not an error)."""
        from ..obs.profile.costmodel import generation_cost

        try:
            if self.backend == "host":
                params = list(self.engine.master.parameters())
                shapes = [tuple(p.shape) for p in params if p.dim() == 2]
                param_dim = int(sum(p.numel() for p in params))
                horizon = None  # host agents own their rollout length
                dtype_bytes, episodes = 4, 1
            else:
                params = jax.tree_util.tree_leaves(
                    self._spec.unravel(self.state.params_flat))
                shapes = [tuple(int(d) for d in p.shape)
                          for p in params if getattr(p, "ndim", 0) == 2]
                param_dim = int(self._spec.dim)
                horizon = int(self.config.horizon)
                dtype_bytes = 2 if self._compute_dtype == "bfloat16" else 4
                episodes = int(self.config.episodes_per_member)
            if not shapes:
                return None
            mesh = getattr(self, "mesh", None)
            n_devices = int(mesh.devices.size) if mesh is not None else 1
            model_shards = 1
            if self._shard_params:
                from ..parallel.mesh import MODEL_AXIS

                model_shards = int(dict(zip(
                    mesh.axis_names, mesh.devices.shape))[MODEL_AXIS])
            return generation_cost(
                population=self.population_size, matmul_shapes=shapes,
                param_dim=param_dim, horizon=horizon,
                episodes_per_member=episodes, mirrored=self._mirrored,
                low_rank=self._low_rank, dtype_bytes=dtype_bytes,
                noise=(self._noise_mode if self._shard_params else "table"),
                n_devices=n_devices, model_shards=model_shards)
        except Exception:  # noqa: BLE001 — diagnostic, never construction
            return None

    # ------------------------------------------- shared generation plumbing

    def _track_best(self, prev_state, fitness: np.ndarray,
                    metrics: dict | None = None) -> tuple[float, bool]:
        """Best-member snapshot (reference: es.best_policy/best_reward).
        Returns (generation max, whether a new best was set).

        NaN-aware: failed members (host fault tolerance marks them NaN) must
        not disable best tracking or poison the metrics.

        ``metrics`` is the sharded path's donated-state protocol: the
        generation program already reconstructed the best member's θ
        (``metrics["best_theta"]``, sharded) because ``prev_state`` —
        which ``member_params`` would need — was donated; the gather
        happens only on improvement.
        """
        finite_any = np.isfinite(fitness).any()
        gen_best = float(np.nanmax(fitness)) if finite_any else float("nan")
        improved = finite_any and gen_best > self.best_reward
        if improved:
            self.best_reward = gen_best
            idx = int(np.nanargmax(fitness))
            if metrics is not None and "best_theta" in metrics:
                from jax.flatten_util import ravel_pytree

                self._best_flat = np.asarray(
                    ravel_pytree(metrics["best_theta"])[0])
            else:
                self._best_flat = np.asarray(
                    self.engine.member_params(prev_state, idx))
        return gen_best, improved

    def _base_record(self, prev_state, fitness, steps, grad_norm, dt,
                     metrics: dict | None = None) -> dict:
        with self.obs.phase("record"):
            # best-member snapshot can dispatch a device program
            # (member_params) — it deserves phase attribution too
            gen_best, improved = self._track_best(prev_state, fitness,
                                                  metrics)
        finite_any = np.isfinite(fitness).any()
        record = {
            "generation": self.generation,
            "reward_max": gen_best,
            "reward_mean": float(np.nanmean(fitness)) if finite_any else float("nan"),
            "reward_min": float(np.nanmin(fitness)) if finite_any else float("nan"),
            "n_failed": int(np.size(fitness) - np.isfinite(fitness).sum()),
            "best_reward": self.best_reward,
            "improved_best": improved,
            "env_steps": steps,
            "env_steps_per_sec": steps / dt if dt > 0 else 0.0,
            "grad_norm": grad_norm,
            # the sharded path donates prev_state — its pre-step σ rides
            # the metrics instead of a (deleted) state buffer
            "sigma": float(np.asarray(metrics["sigma"]))
            if metrics is not None and "sigma" in metrics
            else float(np.asarray(prev_state.sigma))
            if hasattr(prev_state, "sigma") and prev_state.sigma is not None
            else self.sigma,
            "wall_time_s": dt,
        }
        return self._finalize_record(record)

    def _finalize_record(self, record: dict) -> dict:
        """Record plumbing shared by every train loop (sync, fold,
        overlap — algo/scheduler.py builds its own core dict and calls
        this): span flush, compile-ledger merge, one-shot cost model,
        run-level counters."""
        # flush this generation's span accumulator into the record and
        # export the run-level counters (obs/summarize.py consumes both)
        record["phases"] = self.obs.take_phases()
        # performance-attribution facts ride the same record: compile-
        # ledger entries since the last flush, and (once per run) the
        # analytic cost model — `obs profile` joins them with the spans
        compile_events = self.obs.take_compile_events()
        if compile_events:
            record["compile_events"] = compile_events
        if not self._cost_model_emitted and self.obs.cost_model is not None:
            record["cost_model"] = self.obs.cost_model
            self._cost_model_emitted = True
        self.obs.counters.inc("env_steps", record["env_steps"])
        if record["n_failed"]:
            self.obs.counters.inc("rollout_failures", record["n_failed"])
        return record

    def _emit_record(self, record: dict, log_fn, verbose: bool) -> None:
        self.history.append(record)
        self.generation += 1
        if log_fn is not None:
            log_fn(record)
        elif verbose:
            print(self._format_record(record))

    def _format_record(self, r: dict) -> str:
        return (
            f"gen {r['generation']:4d}  "
            f"max {r['reward_max']:9.2f}  "
            f"mean {r['reward_mean']:9.2f}  "
            f"best {r['best_reward']:9.2f}  "
            f"steps/s {r['env_steps_per_sec']:,.0f}"
        )

    # ----------------------------------------------------------- observability

    def run_manifest(self, extra: dict | None = None) -> dict:
        """Immutable facts of THIS run (obs/manifest.py): algorithm +
        backend config, jax version, device topology, git sha.  Safe to
        call any time after construction — the backend is already up, so
        reading device attributes cannot wedge a cold runtime."""
        from ..obs.manifest import collect_manifest

        cfg = {
            "algorithm": type(self).__name__,
            "backend": self.backend,
            "population_size": self.population_size,
            "sigma": self.sigma,
            "seed": self.seed,
            "compute_dtype": self._compute_dtype,
            "mirrored": self._mirrored,
            "obs_norm": self._obs_norm,
            "low_rank": self._low_rank,
            "decomposed": self._decomposed,
            "streamed": self._streamed,
            "shard_params": self._shard_params,
        }
        if self._scenarios is not None:
            # scenario provenance: the distribution spec + draw seed ARE
            # the scenarios (draws are deterministic in them), so the
            # manifest names exactly what this run trained under
            cfg["scenarios"] = self._scenarios.spec_json()
        if self._shard_params:
            from ..parallel.mesh import partition_rules_to_json

            cfg["noise_mode"] = self._noise_mode
            cfg["mesh_axes"] = dict(zip(
                self.mesh.axis_names,
                [int(s) for s in self.mesh.devices.shape]))
            cfg["partition_rules"] = partition_rules_to_json(
                self.engine.partition_rules)
        mesh = getattr(self, "mesh", None)
        devices = list(mesh.devices.flat) if mesh is not None else None
        return collect_manifest(config=cfg, devices=devices, extra=extra)

    def write_manifest(self, path: str, extra: dict | None = None) -> str:
        from ..obs.manifest import write_manifest

        return write_manifest(path, self.run_manifest(extra))

    # ------------------------------------------------------------- inspection

    @property
    def policy(self):
        """Current center policy (reference: es.policy).

        Device path: the flax params pytree.  Host path: the torch master
        module loaded with the current center parameters — exactly the
        reference's ``es.policy``.
        """
        if self.backend == "host":
            self.engine._load(self.engine.master, self.state.params_flat)
            return self.engine.master
        return self._spec.unravel(self.state.params_flat)

    @property
    def policy_variables(self):
        """Full flax variables for ``module.apply`` (params + frozen stats)."""
        if self.backend == "host":
            raise AttributeError("policy_variables is device-path only; use .policy")
        return {"params": self.policy, **self._frozen}

    @property
    def best_policy(self):
        """Best-ever member's parameters (reference: es.best_policy)."""
        if self._best_flat is None:
            return self.policy
        if self.backend == "host":
            if self._best_policy_host is None:
                self._best_policy_host = self.engine.policy_factory()
            self.engine._load(self._best_policy_host, self._best_flat)
            return self._best_policy_host
        return self._spec.unravel(jnp.asarray(self._best_flat))

    @property
    def best_policy_variables(self):
        if self.backend == "host":
            raise AttributeError("best_policy_variables is device-path only; use .best_policy")
        return {"params": self.best_policy, **self._frozen}

    def evaluate_policy(self, n_episodes: int = 10, use_best: bool = False,
                        seed: int = 0, meta_index: int | None = None,
                        return_details: bool = False):
        """Mean/std episode return of the current (or best) policy.

        The reference's users hand-roll this with ``agent.rollout(es.policy)``
        loops; here it is one vmapped compiled program on the device path,
        one batched pooled pass on the pooled path (all episodes step
        concurrently in native threads — ``seed`` picks the episode set on
        both), and the engine's own serial center-evaluation on the host
        path (episode randomness from the env RNG; host agents own their
        rollouts).  ``meta_index`` selects a specific meta-population center
        (novelty family; default = center 0, the one ``es.policy`` exposes).

        ``return_details=True`` adds per-episode arrays: ``rewards``
        (n_episodes,) and — device/pooled paths — ``bc`` (n_episodes, bc_dim),
        the behavior characterizations (e.g. final torso position for the
        locomotion family), for studies that measure more than the return.
        On the device path it also adds ``steps`` (n_episodes,) and — for
        envs exposing the gait-metrics protocol (``step_metrics`` /
        ``episode_metrics``, the locomotion family) — ``gait``: per-episode
        arrays such as ``forward_velocity_mps`` and ``upright_fraction``,
        so "it walks" is stated in m/s and %-upright, not reward units.
        """
        if meta_index is not None:
            if not hasattr(self, "meta_states"):
                raise ValueError(
                    "meta_index applies to the novelty family (NS/NSR/NSRA)"
                )
            if use_best:
                raise ValueError(
                    "use_best evaluates the GLOBAL best member snapshot — "
                    "it cannot be combined with meta_index (per-center eval)"
                )
            base_state = self.meta_states[meta_index]
        else:
            base_state = self.state
        use_best = use_best and self._best_flat is not None
        if self.backend == "device":
            flat = jnp.asarray(self._best_flat) if use_best else base_state.params_flat
            want_gait = return_details and hasattr(self.env, "step_metrics")
            fn = self._eval_gait_fn if want_gait else self._eval_policy_fn
            if fn is None:
                from ..envs.rollout import make_rollout

                apply_fn = self._policy_apply
                if self._obs_norm:
                    from ..parallel.engine import normalize_obs

                    base_apply, clip = self._policy_apply, self._obs_clip
                    if self._recurrent:
                        def apply_fn(packed, obs, h):
                            p, stats = packed
                            return base_apply(
                                p, normalize_obs(obs, stats, clip), h
                            )
                    else:
                        def apply_fn(packed, obs):
                            p, stats = packed
                            return base_apply(p, normalize_obs(obs, stats, clip))
                single = make_rollout(
                    self.env, apply_fn, self.config.horizon,
                    carry_init=self.module.carry_init if self._recurrent else None,
                    with_env_metrics=want_gait,
                )
                # one cached callable: jit re-specializes per n_episodes shape
                fn = jax.jit(jax.vmap(single, in_axes=(None, 0)))
                if want_gait:
                    self._eval_gait_fn = fn
                else:
                    self._eval_policy_fn = fn
            keys = jax.random.split(jax.random.PRNGKey(seed), n_episodes)
            p = self._spec.unravel(flat)
            if self._obs_norm:
                # evaluate with the CURRENT running stats (also for use_best:
                # the snapshot's own stats are part of training state, and
                # the freshest moments are the best estimate of the env)
                p = (p, base_state.obs_stats)
            gait_sums = None
            if want_gait:
                res, gait_sums = fn(p, keys)
                gait_sums = np.asarray(gait_sums)
            else:
                res = fn(p, keys)
            rewards = np.asarray(res.total_reward)
            bc = np.asarray(res.bc)
            eval_steps = np.asarray(res.steps)
        elif self.backend == "pooled":
            # engines read only state.params_flat (+ obs_stats), so a
            # params-swapped state evaluates the requested policy
            flat = self._best_flat if use_best else base_state.params_flat
            eval_state = base_state._replace(params_flat=jnp.asarray(flat))
            res = self.engine.evaluate_center_batch(
                eval_state, n_episodes, seed=seed
            )
            rewards = np.asarray(res.fitness, np.float32)
            bc = np.asarray(res.bc)
        else:
            # host path: torch agents own their rollouts — serial by design
            flat = self._best_flat if use_best else base_state.params_flat
            eval_state = base_state._replace(
                params_flat=np.asarray(flat, np.float32)
            )
            rewards = np.asarray(
                [
                    float(self.engine.evaluate_center(eval_state).total_reward)
                    for _ in range(n_episodes)
                ],
                np.float32,
            )
            bc = None
        out = {
            "mean": float(rewards.mean()),
            "std": float(rewards.std()),
            "min": float(rewards.min()),
            "max": float(rewards.max()),
            "episodes": int(n_episodes),
        }
        if return_details:
            out["rewards"] = rewards
            out["bc"] = bc
            if self.backend == "device":
                out["steps"] = eval_steps
                if gait_sums is not None:
                    per_ep = [
                        self.env.episode_metrics(bc[i], eval_steps[i],
                                                 gait_sums[i])
                        for i in range(n_episodes)
                    ]
                    out["gait"] = {
                        k: np.asarray([m[k] for m in per_ep], np.float32)
                        for k in per_ep[0]
                    }
        return out

    def predict(self, obs, use_best: bool = False, carry=None):
        """Policy forward pass with current (or best) parameters.

        Recurrent policies return ``(out, new_carry)``; pass the returned
        carry back in on the next step (``carry=None`` starts an episode).

        Runs through the SAME jitted program the serving stack builds
        (serve/predictor.py) — normalization composed inside, params and
        running obs stats as arguments — so an exported bundle's
        ``predict`` and a server's batched responses are bit-comparable
        to this method (docs/serving.md "Bit-exactness contract").
        Batched ``obs`` (leading batch axis) is supported and lands in
        the same execution family as the server's bucketed batches.
        """
        if self.backend == "host":
            import torch

            policy = self.best_policy if use_best else self.policy
            with torch.no_grad():
                return policy(torch.as_tensor(np.asarray(obs), dtype=torch.float32))
        p = self.best_policy if use_best else self.policy
        obs = jnp.asarray(obs)
        stats = self.state.obs_stats if self._obs_norm else None
        if self._predict_fn is None:
            from ..serve.predictor import make_single_predict

            self._predict_fn = make_single_predict(
                self._policy_apply, recurrent=self._recurrent,
                obs_norm=self._obs_norm, obs_clip=self._obs_clip,
            )
        if self._recurrent:
            if carry is None:
                # same compat contract as make_rollout: a custom module
                # with the historical zero-arg carry_init() must work here
                # exactly as it does in the rollout path
                from ..envs.rollout import carry_init_takes_params

                ci = self.module.carry_init
                if not hasattr(self, "_ci_takes_params"):
                    self._ci_takes_params = carry_init_takes_params(ci)
                carry = ci(p) if self._ci_takes_params else ci()
            return self._predict_fn(p, stats, obs, carry)
        return self._predict_fn(p, stats, obs)

    # ---------------------------------------------------------------- serving

    def export_bundle(self, path: str, use_best: bool = False,
                      version: str | int | None = None,
                      extra: dict | None = None, **kwargs) -> str:
        """Export this policy as a versioned serving bundle (serve/bundle.py):
        params + frozen stats + obs-normalization moments + a manifest
        (module spec, git sha, jax version, provenance), committed
        atomically.  Serve it with ``python -m estorch_tpu.serve --bundle
        <path>`` (docs/serving.md)."""
        from ..serve.bundle import export_bundle

        return export_bundle(self, path, use_best=use_best, version=version,
                             extra=extra, **kwargs)
