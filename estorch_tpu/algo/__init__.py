from .archive import NoveltyArchive
from .es import ES
from .nses import NS_ES, NSR_ES, NSRA_ES

__all__ = ["ES", "NS_ES", "NSR_ES", "NSRA_ES", "NoveltyArchive"]
