from .es import ES

__all__ = ["ES"]
