from .archive import NoveltyArchive
from .es import ES
from .iwes import IW_ES
from .nses import NS_ES, NSR_ES, NSRA_ES

__all__ = ["ES", "IW_ES", "NS_ES", "NSR_ES", "NSRA_ES", "NoveltyArchive"]
