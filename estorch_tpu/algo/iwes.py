"""IW-ES — importance-weighted reuse of the previous generation's rollouts.

PAPERS.md "Importance Weighted Evolution Strategies" (1811.04624): after the
center moves θ_t → θ_{t+1}, the generation-t members θ_i = θ_t + σ_t s_i ε_i
are still valid Monte-Carlo samples for the gradient at θ_{t+1} — under the
new search distribution they are the perturbations

    ε'_i = (θ_i − θ_{t+1}) / σ_{t+1} = d + c·s_i ε_i,
    d = (θ_t − θ_{t+1})/σ_{t+1},   c = σ_t/σ_{t+1}

with importance ratio

    λ_i = N(θ_i; θ_{t+1}, σ²_{t+1}) / N(θ_i; θ_t, σ²_t)
        = c^dim · exp((‖ε_i‖² − ‖ε'_i‖²)/2).

Each generation this class evaluates the fresh population as usual, then
forms the update from fresh members with their ranks PLUS up to
``reuse_window`` previous generations' members with rank × self-normalized
λ (each buffered generation admitted independently by its own ESS) — up to
(1+W)× the effective sample count per rollout budget.  The classic failure mode (a big center move
collapses the ratios) is guarded by the effective sample size
ESS = (Σλ)²/Σλ²: when ESS/n_old < ``ess_min`` the stale set is dropped and
the generation proceeds as vanilla ES.  (The c^dim prefactor is common to
every member, so self-normalization cancels it — collapse comes from the
SPREAD of the per-member exponents: big center moves, or c ≠ 1 amplifying
the ‖ε‖² spread at large dim.  Annealed runs therefore still fall back to
no-reuse naturally; the guard handles it, no special case.)

Nothing about the reused set is re-evaluated and no old noise is stored:
old ε_i regenerate from the shared table via the PREVIOUS state's offsets
(the same derivation every device already performs —
engine.all_pair_offsets), old fitness is a host-side (n,) float array, and
the two device passes the reuse needs (per-sample ε·d / ‖ε‖², and the
Σ wλε update term) are sharded psum/all_gather programs
(parallel/engine.py::noise_stats / apply_weights_reuse).

Device path only; low_rank is not supported (packed factor noise has no
dense ε for the ratio), and the host/pooled backends raise as usual.
Checkpoint/resume: the reuse ring is deliberately NOT part of run state —
post-resume generations run vanilla until the ring refills (utils/
checkpoint.py stays bit-exact for everything that matters).
"""

from __future__ import annotations

import collections
import time
from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..ops.gradient import fold_mirrored_weights
from ..utils.fault import rank_weights_with_failures
from .es import ES


def stale_log_ratios(dots, norms, d2: float, c: float, dim: int):
    """Per-member log importance ratios of samples drawn under an older
    (θ_old, σ_old) seen from the current (θ_new, σ_new) — THE IW-ES
    formula (module docstring), shared by :class:`IW_ES` and the async
    scheduler's late-result fold (algo/scheduler.py).

    ``dots`` are the SIGNED per-member ε·d values (s_i already applied;
    the mirrored expansion is the caller's job), ``norms`` the per-member
    ‖ε‖², ``d2`` = ‖d‖² with d = (θ_old − θ_new)/σ_new, ``c`` =
    σ_old/σ_new.  Returns log λ (unnormalized — λ only ever enters
    self-normalized, so callers shift by the max before exponentiating).
    """
    dots = np.asarray(dots)
    norms = np.asarray(norms)
    eps_new_sq = d2 + 2.0 * c * dots + c * c * norms
    return dim * np.log(c) + 0.5 * (norms - eps_new_sq)


def mirrored_member_stats(dots, norms):
    """Expand per-PAIR noise stats (``engine.noise_stats``) to the
    mirrored member layout — member 2k = +ε_k, member 2k+1 = −ε_k, the
    ops/noise.py convention every estimator in this family leans on.
    One home for the sign/repeat rule so the three λ computations
    (IW_ES, worker fold, host fold) can never drift apart."""
    dots = np.asarray(dots)
    return (np.repeat(dots, 2) * np.tile([1.0, -1.0], dots.shape[0]),
            np.repeat(np.asarray(norms), 2))


def clipped_stale_lambdas(dots, norms, d2: float, c: float, dim: int,
                          iw_clip: float) -> np.ndarray:
    """Per-member truncated importance weights for ONE stale source —
    the fold rule shared verbatim by the worker-granular and
    host-granular schedulers (docs/async.md, docs/multihost.md):
    :func:`stale_log_ratios`, max-shift stabilization (λ only ever
    enters self-normalized; shift-invariant in log space), mean-1
    self-normalization within the source (IW-ES), then IMPACT's
    truncation at ``iw_clip`` so one wild ratio cannot hijack the
    update.  ``dots`` are SIGNED per-member values (mirrored expansion
    already applied)."""
    log_lam = stale_log_ratios(dots, norms, d2, c, dim)
    log_lam -= log_lam.max()
    lam = np.exp(log_lam)
    lam = lam * (len(lam) / max(lam.sum(), 1e-30))
    return np.minimum(lam, iw_clip).astype(np.float32)


class IW_ES(ES):
    """ES with importance-weighted reuse of the previous generation."""

    def __init__(self, *args, ess_min: float = 0.5, reuse_window: int = 1,
                 **kwargs):
        if not 0.0 < ess_min <= 1.0:
            raise ValueError(f"ess_min must be in (0, 1], got {ess_min}")
        if reuse_window < 1:
            raise ValueError(f"reuse_window must be >= 1, got {reuse_window}")
        self.ess_min = float(ess_min)
        self.reuse_window = int(reuse_window)
        super().__init__(*args, **kwargs)
        if self.backend != "device":
            raise ValueError(
                "IW_ES is a device-path algorithm (the reuse terms are "
                f"sharded table reductions); got backend={self.backend!r}"
            )
        if self._low_rank:
            raise ValueError(
                "IW_ES does not support low_rank — and not merely as "
                "pending work: the reused perturbation seen from the "
                "drifted center, dense(v) + (c_old - c_new)/sigma, "
                "generally has no rank-r preimage, so the factor-space "
                "importance ratio is ill-posed (ROADMAP item 7)"
            )
        if self._streamed or self._noise_kernel:
            raise ValueError(
                "IW_ES supports the standard/decomposed forwards; "
                "streamed/noise_kernel are untested with reuse"
            )
        if self._obs_norm:
            raise ValueError(
                "IW_ES does not support obs_norm: buffered generations' "
                "fitness was measured under OLDER running stats, so the "
                "effective policy f(θ) the density ratio assumes fixed "
                "drifts with the normalization — the reuse estimate would "
                "be silently biased"
            )
        # newest-last ring of minimal per-generation reuse records:
        # (params_flat, sigma, pair_offsets, fitness).  Deliberately NOT the
        # whole ESState — that would pin reuse_window copies of the optax
        # moments (~3·W·dim floats) on device for nothing; offsets are
        # computed ONCE here since they are a pure function of the state
        self._prev = collections.deque(maxlen=self.reuse_window)
        self._dry_gens = 0  # consecutive full-ring generations with no reuse
        self._dry_best_ess = 0.0  # best ESS seen anywhere in the dry streak
        self._warned_never_reusing = False

    def train(
        self,
        n_steps: int,
        n_proc: int = 1,
        log_fn: Callable[[dict], None] | None = None,
        verbose: bool = True,
    ):
        self._setup_n_proc(n_proc)
        obs = self.obs
        obs.discard_phases()  # drop partial spans from an aborted generation
        if self.compile_time_s is None:
            obs.note("compile")
            self.compile_time_s = self.engine.compile_split(self.state)
            self.compile_time_s += self._warm_reuse_programs()
        n = self.population_size
        for _ in range(n_steps):
            t0 = time.perf_counter()
            st = self.state
            with obs.phase("eval"):
                ev = self.engine.evaluate(st)
                fitness = np.asarray(ev.fitness)  # fences the eval program
            # base-class parity BEFORE anything mutates: a dead env (fewer
            # than 2 valid FRESH members) must hard-fail with state intact —
            # reuse must not let stale samples train through a dead generation
            if int(np.isfinite(fitness).sum()) < 2:
                raise RuntimeError(
                    f"only {int(np.isfinite(fitness).sum())}/{n} population "
                    "members produced valid fitness — cannot form an update; "
                    "check env/rollout health"
                )

            # admit each buffered generation independently by its own ESS
            with obs.phase("reuse_ratios"):
                accepted, best_ess = [], 0.0
                for entry in self._prev:
                    lam, d_vec, c, offs = self._ratios(entry, st)
                    ess = (
                        float(lam.sum() ** 2 / (lam**2).sum())
                        if lam.sum() > 0 else 0.0
                    )
                    best_ess = max(best_ess, ess)
                    if ess >= self.ess_min * n:
                        accepted.append((entry[3], lam, d_vec, c, offs))
            reused = bool(accepted)
            with obs.phase("update"):
                if reused:
                    self._dry_gens = 0
                    self._dry_best_ess = 0.0
                    new_st, gnorm = self._reuse_update(st, fitness, accepted)
                else:
                    if len(self._prev) == self.reuse_window:
                        self._dry_gens += 1
                        self._dry_best_ess = max(self._dry_best_ess, best_ess)
                        self._maybe_warn_never_reusing()
                    weights = jnp.asarray(rank_weights_with_failures(fitness))
                    new_st, gnorm = self.engine.apply_weights(st, weights)
                jnp.asarray(new_st.params_flat).block_until_ready()

            self.state = new_st
            with obs.phase("sample"):
                # buffer this generation for future reuse.  The offsets
                # program is left async on purpose (its consumer is next
                # generation's ratio pass) — this span clocks dispatch +
                # the σ host copy, not the offsets compute
                self._prev.append((
                    st.params_flat, float(np.asarray(st.sigma)),
                    self.engine.all_pair_offsets(st), fitness,
                ))
            dt = time.perf_counter() - t0

            record = self._base_record(
                st, fitness, int(ev.steps), float(np.asarray(gnorm)), dt
            )
            record.update(
                reused_prev=reused,
                reused_gens=len(accepted),
                ess=round(best_ess, 2),
                effective_samples=n * (1 + len(accepted)),
            )
            self._emit_record(record, log_fn, verbose)
        return self

    # ------------------------------------------------------------ internals

    DRY_WARN_AFTER = 20

    def _maybe_warn_never_reusing(self) -> None:
        """One-time diagnostic when the ESS guard rejects every generation.

        The log-ratio spread is d·ε ~ N(0, ‖Δθ/σ‖²), so reuse survives only
        when the per-generation center move is small: with a coordinate-wise
        optimizer (Adam) that means lr ≲ σ/√dim.  Users who pick a
        known-good vanilla-ES lr are usually 10× above that and silently get
        vanilla ES at IW-ES prices — say so once, with the fix."""
        if self._warned_never_reusing or self._dry_gens < self.DRY_WARN_AFTER:
            return
        self._warned_never_reusing = True
        import warnings

        sigma = float(np.asarray(self.state.sigma))
        warnings.warn(
            f"IW_ES: no generation passed the ESS guard in the last "
            f"{self._dry_gens} generations (best ESS over the streak "
            f"{self._dry_best_ess:.1f} < ess_min*n = "
            f"{self.ess_min * self.population_size:.1f}); every "
            "update ran as vanilla ES while paying the ratio-computation "
            "overhead. The center is moving too far per generation for "
            "reuse: shrink the step so that lr ≲ sigma/sqrt(dim) "
            f"(≈ {sigma / max(self._spec.dim, 1) ** 0.5:.1e} here), or raise "
            "sigma, or drop back to plain ES.",
            RuntimeWarning,
            stacklevel=3,
        )

    def _warm_reuse_programs(self) -> float:
        """Trace+compile noise_stats and every reuse-window shape of
        apply_weights_reuse OUTSIDE the timed loop (the codebase invariant:
        the primary metric env_steps_per_sec never includes compile time).
        The concatenated old set can be any of 1..reuse_window generations
        long, so each length is a distinct XLA program — warm them all."""
        t0 = time.perf_counter()
        st = self.state
        offsets = self.engine.all_pair_offsets(st)
        zeros_d = jnp.zeros_like(st.params_flat)
        self.engine.noise_stats(offsets, zeros_d)
        n_rows = int(offsets.shape[0])
        dummy_w = jnp.zeros((self.population_size,), jnp.float32)
        for w in range(1, self.reuse_window + 1):
            out, _ = self.engine.apply_weights_reuse(
                st, dummy_w,
                jnp.tile(offsets, w), jnp.zeros((n_rows * w,), jnp.float32),
                jnp.tile(zeros_d[None, :], (w, 1)),
                jnp.zeros((w,), jnp.float32),
            )
            jnp.asarray(out.params_flat).block_until_ready()
        dt = time.perf_counter() - t0
        # one ledger entry for the whole reuse-window warm: reuse_window+1
        # distinct XLA programs (noise_stats + one apply_weights_reuse per
        # window length), traced+executed so only wall seconds are known
        self.obs.compile_event("iwes_reuse_warm", dt,
                               count_recompiles=self.reuse_window + 1,
                               programs=self.reuse_window + 1,
                               first_call=True)
        return dt

    def _ratios(self, entry, st):
        """Per-old-member importance ratios λ under the CURRENT state.

        ``entry`` is a ring record (params_flat, sigma, pair_offsets,
        fitness) — see train()."""
        prev_params, sigma_old, offsets, _ = entry
        sigma_new = float(np.asarray(st.sigma))
        c = sigma_old / sigma_new
        d_vec = (prev_params - st.params_flat) / sigma_new
        dots, norms = self.engine.noise_stats(offsets, d_vec)
        dots, norms = np.asarray(dots), np.asarray(norms)
        d2 = float(jnp.vdot(d_vec, d_vec))
        if self._mirrored:
            dots, norms = mirrored_member_stats(dots, norms)
        log_lam = stale_log_ratios(dots, norms, d2, c, self._spec.dim)
        # log-sum-exp style stabilization: λ only ever enters self-normalized
        # (λ̃ and ESS are shift-invariant in log space)
        log_lam -= log_lam.max()
        return np.exp(log_lam), d_vec, c, offsets

    def _reuse_update(self, st, fitness, accepted):
        """One combined-estimator update: fresh ranks + λ-weighted old ranks
        from every accepted generation.

        Scaling contract with engine.apply_weights_reuse: fresh weights are
        rescaled by n/n_tot so the engine's 1/(n·σ) denominator becomes
        1/(n_tot·σ); the old-side coefficients arrive fully scaled.
        """
        n = self.population_size
        n_tot = n * (1 + len(accepted))
        sigma_new = float(np.asarray(st.sigma))

        combined = np.concatenate([fitness] + [a[0] for a in accepted])
        w_all = rank_weights_with_failures(combined)
        w_fresh = w_all[:n]

        old_w_parts, offs_parts, d_rows, coeff_rows = [], [], [], []
        for g, (prev_fit, lam, d_vec, c, offs) in enumerate(accepted):
            w_old = w_all[n * (g + 1): n * (g + 2)]
            lam_tilde = lam * (n / max(lam.sum(), 1e-30))  # mean-1 normalized
            w_old_eff = w_old * lam_tilde
            # old ε-term: Σ w λ̃ (d + c·s·ε) → the s·ε part folds per pair
            if self._mirrored:
                folded = fold_mirrored_weights(jnp.asarray(w_old_eff))
            else:
                folded = jnp.asarray(w_old_eff)
            old_w_parts.append(folded * (c / (n_tot * sigma_new)))
            offs_parts.append(offs)
            d_rows.append(d_vec)
            coeff_rows.append(w_old_eff.sum() / (n_tot * sigma_new))

        weights = jnp.asarray(w_fresh * (n / n_tot))
        return self.engine.apply_weights_reuse(
            st, weights,
            jnp.concatenate(offs_parts), jnp.concatenate(old_w_parts),
            jnp.stack(d_rows), jnp.asarray(coeff_rows, jnp.float32),
        )
