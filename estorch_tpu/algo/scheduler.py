"""Barrier-free async generations: the event-driven ES scheduler.

``ES.train`` is a hard per-generation barrier: one straggler sets the
step time, and eval + update costs ADD instead of overlapping
(ROADMAP item 2).  This module removes the barrier two ways, picked per
backend by ``ES.train_async``:

**fold** (host backend, thread + process workers) — the IMPACT
architecture (PAPERS.md, arxiv 1912.00167) built on the IW-ES math
(arxiv 1811.04624, ``algo/iwes.py``):

- member rollouts are *tasks* on an event queue, not a blocking gather:
  workers continuously drain whatever is dispatched, and the scheduler
  keeps roughly two populations in flight so a straggler occupies one
  worker instead of the whole generation;
- an *update* fires whenever one population's worth of results has
  arrived — regardless of which dispatch they came from.  Results
  sampled under an older center (θ_s, σ_s) are FOLDED in with clipped
  importance weights (λ self-normalized per source dispatch, clipped at
  ``iw_clip`` — IMPACT's truncated ratios; the ratio formula is the
  IW-ES one, keyed on the σ/θ the sample was drawn under) instead of
  being discarded or waited on;
- results staler than ``max_stale`` center versions are discarded WITH
  EVIDENCE: the ``stale_discarded`` counter and the event log record
  every one — nothing is silently dropped;
- a deterministic event log records every dispatch (and the center
  version it sampled), every update's consumed set (in arrival order,
  with observed fitness/steps), and every discard.  :meth:`replay`
  re-drives the recorded schedule as pure math — bit-identical
  parameters, every time, independent of wall clock, chaos, or load.

**overlap** (device / pooled / sharded backends) — the fused generation
is one XLA program with no partial results to fold; the barrier there
is the host-side fence + record keeping between dispatches.  The
overlap scheduler submits generation g+1's program from a background
thread before generation g's metrics are materialized, so the host-side
tail (fence, D2H, best-member tracking, record emit) runs while the
device executes the next generation.  Same program sequence, same
states: bit-identical to the synchronous loop.

Resilience contracts preserved (docs/resilience.md): the post-update
anomaly guard rejects non-finite updates with the pre-update center
intact (fold mode re-applies the same batch; overlap mode discards the
speculative program and re-runs — on the sharded engine the speculative
step consumed the in-program-rolled-back state, which makes it the
deterministic re-run itself), chaos hooks fire with the same
once-semantics (member faults keyed on the dispatch index, which is the
generation number in the synchronous loop), and ``es.state`` /
``es.generation`` advance only at update boundaries so checkpoint /
supervisor resume see the same protocol as the synchronous loop.

Telemetry (docs/observability.md): ``async/dispatch`` and ``async/fold``
spans on the shared hub, ``overlap_efficiency`` and
``stale_reuse_ratio`` gauges, ``results_folded`` / ``stale_discarded``
/ ``results_lost`` / ``speculative_discarded`` counters, and a per-
update ``record["async"]`` block that ``obs summarize`` renders as the
async section.
"""

from __future__ import annotations

import dataclasses
import math
import queue
import threading
import time

import numpy as np

from ..host.engine import member_sign_offset
from ..resilience.chaos import member_fault, mutate_fitness
from ..utils.fault import rank_weights_with_failures
from .iwes import clipped_stale_lambdas, mirrored_member_stats

# short poll slice for every blocking point in the event loop: the loop
# must wake to notice dead workers / shutdown, never sleep unbounded
# (esguard R11 blocking-wait-in-scheduler is this rule, mechanized)
POLL_SLICE_S = 0.05


def _count_quantile(counts: dict[int, int], q: float) -> float:
    """Exact nearest-rank quantile over a value → count dict (the
    staleness distribution: small bounded integers)."""
    total = sum(counts.values())
    k = max(1, math.ceil(q * total))
    cum = 0
    for v in sorted(counts):
        cum += counts[v]
        if cum >= k:
            return float(v)
    return float(max(counts))


@dataclasses.dataclass(frozen=True)
class Source:
    """What one dispatch sampled under — the (θ, σ) the importance
    ratio of every late result from it is keyed on."""

    dispatch: int  # dispatch index == the noise-stream generation number
    version: int  # center version (update count) at dispatch time
    params: np.ndarray  # (dim,) float32 center snapshot
    sigma: float
    offsets: np.ndarray  # per-pair (mirrored) or per-member table offsets
    t_dispatch: float = 0.0  # perf_counter at snapshot (0 in replay)


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One member's result landing on the event queue."""

    dispatch: int
    member: int
    fitness: float
    steps: int
    eval_s: float  # worker busy seconds (straggler sleeps included)
    t_arrival: float = 0.0  # perf_counter at event-queue entry (0 in replay)


class AsyncEventLog:
    """The deterministic schedule of a fold-mode run.

    JSON-able; :meth:`GenerationScheduler.replay` consumes it.  The log
    is the full accounting contract: every dispatched member appears in
    exactly one of ``consumed`` (in the fold's canonical order, with the
    fitness/steps the update actually ranked — the importance weight
    re-derives from the sources), ``discarded`` (too stale or past run
    end, counted), or ``lost`` (its worker died, counted)."""

    def __init__(self):
        self.dispatches: list[list] = []  # [dispatch, version]
        self.updates: list[dict] = []
        self.discarded: list[list] = []  # [dispatch, member]
        self.lost: list[list] = []  # [dispatch, member]
        # elastic multi-host runs (parallel/elastic.py) additionally
        # record membership transitions: {"event": "join"|"leave",
        # "host": id, "at_dispatch": count}.  Forensic, not replayed —
        # replay is pure math over dispatches/updates; membership is
        # WHY the schedule looked the way it did
        self.membership: list[dict] = []

    def to_dict(self) -> dict:
        out = {
            "schema": 1,
            "dispatches": [list(d) for d in self.dispatches],
            "updates": self.updates,
            "discarded": [list(d) for d in self.discarded],
            "lost": [list(d) for d in self.lost],
        }
        if self.membership:
            out["membership"] = [dict(m) for m in self.membership]
        return out

    @classmethod
    def from_dict(cls, data: dict) -> "AsyncEventLog":
        log = cls()
        log.dispatches = [list(d) for d in data.get("dispatches", [])]
        log.updates = list(data.get("updates", []))
        log.discarded = [list(d) for d in data.get("discarded", [])]
        log.lost = [list(d) for d in data.get("lost", [])]
        log.membership = [dict(m) for m in data.get("membership", [])]
        return log


# ---------------------------------------------------------------------
# result sources: who evaluates dispatched members and how results
# arrive.  Thread source = member-granular; process source = slice-
# granular over the ProcessPool async API.
# ---------------------------------------------------------------------


class _ThreadSource:
    """Member-granular task pool over scheduler-OWNED scratch workers.

    Each worker thread owns one (scratch policy, agent) pair and drains
    a shared task queue; results land on the scheduler's event queue.
    A chaos straggler sleeps inside ONE worker's rollout — the others
    keep draining, which is the whole point.

    The scratch pairs are built fresh here rather than borrowed from
    ``engine._workers``: ``close()`` bounds its join (R11), so a
    straggler can outlive the run as a leaked daemon thread — it must
    then be touching only objects a subsequent ``train()`` call will
    never load a new θ into."""

    def __init__(self, engine, events: "queue.Queue"):
        from ..host.engine import HostEngine

        self.engine = engine
        self.events = events
        self._tasks: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._call_rollout = HostEngine._call_rollout
        self._workers = [
            (engine._new_scratch_policy(), engine.agent_factory())
            for _ in range(engine.n_proc)
        ]
        self._threads = [
            threading.Thread(target=self._worker, args=(w,), daemon=True)
            for w in range(engine.n_proc)
        ]
        for t in self._threads:
            t.start()

    def dispatch(self, source: Source) -> list[int]:
        """Queue every member of ``source``; returns the member list."""
        members = list(range(self.engine.population_size))
        for i in members:
            self._tasks.put((source, i))
        return members

    def _worker(self, w: int) -> None:
        policy, agent = self._workers[w]
        eng = self.engine
        while not self._stop.is_set():
            try:
                source, i = self._tasks.get(timeout=POLL_SLICE_S)
            except queue.Empty:
                continue
            sign, off = member_sign_offset(source.offsets, i, eng.mirrored)
            theta = source.params + source.sigma * sign * eng._eps(off)
            eng._load(policy, theta)
            t0 = time.perf_counter()
            try:
                # chaos keyed on the dispatch index — the same
                # (generation, member) coordinates a synchronous run's
                # plan uses, with the same fire-once semantics
                member_fault(source.dispatch, i)
                res = self._call_rollout(agent, policy)
                fit, steps = res.total_reward, res.steps
            except Exception:  # noqa: BLE001 — NaN marks the member failed
                fit, steps = float("nan"), 0
            t1 = time.perf_counter()
            self.events.put(Arrival(source.dispatch, i, float(fit),
                                    int(steps), t1 - t0, t1))

    def poll_lost(self, timeout_s: float = POLL_SLICE_S
                  ) -> list[tuple[int, int]]:
        return []  # threads don't die silently; exceptions became NaN

    def notify_update(self, version: int, state) -> None:
        pass  # workers read θ from the Source snapshot, not a push

    def close(self) -> None:
        self._stop.set()
        for w, t in enumerate(self._threads):
            t.join(timeout=5.0)
            if t.is_alive():
                # a straggler outliving the bounded join leaks as a
                # daemon thread — harmless (it holds only scheduler-
                # owned scratch), but it must leave evidence (R08)
                self.engine.telemetry.counters.inc("worker_threads_leaked")
                self.engine.telemetry.event("worker_thread_leaked",
                                            worker=w)

    @property
    def n_workers(self) -> int:
        return len(self._threads)


class _ProcessSource:
    """Slice-granular dispatch over the ProcessPool async API.

    One message per (dispatch, worker); a slow slice delays only its
    own members.  Late replies are returned by ``ProcessPool.poll`` —
    never discarded by sequence tag — and a worker that died with
    slices outstanding surrenders them as LOST (counted, evented)."""

    def __init__(self, engine, events: "queue.Queue"):
        self.engine = engine
        self.events = events
        self._ensure_pool()
        # seq -> (dispatch, member indices, worker) for loss accounting
        self._outstanding: dict[int, tuple[int, list[int], int]] = {}
        self._lost_now: list[tuple[int, int]] = []

    def _ensure_pool(self):
        from ..host.procpool import ProcessPool

        eng = self.engine
        if eng._proc_pool is None or eng._proc_pool.n_proc != eng.n_proc:
            if eng._proc_pool is not None:
                eng._proc_pool.close()
            eng._proc_pool = ProcessPool(
                eng.policy_factory, eng.agent_factory, eng.n_proc,
                eng.population_size, eng.dim, eng.table,
                master_state=eng.master.state_dict(),
                mirrored=eng.mirrored,
            )
        eng._proc_pool.telemetry = eng.telemetry
        self.pool = eng._proc_pool

    def dispatch(self, source: Source) -> list[int]:
        from ..resilience.chaos import kill_workers

        # respawn closes dead workers' pipes, which would ORPHAN their
        # outstanding slices (never swept as lost — a permanent phantom
        # in the scheduler's inflight set): drain whatever they managed
        # to buffer, surrender the rest as lost, THEN respawn
        self._drain(0.0)
        self._sweep_dead(final=True)
        self.pool.respawn_dead()  # dispatch boundary = respawn boundary
        killed = kill_workers(source.dispatch, self.pool.worker_pids)
        if killed:
            self.engine.telemetry.counters.inc("chaos_worker_kills",
                                               len(killed))
            self.engine.telemetry.event("chaos_worker_kill", pids=killed,
                                        gen=int(source.dispatch))
        members: list[int] = []
        n, w_n = self.engine.population_size, self.pool.n_proc
        for w in range(w_n):
            indices = list(range(w, n, w_n))
            seq = self.pool.dispatch(
                w, source.params, source.sigma, source.offsets,
                source.dispatch, indices=None,
            )
            if seq is None:
                # send failed (dead pipe): the slice is lost up front
                self._lose(source.dispatch, indices)
                continue
            self._outstanding[seq] = (source.dispatch, indices, w)
            members.extend(indices)
        return members

    def _lose(self, dispatch: int, indices: list[int]) -> None:
        tel = self.engine.telemetry
        tel.counters.inc("results_lost", len(indices))
        tel.event("results_lost", dispatch=int(dispatch), n=len(indices))
        self._lost_now.extend((dispatch, i) for i in indices)

    def _drain(self, timeout_s: float) -> None:
        """Pull buffered replies into the event queue.  A zero timeout
        drains only what is already readable; repeated until dry because
        one wait round returns at most one message per connection."""
        while True:
            got = self.pool.poll(timeout_s)
            for seq, indices, fitness, _bc, steps, eval_s in got:
                info = self._outstanding.pop(seq, None)
                if info is None:
                    continue  # a reply from a pre-scheduler sequence
                dispatch, _, _ = info
                k = max(len(indices), 1)
                per = eval_s / k
                base_steps, rem = divmod(int(steps), k)
                t_arr = time.perf_counter()
                for j, i in enumerate(indices):
                    # remainder spread keeps the slice's step total
                    # EXACT — env_steps is the headline metric
                    self.events.put(Arrival(
                        dispatch, int(i), float(fitness[j]),
                        base_steps + (1 if j < rem else 0), per, t_arr))
            if not got:
                return
            timeout_s = 0.0  # first wait bounded; the rest just drain

    def _sweep_dead(self, final: bool = False) -> None:
        """Account slices owned by dead workers as lost.  ``final``
        surrenders even slices whose pipe still has buffered data (the
        caller is about to close those pipes); otherwise drainable
        replies are left for the next poll."""
        dead = {w for w in range(self.pool.n_proc)
                if not self.pool.worker_alive(w)}
        if not dead:
            return
        for seq in [s for s, (_, _, w) in self._outstanding.items()
                    if w in dead]:
            dispatch, indices, w = self._outstanding[seq]
            if not final and self.pool.conn_has_data(w):
                continue  # buffered reply — the next drain gets it
            del self._outstanding[seq]
            self._lose(dispatch, indices)

    def poll_lost(self, timeout_s: float = POLL_SLICE_S
                  ) -> list[tuple[int, int]]:
        """Drain arrived slices into the event queue; returns members
        lost to dead workers (accumulated since the last call)."""
        self._drain(timeout_s)
        # slices owned by workers that died with an empty pipe never
        # arrive: account them as lost so nothing is silently dropped
        self._sweep_dead(final=False)
        out, self._lost_now = self._lost_now, []
        return out

    def notify_update(self, version: int, state) -> None:
        pass  # workers read θ from the Source snapshot, not a push

    def close(self) -> None:
        pass  # the pool belongs to the engine; HostEngine.close owns it

    @property
    def n_workers(self) -> int:
        return self.pool.n_proc


# ---------------------------------------------------------------------
# the fold scheduler
# ---------------------------------------------------------------------


class GenerationScheduler:
    """Event-driven barrier-free generations for the host backend.

    One instance drives one ``es.train_async`` call; ``run`` is the
    live event loop, ``replay`` re-drives a recorded schedule."""

    def __init__(self, es, max_stale: int = 16, iw_clip: float = 2.0,
                 max_consecutive_rejections: int = 3):
        self._check_es(es)
        if max_stale < 1:
            raise ValueError(f"max_stale must be >= 1, got {max_stale}")
        if iw_clip < 1.0:
            raise ValueError(
                f"iw_clip must be >= 1 (1 = mean-normalized ratios fully "
                f"truncated), got {iw_clip}")
        self.es = es
        self.engine = es.engine
        self.obs = es.obs
        self.max_stale = int(max_stale)
        self.iw_clip = float(iw_clip)
        self.max_consecutive_rejections = int(max_consecutive_rejections)
        self.n = es.population_size
        self.log = AsyncEventLog()
        self._sources: dict[int, Source] = {}
        self._consumed_total = 0
        self._folded_total = 0
        self._discarded_total = 0
        # causal/tail accounting for the CURRENT update window: dispatch
        # ids snapshotted and results discarded since the last record —
        # the record's `async` block carries them so `obs trace` can draw
        # the dispatch → fold/discard flow arrows
        self._dispatched_since_update: list[int] = []
        self._discards_since_update: dict[int, int] = {}
        # exact staleness distribution: value → count.  Staleness is a
        # SMALL INTEGER (bounded by max_stale), so the log-seconds hist
        # ladder would distort it (0 → the underflow midpoint ~9e-6);
        # this dict is bounded by max_stale+1 keys and quantiles walk it
        # exactly
        self._staleness_counts: dict[int, int] = {}

    # -------------------------------------------------- backend hooks
    # (the elastic host-granular scheduler overrides these; everything
    # else — pacing, staleness, accounting, replay — is shared)

    def _check_es(self, es) -> None:
        if es.backend != "host":
            raise ValueError(
                "GenerationScheduler folds partial host results; device/"
                "pooled/sharded backends use the overlap scheduler "
                f"(got backend={es.backend!r})"
            )

    def _sigma_of(self, st) -> float:
        return float(self.engine._state_sigma(st))

    def _offsets_for(self, st, dispatch: int) -> np.ndarray:
        return np.asarray(
            self.engine._pair_offsets(st._replace(generation=dispatch)))

    def _ensure_compiled(self) -> None:
        es = self.es
        if es.compile_time_s is None:
            self.obs.note("compile")
            es.compile_time_s = self.engine.compile(es.state)

    def _make_source(self, events: "queue.Queue"):
        source_cls = (_ProcessSource
                      if self.engine.worker_mode == "process"
                      else _ThreadSource)
        return source_cls(self.engine, events)

    def _inflight_budget(self, src_pool) -> int:
        """Member count to keep in flight beyond the arrived backlog —
        one population here (the loop adds a population per dispatch, so
        ~2 stay in flight); the elastic scheduler scales it by live
        hosts."""
        return self.n

    # ------------------------------------------------------------ sources

    def _snapshot(self, dispatch: int, version: int) -> Source:
        """Freeze the center the noise stream of ``dispatch`` samples
        under.  Offsets derive from (key, dispatch) exactly like the
        synchronous loop's (key, generation) — dispatch d of an async
        run and generation d of a sync run draw the same noise."""
        st = self.es.state
        src = Source(
            dispatch=dispatch, version=version,
            params=np.array(np.asarray(st.params_flat), np.float32,
                            copy=True),
            sigma=self._sigma_of(st),
            offsets=self._offsets_for(st, dispatch),
            t_dispatch=time.perf_counter(),
        )
        self._sources[dispatch] = src
        self.log.dispatches.append([dispatch, version])
        self._dispatched_since_update.append(dispatch)
        self.obs.event("async_dispatch", trace=f"d{dispatch}",
                       dispatch=int(dispatch), version=int(version))
        return src

    def _prune_sources(self, version: int,
                       referenced: set[int] = frozenset()) -> None:
        """Drop snapshots no longer foldable (staler than max_stale —
        the exact complement of the fold-eligibility rule, so a still-
        consumable source is never pruned) and not referenced by any
        in-flight or arrived-but-unconsumed result — bounded memory
        however long the run."""
        for d in [d for d, s in self._sources.items()
                  if s.version < version - self.max_stale
                  and d not in referenced]:
            del self._sources[d]

    # ---------------------------------------------------------- fold math

    def _fold_batch(self, batch: list[Arrival], version: int):
        """One combined update from a mixed-staleness batch.

        Pure given (center state, sources, batch): the live loop and
        :meth:`replay` share it, which is WHY replay is bit-identical.
        Members are processed sorted by (dispatch, member) so the float
        summation order depends only on batch membership, not on the
        arrival interleave inside the batch."""
        eng = self.engine
        st = self.es.state
        batch = sorted(batch, key=lambda a: (a.dispatch, a.member))
        fit = np.asarray([a.fitness for a in batch], np.float32)
        # chaos nan_fitness keyed on the state's generation number —
        # the same coordinate the sync loop's gather mutation uses
        fit = mutate_fitness(int(st.generation), fit)
        n_valid = int(np.isfinite(fit).sum())
        if n_valid < 2:
            return None, None, fit, {"n_valid": n_valid}
        w = rank_weights_with_failures(fit)
        sigma_u = self._sigma_of(st)
        center = np.asarray(st.params_flat, np.float32)
        dim = eng.dim

        grad = np.zeros(dim, np.float32)
        n_fresh = 0
        lam_stale: list[float] = []
        by_dispatch: dict[int, list[int]] = {}
        for j, a in enumerate(batch):
            by_dispatch.setdefault(a.dispatch, []).append(j)
        with self.obs.phase("async"):
            with self.obs.phase("fold"):
                for d in sorted(by_dispatch):
                    src = self._sources[d]
                    idx = by_dispatch[d]
                    k = len(idx)
                    signs = np.empty(k, np.float32)
                    offs = np.empty(k, np.int64)
                    for kk, j in enumerate(idx):
                        sign, off = member_sign_offset(
                            src.offsets, batch[j].member, eng.mirrored)
                        signs[kk] = sign
                        offs[kk] = off
                    if src.version == version:
                        lam = np.ones(k, np.float32)
                        c = 1.0
                        d_vec = None
                        n_fresh += k
                    else:
                        d_vec = ((src.params - center) / sigma_u).astype(
                            np.float32)
                        c = src.sigma / sigma_u
                        dots = np.empty(k, np.float32)
                        norms = np.empty(k, np.float32)
                        for kk in range(k):
                            eps = eng._eps(int(offs[kk]))
                            dots[kk] = float(eps @ d_vec) * signs[kk]
                            norms[kk] = float(eps @ eps)
                        d2 = float(d_vec @ d_vec)
                        lam = clipped_stale_lambdas(dots, norms, d2, c,
                                                    dim, self.iw_clip)
                        lam_stale.extend(float(x) for x in lam)
                    coeff = (np.asarray([w[j] for j in idx], np.float32)
                             * lam)
                    # ε'_i = d + c·s_i·ε_i — the reused perturbation seen
                    # from the CURRENT center (fresh: d=0, c=1 → s·ε),
                    # streamed row-by-row from zero-copy table views like
                    # the synchronous apply_weights (no (k, dim) temp)
                    for kk in range(k):
                        grad += ((coeff[kk] * signs[kk] * c)
                                 * eng._eps(int(offs[kk])))
                    if d_vec is not None:
                        grad += float(coeff.sum()) * d_vec
        grad /= len(batch) * sigma_u
        with self.obs.phase("update"):
            new_state, gnorm = eng.apply_grad(st, grad)
        stats = {
            "n_valid": n_valid,
            "fresh": n_fresh,
            "folded": len(batch) - n_fresh,
            "mean_lambda": (round(float(np.mean(lam_stale)), 4)
                            if lam_stale else None),
            "max_staleness": version - min(
                self._sources[d].version for d in by_dispatch),
            # (dispatch, member count) pairs this update consumed — the
            # causal half of the record's async block (`obs trace` flow
            # arrows link each dispatch to the update that folded it)
            "consumed_by_dispatch": [[int(d), len(by_dispatch[d])]
                                     for d in sorted(by_dispatch)],
        }
        return new_state, gnorm, fit, stats

    def _best_theta(self, arrival: Arrival) -> np.ndarray:
        src = self._sources[arrival.dispatch]
        sign, off = member_sign_offset(src.offsets, arrival.member,
                                       self.engine.mirrored)
        return src.params + src.sigma * sign * np.asarray(
            self.engine._eps(off))

    # -------------------------------------------------------- update step

    def _apply_update(self, batch: list[Arrival], version: int,
                      t_start, log_fn, verbose: bool,
                      rejected_streak: int) -> tuple[bool, int]:
        """Rank + fold + anomaly-guard + record for one batch.
        ``t_start`` is when the previous update finished (None in
        replay); the record's wall window closes AFTER the fold+apply so
        the update's own cost is inside it.  Returns (applied,
        rejected_streak)."""
        es = self.es
        obs = self.obs
        new_state, gnorm, fit, stats = self._fold_batch(batch, version)
        dt = (time.perf_counter() - t_start) if t_start is not None else 0.0
        # the shared rejection policy (ES._update_anomaly — the ONE
        # definition): feed it the same metrics shape the engines report
        reason = es._update_anomaly({
            "n_valid": stats["n_valid"],
            "update_finite": bool(
                new_state is not None and np.isfinite(gnorm)
                and np.isfinite(new_state.params_flat).all()),
        })
        if reason is not None:
            # the center was never touched (apply_grad returns a NEW
            # state); count, event, and re-apply the same batch — chaos
            # nan_update fires once, so the re-apply is clean
            obs.counters.inc("generations_rejected")
            obs.event("generation_rejected", reason=reason,
                      n_valid=int(stats["n_valid"]))
            obs.discard_phases()
            rejected_streak += 1
            if rejected_streak > self.max_consecutive_rejections:
                raise RuntimeError(
                    f"{reason}; {rejected_streak} consecutive updates "
                    "rejected — check env/rollout health")
            return False, rejected_streak

        # best tracking with source-aware member reconstruction;
        # fit is in sorted-batch order (the fold's canonical order)
        batch_sorted = sorted(batch, key=lambda a: (a.dispatch, a.member))
        finite_any = bool(np.isfinite(fit).any())
        gen_best = float(np.nanmax(fit)) if finite_any else float("nan")
        improved = finite_any and gen_best > es.best_reward
        if improved:
            es.best_reward = gen_best
            es._best_flat = np.asarray(
                self._best_theta(batch_sorted[int(np.nanargmax(fit))]),
                np.float32)

        # dispatch-lifecycle distributions (docs/observability.md "Tails
        # & traces"): judged per CONSUMED member at the accepted fold —
        # a rejected batch's retry must not double-observe.  Wall-clock
        # legs (arrival→fold queue wait, dispatch→fold latency) are
        # live-only (t_start is None in replay, whose clocks are fake);
        # staleness is pure math and recorded in both.
        t_now = time.perf_counter() if t_start is not None else None
        for a in batch:
            src = self._sources[a.dispatch]
            staleness = version - src.version
            self._staleness_counts[staleness] = (
                self._staleness_counts.get(staleness, 0) + 1)
            # the hub histogram (exported by /metrics) uses a ladder
            # sized for small integers, not the default seconds ladder
            obs.hists.observe("async/staleness", staleness,
                              lo=0.5, decades=4, per_decade=3)
            if t_now is not None:
                if a.t_arrival:
                    obs.hists.observe("async/queue_wait_s",
                                      t_now - a.t_arrival)
                if src.t_dispatch:
                    obs.hists.observe("async/fold_latency_s",
                                      t_now - src.t_dispatch)

        steps = int(sum(a.steps for a in batch))
        sigma = self._sigma_of(es.state)
        es.state = new_state
        # the log append rides IMMEDIATELY on the state transition: the
        # two together are "this batch was consumed" — anything raising
        # later (record plumbing, a user log_fn) must not let the run
        # loop re-queue or the shutdown sweep double-account the batch
        self.log.updates.append({
            "u": version,
            "consumed": [[a.dispatch, a.member, float(fit[j]), a.steps]
                         for j, a in enumerate(batch_sorted)],
        })
        self._consumed_total += len(batch)
        self._folded_total += int(stats["folded"])
        busy = sum(a.eval_s for a in batch)
        oe = self._overlap_efficiency(busy, dt)
        record = {
            "generation": es.generation,
            "reward_max": gen_best,
            "reward_mean": (float(np.nanmean(fit)) if finite_any
                            else float("nan")),
            "reward_min": (float(np.nanmin(fit)) if finite_any
                           else float("nan")),
            "n_failed": int(np.size(fit) - np.isfinite(fit).sum()),
            "best_reward": es.best_reward,
            "improved_best": improved,
            "env_steps": steps,
            "env_steps_per_sec": steps / dt if dt > 0 else 0.0,
            "grad_norm": float(gnorm),
            "sigma": sigma,
            "wall_time_s": dt,
            "async": {
                "consumed": len(batch),
                "fresh": int(stats["fresh"]),
                "folded": int(stats["folded"]),
                "stale_discarded": int(
                    sum(self._discards_since_update.values())),
                "max_staleness": int(stats["max_staleness"]),
                "mean_lambda": stats["mean_lambda"],
                "overlap_efficiency": oe,
                # causal identity: dispatches snapshotted this window,
                # (dispatch, count) consumed by THIS update, (dispatch,
                # count) discarded this window — `obs trace` renders
                # them as flow arrows (docs/observability.md)
                "dispatches": [int(d) for d in
                               self._dispatched_since_update],
                "consumed_dispatches": stats["consumed_by_dispatch"],
                "discarded_dispatches": [
                    [int(d), int(n)] for d, n in
                    sorted(self._discards_since_update.items())],
            },
        }
        qw50 = obs.hists.quantile("async/queue_wait_s", 0.5)
        qw99 = obs.hists.quantile("async/queue_wait_s", 0.99)
        if qw50 is not None and qw99 is not None:
            record["async"]["queue_wait_s"] = {"p50": round(qw50, 6),
                                               "p99": round(qw99, 6)}
        if self._staleness_counts:
            record["async"]["staleness_q"] = {
                "p50": _count_quantile(self._staleness_counts, 0.5),
                "p99": _count_quantile(self._staleness_counts, 0.99)}
        self._dispatched_since_update = []
        self._discards_since_update = {}
        obs.counters.inc("async_updates")
        if stats["folded"]:
            obs.counters.inc("results_folded", int(stats["folded"]))
        obs.counters.gauge("overlap_efficiency", oe if oe is not None else 0.0)
        obs.counters.gauge(
            "stale_reuse_ratio",
            round(self._folded_total / max(self._consumed_total, 1), 4))
        # (the logged fitness is the POST-chaos-mutation value the fold
        # actually ranked, in canonical sorted order: a replay reproduces
        # a nan_fitness-burst run exactly without re-firing the burst)
        es._emit_record(es._finalize_record(record), log_fn, verbose)
        return True, 0

    def _overlap_efficiency(self, busy_s: float, wall_s: float):
        """Worker-busy fraction of the consuming update's wall window:
        (Σ eval seconds of the batch / n_workers) / wall, clipped to
        [0, 1].  1.0 = the workers never idled while this update's
        window elapsed — evaluation fully hidden behind the rolling
        updates; a synchronous barrier loop scores eval/(eval+update).
        Approximate by construction (a late result's busy seconds were
        spent in earlier windows) and documented as such
        (docs/async.md)."""
        if wall_s <= 0 or not self._n_workers:
            return None
        ratio = (busy_s / self._n_workers) / wall_s
        return round(float(min(max(ratio, 0.0), 1.0)), 4)

    _n_workers = 0

    # ---------------------------------------------------------- live loop

    def run(self, n_steps: int, log_fn=None, verbose: bool = True):
        es = self.es
        obs = self.obs
        obs.discard_phases()
        self._ensure_compiled()
        events: queue.Queue = queue.Queue()
        src_pool = self._make_source(events)
        self._n_workers = src_pool.n_workers
        self._discards_since_update = {}

        version = 0
        dispatched = 0
        # dispatch ids continue the state's generation numbering, so a
        # chaos plan's (gen, member) coordinates and the (key, gen)
        # noise streams mean the same thing in sync and async runs.
        # A lossy run dispatches MORE generations than it applies
        # updates (loss replacement), and state.generation only counts
        # updates — the high-water mark keeps a follow-up train_async
        # call off the already-consumed streams (a follow-up *sync*
        # train() can still overlap them; statistical correlation, not
        # corruption — docs/async.md)
        base = max(int(es.state.generation),
                   int(getattr(es, "_async_next_dispatch", 0)))
        inflight: dict[tuple[int, int], bool] = {}
        arrived: list[Arrival] = []
        updates_done = 0
        rejected_streak = 0
        lost = 0
        t_update = time.perf_counter()

        def discard(a: Arrival, staleness) -> None:
            obs.counters.inc("stale_discarded")
            obs.event("stale_discarded", dispatch=int(a.dispatch),
                      member=int(a.member), staleness=staleness,
                      trace=f"d{a.dispatch}")
            self.log.discarded.append([a.dispatch, a.member])
            self._discarded_total += 1
            self._discards_since_update[a.dispatch] = (
                self._discards_since_update.get(a.dispatch, 0) + 1)
            if a.t_arrival:
                obs.hists.observe("async/discard_latency_s",
                                  time.perf_counter() - a.t_arrival)

        empty_dispatches = 0
        try:
            while updates_done < n_steps:
                # ---- keep the workers fed: at most ~2 populations in
                # flight, and never fewer results in the pipeline than
                # the remaining updates demand — results LOST to dead
                # workers are replaced by extra dispatches (fresh noise
                # generations), so a lossy run still finishes its
                # schedule with full batches
                remaining = (n_steps - updates_done) * self.n - len(arrived)
                while len(inflight) < min(self._inflight_budget(src_pool),
                                          remaining):
                    # the dispatch's trace id threads through its span,
                    # the async_dispatch event, and every later fold /
                    # discard event — one grep through the flight
                    # recorder follows a dispatch end to end
                    with obs.trace_ctx(f"d{base + dispatched}"), \
                            obs.phase("async"):
                        with obs.phase("dispatch"):
                            src = self._snapshot(base + dispatched, version)
                            members = src_pool.dispatch(src)
                            for i in members:
                                inflight[(src.dispatch, i)] = True
                            dispatched += 1
                    # a dispatch that could reach NO worker (every pipe
                    # dead even after respawn) must not spin forever
                    empty_dispatches = (0 if members
                                        else empty_dispatches + 1)
                    if empty_dispatches > 3:
                        raise RuntimeError(
                            f"async scheduler ran dry after "
                            f"{updates_done}/{n_steps} updates: "
                            f"{empty_dispatches} consecutive dispatches "
                            f"reached no live worker ({lost} results "
                            f"lost so far)")

                # ---- collect arrivals (one bounded wait, then drain);
                # with a full population already waiting the wait drops
                # to a pure drain, so a ready update never sits behind a
                # poll slice
                with obs.phase("eval"):
                    ready = len(arrived) >= self.n
                    for d, i in src_pool.poll_lost(
                            0.0 if ready else POLL_SLICE_S):
                        inflight.pop((d, i), None)
                        self.log.lost.append([d, i])
                        lost += 1
                    try:
                        a = (events.get_nowait() if ready
                             else events.get(timeout=POLL_SLICE_S))
                    except queue.Empty:
                        a = None
                    while a is not None:
                        inflight.pop((a.dispatch, a.member), None)
                        # per-member eval seconds as a distribution: the
                        # straggler tail the mean-shaped overlap metrics
                        # fold away
                        obs.hists.observe("async/eval_s", a.eval_s)
                        arrived.append(a)
                        try:
                            a = events.get_nowait()
                        except queue.Empty:
                            a = None

                # ---- staleness is judged when the batch forms (the
                # center may have moved while a result sat in the
                # arrived list): too-stale results are discarded WITH
                # EVIDENCE — counter + event + log entry, never silently
                still: list[Arrival] = []
                for a in arrived:
                    s = self._sources.get(a.dispatch)
                    if s is None or s.version < version - self.max_stale:
                        discard(a, version - s.version if s else None)
                    else:
                        still.append(a)
                arrived = still

                # ---- update trigger: one population's worth arrived
                # (lost results were re-dispatched above, so every
                # update consumes a full population's worth)
                if len(arrived) >= self.n:
                    batch, arrived = arrived[:self.n], arrived[self.n:]
                    n_logged = len(self.log.updates)
                    try:
                        applied, rejected_streak = self._apply_update(
                            batch, version, t_update, log_fn, verbose,
                            rejected_streak)
                    except BaseException:
                        # an aborting update (persistent-rejection raise,
                        # KeyboardInterrupt, a raising user log_fn) must
                        # not lose its batch from the finally's
                        # accounting sweep — unless the batch was already
                        # CONSUMED (state advanced + logged), in which
                        # case re-queueing would double-account it
                        if len(self.log.updates) == n_logged:
                            arrived = batch + arrived
                        raise
                    if applied:
                        t_update = time.perf_counter()
                        version += 1
                        updates_done += 1
                        # the elastic source pushes the new center to
                        # every live host here (O(dim) broadcast);
                        # in-process sources have nothing to push
                        src_pool.notify_update(version, es.state)
                        self._prune_sources(
                            version,
                            {d for d, _ in inflight}
                            | {a.dispatch for a in arrived})
                    else:
                        # rejected: re-queue the batch for the retried
                        # apply (same membership → deterministic re-run)
                        arrived = batch + arrived
        finally:
            # one final zero-timeout loss drain: a dispatch surrendered
            # as lost moments before an aborting raise (the dry-out
            # guard fires straight after the empty dispatch) must still
            # land on the log — no poll ever ran after it
            try:
                for d, i in src_pool.poll_lost(0.0):
                    inflight.pop((d, i), None)
                    self.log.lost.append([d, i])
                    lost += 1
            except Exception:  # noqa: BLE001 — the run is already over
                obs.event("final_loss_drain_failed")
            src_pool.close()
            # tail accounting: results still in flight or arrived-but-
            # unconsumed at shutdown are recorded as discarded (the run
            # is over; they fold nowhere) — the accounting invariant
            # dispatched == consumed + discarded + lost always holds
            leftovers = list(inflight) + [(a.dispatch, a.member)
                                          for a in arrived]
            for d, i in leftovers:
                self.log.discarded.append([d, i])
            if leftovers:
                obs.counters.inc("stale_discarded", len(leftovers))
                obs.event("run_end_discard", n=len(leftovers))
                self._discarded_total += len(leftovers)
            es._async_next_dispatch = base + dispatched
            # the log is the torn run's forensic artifact — it must
            # survive a raising run, not only a clean one
            es._async_log = self.log
        return es

    # -------------------------------------------------------------- replay

    def replay(self, log: "AsyncEventLog | dict", log_fn=None,
               verbose: bool = False, n_steps: int | None = None):
        """Re-drive a recorded schedule as pure math: same dispatch
        snapshots, same batches in the same order, same fold formula —
        bit-identical parameters, independent of wall clock or chaos.

        The recorded fitness/steps are applied directly (no re-rollout),
        so a replay reproduces a chaos-torn live run exactly: a member
        the live run saw NaN (injected rollout_exc) stays NaN here.
        ``n_steps`` (when given) must match the recorded update count —
        a mismatch is a caller error, not something to silently ignore."""
        if isinstance(log, dict):
            log = AsyncEventLog.from_dict(log)
        if n_steps is not None and n_steps != len(log.updates):
            raise ValueError(
                f"replay drives the RECORDED schedule: n_steps={n_steps} "
                f"but the log holds {len(log.updates)} updates — pass the "
                "log's own count (or drop n_steps)")
        es = self.es
        es.obs.discard_phases()
        dispatch_iter = iter(log.dispatches)
        next_dispatch = next(dispatch_iter, None)
        version = 0
        rejected_streak = 0
        self._n_workers = 0
        self._dispatched_since_update = []
        self._discards_since_update = {}
        self._staleness_counts = {}
        for entry in log.updates:
            # materialize every snapshot the schedule took at <= this
            # version, in recorded order (dispatch versions are
            # non-decreasing by construction)
            while (next_dispatch is not None
                   and next_dispatch[1] <= version):
                self._snapshot(int(next_dispatch[0]),
                               int(next_dispatch[1]))
                next_dispatch = next(dispatch_iter, None)
            batch = [Arrival(int(d), int(i), float(f), int(s), 0.0)
                     for d, i, f, s in entry["consumed"]]
            applied = False
            while not applied:
                applied, rejected_streak = self._apply_update(
                    batch, version, None, log_fn, verbose, rejected_streak)
            version += 1
            self._prune_sources(version)
        es._async_log = self.log
        return es


# ---------------------------------------------------------------------
# the elastic host-granular scheduler (parallel/elastic.py fleets)
# ---------------------------------------------------------------------


class _HostSource:
    """Host-granular source: each dispatch is a FULL population evaluated
    by one remote host of an elastic fleet (parallel/elastic.py), results
    arrive a population at a time, and a dead host's in-flight dispatches
    surrender as ``results_lost`` — the PR-8 worker-source contract lifted
    to host granularity.

    The fleet object (``ElasticCoordinator``) owns the sockets and the
    membership table; this adapter owns the scheduler-facing accounting:
    Arrival conversion, membership entries on the event log, the per-host
    latency distributions, and the loss/membership counters."""

    def __init__(self, scheduler: "ElasticScheduler", fleet,
                 events: "queue.Queue"):
        self.sched = scheduler
        self.fleet = fleet
        self.events = events
        self.n = scheduler.n
        self.obs = scheduler.obs
        self._fold_p99: dict[int, float] = {}
        self._lost_now: list[tuple[int, int]] = []

    def dispatch(self, source: Source) -> list[int]:
        host = self.fleet.dispatch(source.dispatch, source.version)
        if host is None:
            # grace expired with no live host: the never-sent population
            # is surrendered as lost UP FRONT (the _ProcessSource dead-
            # pipe contract), because the dispatch is already on the log
            # — dispatched == consumed + discarded + lost must survive
            # even a run that recovers when a host finally joins.  The
            # empty member list still feeds the dry-out guard
            self.obs.counters.inc("results_lost", self.n)
            self.obs.event("results_lost", dispatch=int(source.dispatch),
                           host=None, n=self.n)
            self._lost_now.extend((int(source.dispatch), i)
                                  for i in range(self.n))
            return []
        self.obs.event("elastic_dispatch", trace=f"d{source.dispatch}",
                       dispatch=int(source.dispatch), host=int(host))
        return list(range(self.n))

    def _note_membership(self, events: list[dict]) -> None:
        for m in events:
            entry = dict(m, at_dispatch=len(self.sched.log.dispatches))
            self.sched.log.membership.append(entry)
            if m["event"] == "join":
                self.obs.counters.inc("hosts_joined")
            else:
                self.obs.counters.inc("hosts_lost")
                # the worst-host rollup must not be pinned by a dead
                # straggler's history: drop its distribution snapshot
                if self._fold_p99.pop(int(m["host"]), None) is not None:
                    self.obs.counters.gauge(
                        "elastic_fold_p99_worst_s",
                        round(max(self._fold_p99.values()), 6)
                        if self._fold_p99 else 0.0)
            self.obs.event(f"host_{m['event']}", host=int(m["host"]))
        self.obs.counters.gauge("elastic_hosts", self.fleet.n_live())

    def poll_lost(self, timeout_s: float = POLL_SLICE_S
                  ) -> list[tuple[int, int]]:
        results, lost_dispatches, membership = self.fleet.poll(timeout_s)
        if membership:
            self._note_membership(membership)
        t_arr = time.perf_counter()
        for r in results:
            d, host = int(r["dispatch"]), int(r["host"])
            src = self.sched._sources.get(d)
            if src is None:
                # a stray from a PREVIOUS run on this fleet (the fleet
                # outlives runs; a straggler can answer run 1's dispatch
                # during run 2): not this log's dispatch, so folding or
                # even discard-logging it would break the run's
                # dispatched == consumed + discarded + lost invariant —
                # dropped WITH evidence, outside the log
                self.obs.counters.inc("foreign_results_dropped")
                self.obs.event("foreign_result_dropped", dispatch=d,
                               host=host)
                continue
            fit = np.asarray(r["fitness"], np.float32)
            k = max(len(fit), 1)
            per = float(r["eval_s"]) / k
            base_steps, rem = divmod(int(r["steps"]), k)
            if src.t_dispatch:
                # per-host dispatch→arrival latency: the host's whole
                # contribution lag, the tail `obs dash`'s host column
                # renders (worst host p99 rides a gauge so the dash can
                # read it from the store alone)
                lat = t_arr - src.t_dispatch
                self.obs.hists.observe("elastic/fold_s", lat)
                self.obs.hists.observe(f"elastic/h{host}/fold_s", lat)
                p99 = self.obs.hists.quantile(f"elastic/h{host}/fold_s",
                                              0.99)
                if p99 is not None:
                    self._fold_p99[host] = p99
                    self.obs.counters.gauge(f"elastic_fold_p99_s_h{host}",
                                            round(p99, 6))
                    self.obs.counters.gauge(
                        "elastic_fold_p99_worst_s",
                        round(max(self._fold_p99.values()), 6))
            self.obs.event("elastic_result", trace=f"d{d}", dispatch=d,
                           host=host, eval_s=round(float(r["eval_s"]), 4))
            for i in range(len(fit)):
                self.events.put(Arrival(
                    d, i, float(fit[i]),
                    base_steps + (1 if i < rem else 0), per, t_arr))
        lost: list[tuple[int, int]] = []
        for d, host in lost_dispatches:
            if self.sched._sources.get(int(d)) is None:
                # same foreign-dispatch rule as above: a host that died
                # still holding a PREVIOUS run's dispatch must not
                # inflate this run's loss accounting
                self.obs.event("foreign_loss_dropped", dispatch=int(d),
                               host=int(host))
                continue
            self.obs.counters.inc("results_lost", self.n)
            self.obs.event("results_lost", dispatch=int(d),
                           host=int(host), n=self.n)
            lost.extend((int(d), i) for i in range(self.n))
        out = self._lost_now + lost
        self._lost_now = []
        return out

    def notify_update(self, version: int, state) -> None:
        self.fleet.push_center(
            version, np.asarray(state.params_flat, np.float32),
            float(np.asarray(state.sigma)))

    def close(self) -> None:
        # the fleet outlives the run (hosts stay joined for the next
        # train_elastic call / operator shutdown) — nothing to tear down
        self.obs.counters.gauge("elastic_hosts", self.fleet.n_live())

    @property
    def n_workers(self) -> int:
        return max(self.fleet.n_live(), 1)


class ElasticScheduler(GenerationScheduler):
    """The fold scheduler at HOST granularity on the device engine
    (docs/multihost.md): dispatches go to remote hosts running the
    sharded/replicated generation program as async sources
    (parallel/elastic.py), per-host fitness contributions fold in with
    the same clipped-importance-weight math (``iwes.stale_log_ratios``,
    mean-1 self-normalized, truncated at ``iw_clip``), an update fires
    per population's-worth of arrivals, and only the O(dim) center rides
    the wire back to the hosts.

    The coordinator's update programs are the REPLICATED device engine's
    split path: a batch whose single source is the current center is the
    plain ``apply_weights`` update (the exact synchronous estimator); a
    batch carrying stale sources routes through ``apply_weights_reuse``
    (the IW-ES combined-estimator program) with λ per source dispatch.
    Event log, staleness discards, loss replacement, accounting and
    bit-exact ``replay`` are all inherited from the base scheduler —
    host granularity changes who evaluates, not what is recorded."""

    def __init__(self, es, fleet, max_stale: int = 16,
                 iw_clip: float = 2.0,
                 max_consecutive_rejections: int = 3):
        self.fleet = fleet
        super().__init__(
            es, max_stale=max_stale, iw_clip=iw_clip,
            max_consecutive_rejections=max_consecutive_rejections)

    # ----------------------------------------------------- backend hooks

    def _check_es(self, es) -> None:
        if es.backend != "device" or getattr(es, "_shard_params", False):
            raise ValueError(
                "ElasticScheduler runs on the coordinator's replicated "
                "device engine (table noise); hosts may run the sharded "
                "program, the coordinator's fold/update programs are the "
                f"replicated split path (got backend={es.backend!r}"
                f"{', shard_params=True' if getattr(es, '_shard_params', False) else ''})"
            )
        es.engine._require_dense_noise("elastic host fold")
        if getattr(es, "_obs_norm", False):
            raise ValueError(
                "elastic folding does not support obs_norm: a stale "
                "host's fitness was measured under OLDER running stats, "
                "so the density ratio's fixed-f(θ) assumption silently "
                "breaks (same refusal as IW_ES)")
        if getattr(es, "_streamed", False) or getattr(es, "_noise_kernel",
                                                      False):
            raise ValueError(
                "elastic folding supports the standard/decomposed "
                "forwards; streamed/noise_kernel are untested with the "
                "reuse-update program")

    def _sigma_of(self, st) -> float:
        return float(np.asarray(st.sigma))

    def _offsets_for(self, st, dispatch: int) -> np.ndarray:
        import jax.numpy as jnp

        return np.asarray(self.engine.all_pair_offsets(
            st._replace(generation=jnp.asarray(int(dispatch),
                                               jnp.int32))))

    def _ensure_compiled(self) -> None:
        es = self.es
        if es.compile_time_s is not None:
            return
        self.obs.note("compile")
        es.compile_time_s = self.engine.compile_split(es.state)
        # warm the single-source-group reuse shape (the host-granular
        # common case: one whole stale population per update) outside
        # the timed loop — the IW_ES._warm_reuse_programs discipline
        import jax.numpy as jnp

        t0 = time.perf_counter()
        st = es.state
        offs = self.engine.all_pair_offsets(st)
        zeros_d = jnp.zeros_like(st.params_flat)
        self.engine.noise_stats(offs, zeros_d)
        out, _ = self.engine.apply_weights_reuse(
            st, jnp.zeros((self.n,), jnp.float32),
            offs, jnp.zeros((int(offs.shape[0]),), jnp.float32),
            zeros_d[None, :], jnp.zeros((1,), jnp.float32))
        jnp.asarray(out.params_flat).block_until_ready()
        dt = time.perf_counter() - t0
        self.obs.compile_event("elastic_fold_warm", dt,
                               count_recompiles=2, programs=2,
                               first_call=True)
        es.compile_time_s += dt

    def _make_source(self, events: "queue.Queue"):
        # seed the fleet's center (version 0) so hosts that joined
        # before this run — or join during it — sync the right state
        st = self.es.state
        self.fleet.push_center(
            0, np.asarray(st.params_flat, np.float32), self._sigma_of(st))
        return _HostSource(self, self.fleet, events)

    def _inflight_budget(self, src_pool) -> int:
        # one population in flight PER LIVE HOST (plus the one the loop
        # is about to add): every host stays fed, a straggling host
        # queues at most ~one extra dispatch
        return self.n * max(1, self.fleet.n_live())

    # -------------------------------------------------------- fold math

    def _best_theta(self, arrival: Arrival) -> np.ndarray:
        eng = self.engine
        src = self._sources[arrival.dispatch]
        sign, off = member_sign_offset(src.offsets, arrival.member,
                                       bool(eng.config.mirrored))
        eps = np.asarray(eng.table.slice(int(off), eng.spec.dim))
        return src.params + src.sigma * sign * eps

    def _fold_batch(self, batch: list[Arrival], version: int):
        """Device-path fold of one mixed-staleness batch — pure given
        (center state, sources, batch), exactly like the host fold, so
        replay stays bit-identical.  All-fresh single-source batches are
        the synchronous estimator through ``apply_weights``; anything
        else is the IW-ES combined-estimator program with per-source λ.
        """
        import jax.numpy as jnp

        from ..ops.gradient import fold_mirrored_weights

        eng = self.engine
        st = self.es.state
        dim = int(eng.spec.dim)
        mirrored = bool(eng.config.mirrored)
        batch = sorted(batch, key=lambda a: (a.dispatch, a.member))
        fit = np.asarray([a.fitness for a in batch], np.float32)
        fit = mutate_fitness(int(np.asarray(st.generation)), fit)
        n_valid = int(np.isfinite(fit).sum())
        if n_valid < 2:
            return None, None, fit, {"n_valid": n_valid}
        w = rank_weights_with_failures(fit)
        sigma_u = self._sigma_of(st)
        n_tot = len(batch)
        center = np.asarray(st.params_flat, np.float32)

        by_dispatch: dict[int, list[int]] = {}
        for j, a in enumerate(batch):
            by_dispatch.setdefault(a.dispatch, []).append(j)
        lam_stale: list[float] = []
        n_fresh = 0
        with self.obs.phase("async"):
            with self.obs.phase("fold"):
                only = next(iter(by_dispatch))
                fresh_single = (
                    len(by_dispatch) == 1 and n_tot == self.n
                    and self._sources[only].version == version)
                if fresh_single:
                    w_vec = np.zeros(self.n, np.float32)
                    for kk, j in enumerate(by_dispatch[only]):
                        w_vec[batch[j].member] = w[j]
                    n_fresh = n_tot
                    reuse_args = None
                else:
                    offs_parts, oldw_parts, d_rows, coeffs = [], [], [], []
                    for d in sorted(by_dispatch):
                        src = self._sources[d]
                        idx = by_dispatch[d]
                        k = len(idx)
                        if src.version == version:
                            lam = np.ones(k, np.float32)
                            c = 1.0
                            d_vec = np.zeros(dim, np.float32)
                            n_fresh += k
                        else:
                            d_vec = ((src.params - center)
                                     / sigma_u).astype(np.float32)
                            c = src.sigma / sigma_u
                            dots, norms = eng.noise_stats(
                                jnp.asarray(src.offsets),
                                jnp.asarray(d_vec))
                            dots, norms = (np.asarray(dots),
                                           np.asarray(norms))
                            if mirrored:
                                dots, norms = mirrored_member_stats(
                                    dots, norms)
                            members = np.asarray(
                                [batch[j].member for j in idx], np.intp)
                            d2 = float(d_vec @ d_vec)
                            lam = clipped_stale_lambdas(
                                dots[members], norms[members], d2, c,
                                dim, self.iw_clip)
                            lam_stale.extend(float(x) for x in lam)
                        # per-member weights over the dispatch's FULL
                        # population; members not in the batch weigh 0
                        w_eff = np.zeros(self.n, np.float32)
                        for kk, j in enumerate(idx):
                            w_eff[batch[j].member] = w[j] * lam[kk]
                        folded = (np.asarray(fold_mirrored_weights(
                            jnp.asarray(w_eff))) if mirrored else w_eff)
                        oldw_parts.append(
                            folded * np.float32(c / (n_tot * sigma_u)))
                        offs_parts.append(src.offsets)
                        d_rows.append(d_vec)
                        coeffs.append(float(w_eff.sum())
                                      / (n_tot * sigma_u))
                    reuse_args = (
                        np.concatenate(offs_parts),
                        np.concatenate(oldw_parts).astype(np.float32),
                        np.stack(d_rows).astype(np.float32),
                        np.asarray(coeffs, np.float32),
                    )
            with self.obs.phase("update"):
                if reuse_args is None:
                    new_state, gnorm = eng.apply_weights(
                        st._replace(generation=jnp.asarray(int(only),
                                                           jnp.int32)),
                        jnp.asarray(w_vec))
                else:
                    new_state, gnorm = eng.apply_weights_reuse(
                        st, jnp.zeros((self.n,), jnp.float32),
                        jnp.asarray(reuse_args[0]),
                        jnp.asarray(reuse_args[1]),
                        jnp.asarray(reuse_args[2]),
                        jnp.asarray(reuse_args[3]))
                # state.generation counts UPDATES (the fold-scheduler
                # contract); the per-dispatch noise generation was an
                # operand of this one program only
                new_state = new_state._replace(
                    generation=jnp.asarray(version + 1, jnp.int32))
                gnorm = float(np.asarray(gnorm))
        stats = {
            "n_valid": n_valid,
            "fresh": n_fresh,
            "folded": len(batch) - n_fresh,
            "mean_lambda": (round(float(np.mean(lam_stale)), 4)
                            if lam_stale else None),
            "max_staleness": version - min(
                self._sources[d].version for d in by_dispatch),
            "consumed_by_dispatch": [[int(d), len(by_dispatch[d])]
                                     for d in sorted(by_dispatch)],
        }
        return new_state, gnorm, fit, stats


# ---------------------------------------------------------------------
# the overlap scheduler (device / pooled / sharded backends)
# ---------------------------------------------------------------------


def train_overlap(es, n_steps: int, log_fn=None, verbose: bool = True,
                  max_consecutive_rejections: int = 3,
                  step_timeout_s: float = 3600.0):
    """Pipelined generations: generation g+1's program is submitted from
    a background thread before generation g's metrics are materialized,
    so the host-side tail (fence, D2H, best tracking, record emit)
    overlaps the next dispatch.  Same program sequence and inputs as the
    synchronous loop — bit-identical parameters and records.

    Rejection protocol: a rejected generation's speculative successor
    consumed a poisoned state, so it is DISCARDED (counted in
    ``speculative_discarded``) and the loop re-runs from the restored
    state — except on the sharded engine, whose in-program rollback
    means the speculative step already re-ran the SAME generation on the
    rolled-back state: its result is kept as the deterministic re-run.
    """
    import concurrent.futures as cf
    import itertools

    import jax

    obs = es.obs
    obs.discard_phases()
    if es.compile_time_s is None:
        obs.note("compile")
        es.compile_time_s = es.engine.compile(es.state)
    ex = cf.ThreadPoolExecutor(max_workers=1,
                               thread_name_prefix="estorch-overlap")

    dispatch_seq = itertools.count(int(es.state.generation))

    def submit(state):
        # speculative dispatches carry trace ids too, so a wedged
        # program's last recorder span names WHICH dispatch wedged
        with obs.trace_ctx(f"d{next(dispatch_seq)}"), obs.phase("async"):
            with obs.phase("dispatch"):
                return ex.submit(es.engine.generation_step, state)

    def result_of(fut):
        # bounded wait in poll slices: the event loop must never block
        # unbounded on a wedged program (esguard R11)
        deadline = time.monotonic() + step_timeout_s
        while True:
            try:
                return fut.result(timeout=POLL_SLICE_S)
            except cf.TimeoutError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"generation program silent for {step_timeout_s}s"
                        " — wedged dispatch") from None

    try:
        done = 0
        rejected_streak = 0
        prev_state = es.state
        t0 = time.perf_counter()
        pending = submit(prev_state)
        while done < n_steps:
            new_state, metrics = result_of(pending)
            speculative = None
            if done + 1 < n_steps:
                # dispatch g+1 BEFORE touching g's metrics: on the
                # device path the fence below runs while the next
                # program executes
                speculative = submit(new_state)
            with obs.phase("host_sync"):
                fitness = np.asarray(metrics["fitness"])
                if es.backend != "host":
                    if es._shard_params:
                        jax.block_until_ready(
                            jax.tree_util.tree_leaves(new_state.params))
                    else:
                        jax.block_until_ready(new_state.params_flat)
            dt = time.perf_counter() - t0

            reason = es._update_anomaly(metrics)
            if reason is not None:
                obs.counters.inc("generations_rejected")
                obs.event("generation_rejected", reason=reason)
                obs.discard_phases()
                rejected_streak += 1
                if rejected_streak > max_consecutive_rejections:
                    raise RuntimeError(
                        f"{reason}; {rejected_streak} consecutive "
                        "generations rejected — check env/rollout health")
                if es._shard_params:
                    # in-program rollback: new_state IS the rolled-back
                    # input, so the speculative program is re-running
                    # the SAME generation deterministically — keep it
                    es.state = new_state
                    prev_state = new_state
                    pending = (speculative if speculative is not None
                               else submit(new_state))
                else:
                    if speculative is not None:
                        result_of(speculative)  # drain, then drop
                        obs.counters.inc("speculative_discarded")
                        obs.event("speculative_discarded",
                                  generation=int(done))
                    pending = submit(prev_state)
                t0 = time.perf_counter()
                continue
            rejected_streak = 0
            es.state = new_state
            record = es._base_record(
                prev_state, fitness, int(metrics["steps"]),
                float(np.asarray(metrics["grad_norm"])), dt,
                metrics=metrics if es._shard_params else None,
            )
            es._attach_scenarios(record, fitness, metrics)
            es._emit_record(record, log_fn, verbose)
            done += 1
            prev_state = new_state
            t0 = time.perf_counter()
            if speculative is not None:
                pending = speculative
    finally:
        ex.shutdown(wait=False)
    return es
