"""Novelty archive + k-NN novelty (Conti et al. 2018, NS-ES family).

Reference: the archive of behavior characterizations and the mean-k-NN
novelty inside ``estorch/estorch.py`` class ``NS_ES`` (SURVEY.md §2 item 3).

Stays HOST-side on purpose (BASELINE.json north star: "the NS-ES / NSR-ES
novelty archive and behavior-characterization k-NN stay host-side but consume
device-gathered BCs"): the archive is tiny (one BC per generation), grows
dynamically — a shape XLA hates — and the k-NN over it is O(|archive|·pop)
flops, noise compared to the rollouts.  BCs arrive as one device->host
transfer of the already-all-gathered (population, bc_dim) array.
"""

from __future__ import annotations

import numpy as np


class NoveltyArchive:
    """Append-only store of behavior characterizations with mean-k-NN novelty.

    ``max_size`` bounds long runs: beyond it the OLDEST entries are evicted
    (FIFO), keeping novelty focused on recent behavior and the k-NN cost
    constant.  0 (default) = unbounded, the reference's behavior.
    """

    def __init__(self, k: int = 10, bc_dim: int | None = None, max_size: int = 0):
        self.k = int(k)
        self.bc_dim = bc_dim
        if max_size < 0:
            raise ValueError(
                f"max_size must be >= 0 (0 = unbounded), got {max_size}"
            )
        self.max_size = int(max_size)
        self._bcs: list[np.ndarray] = []

    def __len__(self) -> int:
        return len(self._bcs)

    @property
    def bcs(self) -> np.ndarray:
        if not self._bcs:
            return np.zeros((0, self.bc_dim or 0), dtype=np.float32)
        return np.stack(self._bcs)

    def add(self, bc) -> None:
        bc = np.asarray(bc, dtype=np.float32).reshape(-1)
        if self.bc_dim is None:
            self.bc_dim = bc.shape[0]
        elif bc.shape[0] != self.bc_dim:
            raise ValueError(f"BC dim {bc.shape[0]} != archive dim {self.bc_dim}")
        self._bcs.append(bc)
        if self.max_size and len(self._bcs) > self.max_size:
            del self._bcs[: len(self._bcs) - self.max_size]

    def novelty(self, bcs) -> np.ndarray:
        """Mean distance to the k nearest archived BCs, per query row.

        ``bcs``: (n, bc_dim) or (bc_dim,).  With an empty archive every
        query is maximally novel — returns ones (any positive constant works:
        only relative novelty matters for selection and ranking).
        """
        q = np.asarray(bcs, dtype=np.float32)
        single = q.ndim == 1
        q = np.atleast_2d(q)
        if not self._bcs:
            out = np.ones(q.shape[0], dtype=np.float32)
            return out[0] if single else out
        a = self.bcs  # (m, d)
        # pairwise Euclidean distances, (n, m), via the matmul identity
        # |q-a|² = |q|² + |a|² − 2 q·a — no (n, m, d) intermediate, so host
        # memory stays O(n·m) even for pop-10k × multi-k-generation archives.
        # Accumulated in float64: the identity cancels catastrophically in
        # float32 when |q|,|a| are large and the true distance is small.
        q64 = q.astype(np.float64)
        a64 = a.astype(np.float64)
        d2 = (
            (q64**2).sum(1)[:, None]
            + (a64**2).sum(1)[None, :]
            - 2.0 * (q64 @ a64.T)
        )
        d = np.sqrt(np.maximum(d2, 0.0))
        k = min(self.k, d.shape[1])
        part = np.partition(d, k - 1, axis=1)[:, :k]
        out = part.mean(axis=1).astype(np.float32)
        return out[0] if single else out

    def state_dict(self) -> dict:
        """For checkpointing (utils/checkpoint.py)."""
        return {
            "k": self.k,
            "bc_dim": self.bc_dim,
            "max_size": self.max_size,
            "bcs": self.bcs,
        }

    @classmethod
    def from_state_dict(cls, d: dict) -> "NoveltyArchive":
        bc_dim = d.get("bc_dim")
        ar = cls(
            k=int(d["k"]),
            bc_dim=None if bc_dim is None else int(bc_dim),
            max_size=int(d.get("max_size", 0)),
        )
        for row in np.asarray(d["bcs"]):
            ar.add(row)
        return ar
