"""The driver-mandated benchmark configurations (BASELINE.json `configs`).

Each config is a ready-to-run recipe mapping a BASELINE entry to the backend
this environment can execute it on:

1. cartpole_smoke   — CartPole-v1, 2-layer MLP, vanilla ES, pop 64
                      (device path: the env itself runs on-chip)
2. halfcheetah_vbn  — HalfCheetah (gymnasium MuJoCo), MLP+VBN, pop 1k
                      (host path: MuJoCo steps on CPU workers; MJX is not in
                      this image, so the device-physics variant is deferred)
3. humanoid_mirrored— Humanoid (gymnasium MuJoCo), MLP, mirrored ES, pop 10k
                      (host path, same note)
4. humanoid_nsres   — NSR-ES on Humanoid with BC = final (x, y) torso position
5. pong84_conv      — the conv-rollout stress path: NatureCNN population on
                      the bundled 84×84 C++ pixel pong (pooled execution);
                      stands in for the Atari config without ALE
6. atari_frostbite  — Frostbite Nature-CNN pop 5k — GATED: ale_py is not in
                      this image; raises with a clear message.

Use:  python -m estorch_tpu.configs <name> [--generations N] [--n-proc K]
"""

from __future__ import annotations

import argparse
from typing import Callable

import numpy as np


def _torch_mlp(n_in: int, n_out: int, hidden=(64, 64), vbn: bool = False):
    import torch

    from .models.vbn_torch import TorchVirtualBatchNorm

    class MLP(torch.nn.Module):
        def __init__(self):
            super().__init__()
            layers = []
            last = n_in
            for h in hidden:
                layers.append(torch.nn.Linear(last, h))
                if vbn:
                    layers.append(TorchVirtualBatchNorm(h))
                layers.append(torch.nn.Tanh())
                last = h
            layers.append(torch.nn.Linear(last, n_out))
            self.net = torch.nn.Sequential(*layers)

        def forward(self, x):
            return self.net(x)

    return MLP


def _mujoco_agent(env_id: str, bc_xy: bool = False):
    """Host agent for gymnasium MuJoCo envs (reference rollout contract)."""
    import gymnasium as gym
    import torch

    class MujocoAgent:
        def __init__(self):
            self.env = gym.make(env_id)

        def rollout(self, policy, render=False):
            obs, _ = self.env.reset()
            total, steps, done = 0.0, 0, False
            with torch.no_grad():
                while not done:
                    a = policy(torch.from_numpy(np.asarray(obs, np.float32)))
                    obs, r, term, trunc, _ = self.env.step(a.numpy())
                    total += float(r)
                    steps += 1
                    done = term or trunc
            self.last_episode_steps = steps
            if bc_xy:
                # BC: final torso (x, y) — the Conti-2018 Humanoid BC
                data = self.env.unwrapped.data
                return total, np.asarray(data.qpos[:2], np.float32)
            return total

    return MujocoAgent


def cartpole_smoke(**over):
    """BASELINE config 1 — device-native CartPole ES, population 64."""
    import optax

    from . import ES, JaxAgent, MLPPolicy
    from .envs import CartPole

    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=64,
        sigma=0.1,
        policy_kwargs={"action_dim": 2, "hidden": (32, 32)},
        agent_kwargs={"env": CartPole()},
        optimizer_kwargs={"learning_rate": 3e-2},
    )
    kw.update(over)
    return ES(**kw)


def _planar_device(env, population, hidden, horizon, lr, over,
                   sigma=0.08):
    """Shared recipe body for the device-native locomotion configs: MLP
    policy on the JaxAgent path, physics compiled into the generation."""
    import optax

    from . import ES, JaxAgent, MLPPolicy

    kw = dict(
        policy=MLPPolicy,
        agent=JaxAgent,
        optimizer=optax.adam,
        population_size=population,
        sigma=sigma,
        policy_kwargs={"action_dim": env.action_dim, "hidden": hidden,
                       "discrete": False, "action_scale": 1.0},
        agent_kwargs={"env": env, "horizon": horizon},
        optimizer_kwargs={"learning_rate": lr},
    )
    kw.update(over)
    return ES(**kw)


def swimmer2d_device(**over):
    """Device-native locomotion: pure-JAX planar swimmer, whole generation
    compiled on-chip (envs/locomotion.py — the MJX-fallback path)."""
    from .envs import Swimmer2D

    return _planar_device(Swimmer2D(), 512, (32, 32), 300, 3e-2, over)


def hopper2d_device(**over):
    """Device-native locomotion with contact + falling termination: pure-JAX
    planar hopper (envs/locomotion.py), Hopper-class difficulty."""
    from .envs import Hopper2D

    return _planar_device(Hopper2D(), 1024, (64, 64), 400, 2e-2, over)


def walker2d_device(**over):
    """Device-native locomotion, planar biped (Walker2d-class): two-legged
    balance + gait with falling termination — the in-tree stepping stone
    toward the Humanoid north star."""
    from .envs import Walker2D

    return _planar_device(Walker2D(), 1024, (64, 64), 400, 2e-2, over)


def humanoid2d_device(**over):
    """Device-native locomotion, planar humanoid (11 bodies, 10 joints):
    the hardest in-tree task — balance a jointed column on two legs with
    free-swinging arm counterweights — and the device-native stand-in for
    the MuJoCo-Humanoid configs (BASELINE config 3 stays on host/pooled).

    obs_norm defaults ON (round-4 A/B, BENCHMARKS.md: Humanoid2D's obs
    variance spans 165×, and normalization won 2/2 seeds on final mean
    and AUC — passing round 3's 600-generation plateau by gen 80); pass
    obs_norm=False for the raw-observation variant — including to
    RESTORE checkpoints saved before round 4 (the running stats are
    training state, so restore_checkpoint rejects an obs_norm
    mismatch).

    obs_probe_episodes defaults to 4 here (round-5 A/B, BENCHMARKS.md:
    4 probes tied 1 probe on one seed and found a 2.2× better optimum
    on the other, at ~0.6% extra episode cost — the same faster-stats
    lever as warmup).  The ENGINE default stays 1 (parity-minimal,
    goldens pinned); this is a recipe-level choice.  Unlike obs_norm,
    the probe count is NOT training state and restore does not gate on
    it — resuming a pre-round-5 run under this default accumulates
    stats 4× faster from the resume point (statistically sound either
    way); pass obs_probe_episodes=1 for a procedure-exact
    continuation."""
    from .envs import Humanoid2D

    return _planar_device(Humanoid2D(), 1024, (64, 64), 400, 2e-2,
                          {"obs_norm": True, "obs_probe_episodes": 4,
                           **over})


def cheetah2d_device(**over):
    """Device-native locomotion, 7-body planar runner (HalfCheetah-class):
    the on-chip stand-in for BASELINE config 2 until mjx is installable."""
    from .envs import Cheetah2D

    return _planar_device(Cheetah2D(), 1024, (64, 64), 500, 2e-2, over)


def halfcheetah_vbn(**over):
    """BASELINE config 2 — HalfCheetah MLP+VBN, population 1k (host path)."""
    import torch

    from . import ES

    kw = dict(
        policy=_torch_mlp(17, 6, hidden=(64, 64), vbn=True),
        agent=_mujoco_agent("HalfCheetah-v5"),
        optimizer=torch.optim.Adam,
        population_size=1000,
        sigma=0.02,
        optimizer_kwargs={"lr": 1e-2},
        weight_decay=0.005,
    )
    kw.update(over)
    es = ES(**kw)
    _freeze_host_vbn(es)
    return es


def humanoid2d_pop10k(**over):
    """Config-3 scale on the DEVICE path: Humanoid2D at population 10240
    with rank-1 perturbations, running obs normalization, and a
    Humanoid-sized policy (256×256).

    The engine-mode choices are evidence-driven (bench_ab_cpu.jsonl,
    BENCHMARKS.md): at pop-10240 × 166k-params, `low_rank=1` measured 9.5×
    the full-rank throughput with 3× less memory — the member noise state
    drops from O(dim) to O(Σ(m+n)r) — and `obs_norm` measured +30-43%
    held-out eval on real MuJoCo (3/3 HalfCheetah seeds).  The two compose
    as of round 4 (normalization is an input-side transform, independent
    of the noise representation).  eval_chunk bounds materialized member
    weights the same way the bench's pop-10k point does.
    obs_probe_episodes=4 per the round-5 probe-count A/B (see
    humanoid2d_device)."""
    from .envs import Humanoid2D

    return _planar_device(Humanoid2D(), 10240, (256, 256), 400, 2e-2,
                          {"low_rank": 1, "obs_norm": True,
                           "obs_probe_episodes": 4,
                           "eval_chunk": 1024, **over})


def humanoid_mirrored(**over):
    """BASELINE config 3 — Humanoid mirrored-sampling ES, population 10k."""
    import torch

    from . import ES

    kw = dict(
        policy=_torch_mlp(348, 17, hidden=(256, 256)),
        agent=_mujoco_agent("Humanoid-v5"),
        optimizer=torch.optim.Adam,
        population_size=10000,
        sigma=0.02,
        optimizer_kwargs={"lr": 1e-2},
        weight_decay=0.005,
    )
    kw.update(over)
    return ES(**kw)


def humanoid_nsres(**over):
    """BASELINE config 4 — NSR-ES on Humanoid, BC = final torso (x, y)."""
    import torch

    from . import NSR_ES

    kw = dict(
        policy=_torch_mlp(348, 17, hidden=(256, 256)),
        agent=_mujoco_agent("Humanoid-v5", bc_xy=True),
        optimizer=torch.optim.Adam,
        population_size=1000,
        sigma=0.02,
        k=10,
        meta_population_size=3,
        optimizer_kwargs={"lr": 1e-2},
    )
    kw.update(over)
    return NSR_ES(**kw)


def halfcheetah_pooled(**over):
    """BASELINE config 2, pooled edition: HalfCheetah physics in gym.vector
    workers while the population's policy forwards run device-batched —
    the no-MJX path to MuJoCo at scale (vs halfcheetah_vbn's per-member
    host rollouts).  Pass ``obs_norm=True`` for the OpenAI-ES MuJoCo
    setup (running observation normalization; default off for reference
    parity — estorch has no such machinery)."""
    import optax

    from . import ES, MLPPolicy, PooledAgent

    kw = dict(
        policy=MLPPolicy,
        agent=PooledAgent,
        optimizer=optax.adam,
        population_size=1000,
        sigma=0.02,
        policy_kwargs={"action_dim": 6, "hidden": (64, 64), "discrete": False},
        agent_kwargs={"env_name": "gym:HalfCheetah-v5", "horizon": 1000},
        optimizer_kwargs={"learning_rate": 1e-2},
        weight_decay=0.005,
    )
    kw.update(over)
    return ES(**kw)


def humanoid_pooled(**over):
    """BASELINE config 3's pooled edition on REAL MuJoCo (round-4 verdict
    next #2 — the one BASELINE env besides gated Atari never trained on):
    Humanoid-v5 physics in gym.vector workers, device-batched population
    forwards, the Humanoid-sized MLP (obs 348 → 256×256 → 17, actions
    squashed to the env's ±0.4 bound), mirrored sampling, obs_norm on
    (the OpenAI-ES Humanoid setup — the 348-dim observation spans wildly
    different scales).  Population defaults to 512 (CPU-feasible at tens
    of generations; pass population_size=10000 for the full config-3
    scale on the chip)."""
    import optax

    from . import ES, MLPPolicy, PooledAgent

    kw = dict(
        policy=MLPPolicy,
        agent=PooledAgent,
        optimizer=optax.adam,
        population_size=512,
        sigma=0.02,
        policy_kwargs={"action_dim": 17, "hidden": (256, 256),
                       "discrete": False, "action_scale": 0.4},
        agent_kwargs={"env_name": "gym:Humanoid-v5", "horizon": 1000},
        optimizer_kwargs={"learning_rate": 1e-2},
        weight_decay=0.005,
        obs_norm=True,
    )
    kw.update(over)
    return ES(**kw)


def halfcheetah_nsres(**over):
    """BASELINE config 4, pooled edition on REAL MuJoCo: NSR-ES on
    HalfCheetah with BC = final x-position (Conti et al.'s locomotion
    characterization).  ``env_kwargs`` puts the x-position into the
    observation (gymnasium excludes it by default) and ``bc_indices=(0,)``
    selects it as the archive's 1-dim BC — the novelty family then
    searches over where the gait ENDS, not what it looks like."""
    import optax

    from . import NSR_ES, MLPPolicy, PooledAgent

    kw = dict(
        policy=MLPPolicy,
        agent=PooledAgent,
        optimizer=optax.adam,
        population_size=256,
        sigma=0.02,
        k=10,
        meta_population_size=3,
        policy_kwargs={"action_dim": 6, "hidden": (64, 64), "discrete": False},
        agent_kwargs={
            "env_name": "gym:HalfCheetah-v5",
            "horizon": 1000,
            "env_kwargs": {"exclude_current_positions_from_observation": False},
            "bc_indices": (0,),
        },
        optimizer_kwargs={"learning_rate": 1e-2},
        weight_decay=0.005,
    )
    kw.update(over)
    return NSR_ES(**kw)


def pong84_conv(**over):
    """Conv-rollout stress without ALE: NatureCNN on the bundled C++ pixel
    pong (84×84), pooled execution with the full Atari preprocessing stack
    (4-frame stacking → the CNN's designed 84×84×4 input, action repeat,
    sticky actions; envs/atari_wrappers.py) — the same machinery BASELINE
    config 5 exercises, with the env swapped for the in-tree stand-in."""
    import optax

    from . import ES, NatureCNN, PooledAgent

    kw = dict(
        policy=NatureCNN,
        agent=PooledAgent,
        optimizer=optax.adam,
        population_size=256,
        sigma=0.02,
        policy_kwargs={"action_dim": 3, "use_vbn": True},
        agent_kwargs={"env_name": "pong84", "horizon": 500,
                      "frame_stack": 4, "action_repeat": 2,
                      "sticky_prob": 0.25},
        optimizer_kwargs={"learning_rate": 1e-2},
        table_size=1 << 23,
    )
    kw.update(over)
    return ES(**kw)


def atari_frostbite(**over):
    """BASELINE config 5 — Frostbite Nature-CNN pop 5k. Gated: needs ALE."""
    try:
        import ale_py  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "the Atari config needs ale_py, which is not in this image; "
            "the NatureCNN policy (models/policies.py) and the pooled "
            "execution path are ready for it once ALE is available"
        ) from e
    raise NotImplementedError("wire up ALE via PooledAgent once available")


def _freeze_host_vbn(es) -> None:
    """Collect a random-rollout batch and freeze VBN stats via the engine."""
    env = es.agent.env  # the prototype agent's env (worker 0)
    frames = []
    obs, _ = env.reset(seed=0)
    for _ in range(128):
        obs, _, term, trunc, _ = env.step(env.action_space.sample())
        frames.append(np.asarray(obs, np.float32))
        if term or trunc:
            obs, _ = env.reset()
    es.engine.freeze_vbn(np.stack(frames))


CONFIGS: dict[str, Callable] = {
    "cartpole_smoke": cartpole_smoke,
    "swimmer2d_device": swimmer2d_device,
    "hopper2d_device": hopper2d_device,
    "walker2d_device": walker2d_device,
    "humanoid2d_device": humanoid2d_device,
    "humanoid2d_pop10k": humanoid2d_pop10k,
    "cheetah2d_device": cheetah2d_device,
    "halfcheetah_vbn": halfcheetah_vbn,
    "humanoid_mirrored": humanoid_mirrored,
    "humanoid_nsres": humanoid_nsres,
    "halfcheetah_pooled": halfcheetah_pooled,
    "halfcheetah_nsres": halfcheetah_nsres,
    "humanoid_pooled": humanoid_pooled,
    "pong84_conv": pong84_conv,
    "atari_frostbite": atari_frostbite,
}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("config", choices=sorted(CONFIGS))
    p.add_argument("--generations", type=int, default=10)
    p.add_argument("--n-proc", type=int, default=8)
    p.add_argument("--population", type=int, default=None)
    p.add_argument("--log-jsonl", type=str, default=None)
    args = p.parse_args(argv)

    over = {}
    if args.population:
        over["population_size"] = args.population
    es = CONFIGS[args.config](**over)

    log_fn = None
    if args.log_jsonl:
        from .utils import JsonlWriter, MultiWriter

        log_fn = MultiWriter([JsonlWriter(args.log_jsonl)], echo=True)
    es.train(args.generations, n_proc=args.n_proc, log_fn=log_fn)
    print(f"\nbest reward: {es.best_reward:.2f}")
    return es


if __name__ == "__main__":
    main()
