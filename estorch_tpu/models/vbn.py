"""VirtualBatchNorm — the OpenAI-ES Atari normalization trick, flax-native.

Reference: ``estorch.VirtualBatchNorm`` (``estorch/estorch.py`` — SURVEY.md
§2 item 6): normalization statistics are computed ONCE from a fixed reference
batch and frozen; rollouts then normalize with those frozen statistics plus a
learned affine, so ES policies see stable activations without per-batch stats.

TPU-native design: statistics live in a separate flax variable collection
(``vbn_stats``), NOT in ``params`` — so the ES perturbation (which flattens
only ``params``) never touches them, and the whole population shares one
frozen copy, exactly matching the reference semantics (and avoiding
per-member stat drift under vmap, SURVEY.md §7 hard-part 5).

Usage:
    stats = capture_reference_stats(module, params, reference_batch)
    out = module.apply({"params": params, "vbn_stats": stats}, obs)
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class VirtualBatchNorm(nn.Module):
    """Normalize with frozen reference-batch statistics + learned affine.

    During a reference pass (``mutable=["vbn_stats"]`` with
    ``update_stats=True``), the module computes mean/var over the batch axes
    of the reference batch and stores them.  All later calls normalize with
    the stored values.  Works on (features,) single observations and
    (batch, features) batches alike.
    """

    num_features: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray, update_stats: bool = False) -> jnp.ndarray:
        mean = self.variable(
            "vbn_stats", "mean", lambda: jnp.zeros((self.num_features,), jnp.float32)
        )
        var = self.variable(
            "vbn_stats", "var", lambda: jnp.ones((self.num_features,), jnp.float32)
        )
        gamma = self.param("scale", nn.initializers.ones, (self.num_features,))
        beta = self.param("bias", nn.initializers.zeros, (self.num_features,))

        if update_stats:
            axes = tuple(range(x.ndim - 1))  # all but the feature axis
            mean.value = jnp.mean(x, axis=axes)
            var.value = jnp.var(x, axis=axes)

        inv = jax.lax.rsqrt(var.value + self.eps)
        return (x - mean.value) * inv * gamma + beta


def capture_reference_stats(module: nn.Module, variables: dict, reference_batch):
    """Run the reference batch once, returning the frozen ``vbn_stats``.

    ``variables`` is the dict from ``module.init`` (contains ``params`` and
    initial ``vbn_stats``).  Returns the updated ``vbn_stats`` collection to
    be passed (immutably) to every subsequent apply.
    """
    _, updated = module.apply(
        variables, reference_batch, update_stats=True, mutable=["vbn_stats"]
    )
    return updated["vbn_stats"]
