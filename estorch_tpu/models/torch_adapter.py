"""Torch ↔ flax parameter adapters (SURVEY.md §7 design-delta 6).

Lets a reference user move a trained torch MLP onto the device path (or a
device-trained MLPPolicy back into torch for deployment) without retraining:

    params = torch_mlp_to_flax(torch_policy, MLPPolicy(action_dim=2, hidden=(32, 32)))
    flax_mlp_to_torch(params, torch_policy)

Covers the Sequential-of-Linear MLP shape both sides use (the reference's
example policies and our MLPPolicy).  Linear weights transpose between
conventions: torch stores (out, in), flax Dense stores (in, out).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np


def _torch_linears(policy):
    import torch

    linears = [m for m in policy.modules() if isinstance(m, torch.nn.Linear)]
    for i, lin in enumerate(linears):
        if lin.bias is None:
            raise ValueError(
                f"Linear layer {i} has bias=False; the adapter maps to flax "
                "Dense layers which always carry a bias — add biases (they "
                "can be zero) or adapt the layer manually"
            )
    return linears


def _flax_dense_names(params: Any) -> list[str]:
    """MLPPolicy layer names in forward order: dense_0..dense_{n-1}, head."""
    names = sorted(
        (n for n in params if n.startswith("dense_")),
        key=lambda n: int(n.split("_")[1]),
    )
    if "head" in params:
        names.append("head")
    return names


def torch_mlp_to_flax(torch_policy, flax_module, example_obs=None) -> Any:
    """Flax ``params`` for ``flax_module`` carrying ``torch_policy``'s weights.

    ``flax_module`` must be an MLPPolicy-shaped module whose Dense layers
    correspond 1:1 (in forward order) to the torch policy's Linear layers.
    """
    import jax

    linears = _torch_linears(torch_policy)
    if example_obs is None:
        example_obs = jnp.zeros((linears[0].in_features,), jnp.float32)
    variables = flax_module.init(jax.random.PRNGKey(0), example_obs)
    params = jax.tree_util.tree_map(np.asarray, variables["params"])

    names = _flax_dense_names(params)
    if len(linears) != len(names):
        raise ValueError(
            f"layer count mismatch: torch has {len(linears)} Linear layers, "
            f"flax module has {len(names)} Dense layers ({names})"
        )
    for lin, name in zip(linears, names):
        w = lin.weight.detach().cpu().numpy().T  # (out,in) -> (in,out)
        b = lin.bias.detach().cpu().numpy()
        if params[name]["kernel"].shape != w.shape:
            raise ValueError(
                f"shape mismatch at {name}: flax {params[name]['kernel'].shape} "
                f"vs torch {w.shape}"
            )
        params[name]["kernel"] = w
        params[name]["bias"] = b
    return jax.tree_util.tree_map(jnp.asarray, params)


def flax_mlp_to_torch(params: Any, torch_policy) -> None:
    """Load MLPPolicy ``params`` into a torch policy in place (inverse map)."""
    import torch

    linears = _torch_linears(torch_policy)
    names = _flax_dense_names(params)
    if len(linears) != len(names):
        raise ValueError(
            f"layer count mismatch: torch has {len(linears)}, flax has {len(names)}"
        )
    with torch.no_grad():
        for lin, name in zip(linears, names):
            # copies: jax-backed numpy views are read-only and/or
            # non-contiguous after .T, which torch.from_numpy rejects/warns on
            w = np.array(np.asarray(params[name]["kernel"]).T)
            b = np.array(np.asarray(params[name]["bias"]))
            if tuple(lin.weight.shape) != w.shape:
                # explicit check: Tensor.copy_ BROADCASTS, so a size-1
                # mismatch would silently duplicate rows instead of erroring
                raise ValueError(
                    f"shape mismatch at {name}: torch {tuple(lin.weight.shape)} "
                    f"vs flax (transposed) {w.shape}"
                )
            lin.weight.copy_(torch.from_numpy(w))
            lin.bias.copy_(torch.from_numpy(b))
