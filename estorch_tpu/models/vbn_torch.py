"""Torch VirtualBatchNorm for the host (reference-parity) backend.

The reference ships ``estorch.VirtualBatchNorm`` as a ``torch.nn.Module``
(SURVEY.md §2 item 6) so users drop it into their torch policies.  Host-path
users here get the same module; device-path users get the flax twin
(models/vbn.py).  Semantics (both): statistics are computed once from the
first batch seen (the reference batch) and frozen; later calls normalize
with those frozen statistics plus a learned affine.
"""

from __future__ import annotations

import torch


class TorchVirtualBatchNorm(torch.nn.Module):
    """Freeze normalization stats on the first (reference) forward pass."""

    def __init__(self, num_features: int, eps: float = 1e-5):
        super().__init__()
        self.num_features = num_features
        self.eps = eps
        self.scale = torch.nn.Parameter(torch.ones(num_features))
        self.bias = torch.nn.Parameter(torch.zeros(num_features))
        self.register_buffer("ref_mean", torch.zeros(num_features))
        self.register_buffer("ref_var", torch.ones(num_features))
        self.register_buffer("initialized", torch.tensor(False))

    @torch.no_grad()
    def set_reference(self, reference_batch: torch.Tensor) -> None:
        """Explicitly freeze statistics from a reference batch."""
        dims = tuple(range(reference_batch.dim() - 1))
        self.ref_mean.copy_(reference_batch.mean(dim=dims))
        self.ref_var.copy_(reference_batch.var(dim=dims, unbiased=False))
        self.initialized.fill_(True)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        if not bool(self.initialized):
            if x.dim() < 2 or x.shape[0] < 2:
                # a single observation has zero variance — freezing from it
                # would scale activations by rsqrt(eps). Require a real batch.
                raise RuntimeError(
                    "TorchVirtualBatchNorm statistics are not initialized; "
                    "call set_reference(reference_batch) with a batch of "
                    "observations before rollouts (or run one batched forward)"
                )
            # first *batched* call = reference pass (lazy init)
            self.set_reference(x)
        inv = torch.rsqrt(self.ref_var + self.eps)
        return (x - self.ref_mean) * inv * self.scale + self.bias
