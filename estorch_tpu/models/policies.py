"""Bundled policy networks (flax), mirroring the reference's example policies.

The reference leaves policies entirely to the user (any ``torch.nn.Module``,
SURVEY.md §1 'rollout contract'); its examples use small MLPs, and the Atari
config implies the Nature DQN CNN.  We bundle TPU-idiomatic equivalents:

- ``MLPPolicy`` — tanh MLP for classic-control / MuJoCo configs.  Continuous
  heads tanh-squash and scale, discrete heads emit logits (argmax action
  selection happens in envs/rollout.py, matching the reference).
- ``NatureCNN`` — the 84×84×4 Atari trunk (conv 32×8s4, 64×4s2, 64×3s1,
  dense 512) with an optional VirtualBatchNorm after each conv, which is the
  OpenAI-ES Atari setup the reference's VBN module exists for.

All modules are shape-static and bf16-friendly; matmuls/convs land on the
MXU when vmapped across the population.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from .vbn import VirtualBatchNorm


class MLPPolicy(nn.Module):
    """Tanh MLP policy.

    ``action_dim`` is the number of discrete actions (``discrete=True``) or
    the action dimensionality (continuous, squashed to ±``action_scale``).
    """

    action_dim: int
    hidden: Sequence[int] = (64, 64)
    discrete: bool = True
    action_scale: float = 1.0
    activation: Callable = nn.tanh
    use_vbn: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, update_stats: bool = False) -> jnp.ndarray:
        for i, h in enumerate(self.hidden):
            x = nn.Dense(h, name=f"dense_{i}")(x)
            if self.use_vbn:
                x = VirtualBatchNorm(h, name=f"vbn_{i}")(x, update_stats=update_stats)
            x = self.activation(x)
        x = nn.Dense(self.action_dim, name="head")(x)
        if not self.discrete:
            x = jnp.tanh(x) * self.action_scale
        return x


class NatureCNN(nn.Module):
    """Nature-DQN CNN policy for Atari-style (84, 84, C) observations."""

    action_dim: int
    use_vbn: bool = True
    discrete: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray, update_stats: bool = False) -> jnp.ndarray:
        squeeze = x.ndim == 3
        if squeeze:  # single observation -> add batch axis for convs
            x = x[None]
        if jnp.issubdtype(x.dtype, jnp.integer):
            x = x.astype(jnp.float32) / 255.0  # raw Atari bytes
        else:
            x = x.astype(jnp.float32)  # already-normalized pixels (pong84)
        for i, (feat, kern, stride) in enumerate(
            [(32, 8, 4), (64, 4, 2), (64, 3, 1)]
        ):
            x = nn.Conv(feat, (kern, kern), strides=(stride, stride), padding="VALID",
                        name=f"conv_{i}")(x)
            if self.use_vbn:
                x = VirtualBatchNorm(feat, name=f"vbn_{i}")(x, update_stats=update_stats)
            x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, name="fc")(x))
        x = nn.Dense(self.action_dim, name="head")(x)
        return x[0] if squeeze else x
