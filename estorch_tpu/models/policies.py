"""Bundled policy networks (flax), mirroring the reference's example policies.

The reference leaves policies entirely to the user (any ``torch.nn.Module``,
SURVEY.md §1 'rollout contract'); its examples use small MLPs, and the Atari
config implies the Nature DQN CNN.  We bundle TPU-idiomatic equivalents:

- ``MLPPolicy`` — tanh MLP for classic-control / MuJoCo configs.  Continuous
  heads tanh-squash and scale, discrete heads emit logits (argmax action
  selection happens in envs/rollout.py, matching the reference).
- ``NatureCNN`` — the 84×84×4 Atari trunk (conv 32×8s4, 64×4s2, 64×3s1,
  dense 512) with an optional VirtualBatchNorm after each conv, which is the
  OpenAI-ES Atari setup the reference's VBN module exists for.
- ``RecurrentPolicy`` — MLP trunk + GRU core for partially observable
  tasks.  The reference has no recurrent machinery (the user-owned
  ``agent.rollout`` loop threads hidden state by hand, SURVEY.md §3.3);
  here the episode loop is a compiled ``lax.scan``, so the framework
  threads the carry (envs/rollout.py) — marked by ``is_recurrent`` and the
  ``carry_init``/two-return apply contract.

All modules are shape-static and bf16-friendly; matmuls/convs land on the
MXU when vmapped across the population.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .vbn import VirtualBatchNorm


class MLPPolicy(nn.Module):
    """Tanh MLP policy.

    ``action_dim`` is the number of discrete actions (``discrete=True``) or
    the action dimensionality (continuous, squashed to ±``action_scale``).
    """

    action_dim: int
    hidden: Sequence[int] = (64, 64)
    discrete: bool = True
    action_scale: float = 1.0
    activation: Callable = nn.tanh
    use_vbn: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, update_stats: bool = False) -> jnp.ndarray:
        for i, h in enumerate(self.hidden):
            x = nn.Dense(h, name=f"dense_{i}")(x)
            if self.use_vbn:
                x = VirtualBatchNorm(h, name=f"vbn_{i}")(x, update_stats=update_stats)
            x = self.activation(x)
        x = nn.Dense(self.action_dim, name="head")(x)
        if not self.discrete:
            x = jnp.tanh(x) * self.action_scale
        return x


class RecurrentPolicy(nn.Module):
    """MLP trunk + recurrent core (GRU or LSTM stack) + action head, for
    POMDPs.

    Apply contract (recurrent): ``module.apply(vars, obs, carry) ->
    (out, new_carry)``; ``carry_init(params=None)`` gives the
    episode-start carry — an array for the GRU, a ``(c, h)`` tuple for
    the LSTM, and a tuple of per-layer carries when ``n_layers > 1``
    (every consumer is pytree-agnostic, so the cell choice and depth are
    invisible downstream).  The cells are ordinary dense matmuls —
    vmapped across the population they batch onto the MXU exactly like
    the feedforward policies.

    ``learned_carry=True`` promotes the episode-start carry to ordinary
    parameters (``carry0_*``): they are perturbed by ES noise and moved
    by the update like any weight, and ``carry_init(params)`` reads the
    member's values at episode start (the rollout passes the member's
    perturbed tree — envs/rollout.py).  With ``params=None`` it falls
    back to zeros, which is exactly what module init needs for a shape
    donor.  Device path only: the pooled backend initializes carries
    before member params exist and is gated in ``algo/es.py``.
    """

    action_dim: int
    hidden: Sequence[int] = (64,)
    gru_size: int = 64
    discrete: bool = True
    action_scale: float = 1.0
    activation: Callable = nn.tanh
    cell: str = "gru"  # "gru" | "lstm"
    n_layers: int = 1
    learned_carry: bool = False

    # marks the module for ES/rollout wiring (not a dataclass field)
    is_recurrent = True

    def _check_cell(self) -> None:
        if self.cell not in ("gru", "lstm"):
            raise ValueError(f"cell must be 'gru' or 'lstm', got {self.cell!r}")
        if self.n_layers < 1:
            raise ValueError(f"n_layers must be >= 1, got {self.n_layers}")

    def _cell_name(self, j: int) -> str:
        # layer 0 keeps the historical single-layer name so existing
        # checkpoints and goldens stay valid
        return self.cell if j == 0 else f"{self.cell}_{j}"

    def _carry0_names(self, j: int) -> tuple[str, ...]:
        if self.cell == "lstm":
            return (f"carry0_c_{j}", f"carry0_h_{j}")
        return (f"carry0_{j}",)

    @nn.compact
    def __call__(self, x: jnp.ndarray, carry) -> tuple[jnp.ndarray, Any]:
        self._check_cell()
        for i, h in enumerate(self.hidden):
            x = self.activation(nn.Dense(h, name=f"dense_{i}")(x))
        carries = (carry,) if self.n_layers == 1 else tuple(carry)
        new_carries = []
        for j in range(self.n_layers):
            if self.cell == "lstm":
                c, x = nn.OptimizedLSTMCell(
                    features=self.gru_size, name=self._cell_name(j)
                )(carries[j], x)
            else:
                c, x = nn.GRUCell(
                    features=self.gru_size, name=self._cell_name(j)
                )(carries[j], x)
            new_carries.append(c)
        if self.learned_carry:
            # declared here so they live in the param tree (created at
            # module.init); consumed by carry_init(params) at episode
            # start, not by the per-step forward
            for j in range(self.n_layers):
                for name in self._carry0_names(j):
                    self.param(name, nn.initializers.zeros,
                               (self.gru_size,))
        x = nn.Dense(self.action_dim, name="head")(x)
        if not self.discrete:
            x = jnp.tanh(x) * self.action_scale
        out_carry = new_carries[0] if self.n_layers == 1 else tuple(new_carries)
        return x, out_carry

    def carry_init(self, params=None):
        self._check_cell()
        if self.learned_carry and params is not None:
            p = params["params"] if "params" in params else params

            def one(j):
                vals = tuple(p[name] for name in self._carry0_names(j))
                return vals if self.cell == "lstm" else vals[0]
        else:
            z = jnp.zeros((self.gru_size,), jnp.float32)

            def one(j):
                return (z, z) if self.cell == "lstm" else z
        per = [one(j) for j in range(self.n_layers)]
        return per[0] if self.n_layers == 1 else tuple(per)


def _nature_conv_stack(x: jnp.ndarray, use_vbn: bool = False,
                       update_stats: bool = False) -> jnp.ndarray:
    """The shared Nature-DQN conv trunk (32×8s4, 64×4s2, 64×3s1) — ONE
    definition serves NatureCNN and RecurrentNatureCNN so the spec cannot
    drift between them.  Called inside an ``nn.compact`` ``__call__``;
    submodule names stay ``conv_i``/``vbn_i``."""
    for i, (feat, kern, stride) in enumerate(
        [(32, 8, 4), (64, 4, 2), (64, 3, 1)]
    ):
        x = nn.Conv(feat, (kern, kern), strides=(stride, stride),
                    padding="VALID", name=f"conv_{i}")(x)
        if use_vbn:
            x = VirtualBatchNorm(feat, name=f"vbn_{i}")(x,
                                                        update_stats=update_stats)
        x = nn.relu(x)
    return x


class RecurrentNatureCNN(nn.Module):
    """Nature-DQN conv trunk + GRU core + head: vision policies with
    memory, for the pooled Atari path (flickering/occluded-screen POMDPs
    where frame stacking is not enough).

    Same recurrent apply contract as :class:`RecurrentPolicy`.  No VBN:
    the reference-batch capture applies the module statelessly, which has
    no recurrent form (the GRU core provides the activation stability VBN
    exists to add).
    """

    action_dim: int
    gru_size: int = 256
    discrete: bool = True
    action_scale: float = 1.0

    is_recurrent = True

    @nn.compact
    def __call__(self, x: jnp.ndarray, carry) -> tuple[jnp.ndarray, Any]:
        # normalize into the CARRY's dtype — the engine casts carry_init
        # to the compute dtype (bf16 path), so this keeps the whole
        # forward and the returned carry dtype-pure; a hard f32 cast here
        # would silently promote every activation and flip the scan
        # carry's dtype mid-episode
        target = jax.tree_util.tree_leaves(carry)[0].dtype
        if jnp.issubdtype(x.dtype, jnp.integer):
            x = x.astype(target) / jnp.asarray(255.0, target)
        else:
            x = x.astype(target)
        x = x[None]  # single observation -> batch axis for the convs
        x = _nature_conv_stack(x)
        x = x.reshape(-1)
        x = nn.relu(nn.Dense(512, name="fc")(x))
        carry, x = nn.GRUCell(features=self.gru_size, name="gru")(carry, x)
        x = nn.Dense(self.action_dim, name="head")(x)
        if not self.discrete:
            x = jnp.tanh(x) * self.action_scale
        return x, carry

    def carry_init(self, params=None) -> jnp.ndarray:
        return jnp.zeros((self.gru_size,), jnp.float32)


class NatureCNN(nn.Module):
    """Nature-DQN CNN policy for Atari-style (84, 84, C) observations."""

    action_dim: int
    use_vbn: bool = True
    discrete: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray, update_stats: bool = False) -> jnp.ndarray:
        squeeze = x.ndim == 3
        if squeeze:  # single observation -> add batch axis for convs
            x = x[None]
        if jnp.issubdtype(x.dtype, jnp.integer):
            x = x.astype(jnp.float32) / 255.0  # raw Atari bytes
        else:
            x = x.astype(jnp.float32)  # already-normalized pixels (pong84)
        x = _nature_conv_stack(x, use_vbn=self.use_vbn,
                               update_stats=update_stats)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, name="fc")(x))
        x = nn.Dense(self.action_dim, name="head")(x)
        return x[0] if squeeze else x
