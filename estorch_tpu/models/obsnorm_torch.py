"""Running observation normalization for HOST-path (torch) policies.

The device path normalizes observations inside the compiled generation
program (``ES(..., obs_norm=True)``, parallel/engine.py).  Host-path
users own their rollout loops (reference contract, SURVEY.md §3.3), so
they normalize there — this module is the torch twin with the SAME math
(Welford (count, mean, m2) running triple, Chan parallel merge, clipped
(obs−mean)·rsqrt(var)), so a policy trained either way sees identically
normalized inputs.

Usage in a reference-style agent::

    norm = TorchRunningObsNorm(obs_dim)
    def rollout(self, policy):
        obs = env.reset()
        while not done:
            action = policy(norm(torch.as_tensor(obs)))
            ...
        norm.update(torch.as_tensor(episode_obs))   # feed raw moments

The stats are registered buffers: ``state_dict()`` round-trips them, so
torch checkpoints resume with the stats intact.
"""

from __future__ import annotations

import torch


class TorchRunningObsNorm(torch.nn.Module):
    def __init__(self, obs_dim: int, clip: float = 5.0):
        super().__init__()
        self.clip = float(clip)
        # count=1, mean=0, m2=1 → var 1: identity-ish until fed, matching
        # the device path's init (parallel/engine.py init_state)
        self.register_buffer("count", torch.tensor(1.0))
        self.register_buffer("mean", torch.zeros(obs_dim))
        self.register_buffer("m2", torch.ones(obs_dim))

    @torch.no_grad()
    def update(self, obs_batch: torch.Tensor) -> None:
        """Fold a (n, obs_dim) batch of RAW observations into the running
        stats — the Chan parallel update, identical to the device path's
        merge_obs_moments."""
        obs_batch = obs_batch.reshape(-1, self.mean.shape[0]).float()
        c1 = torch.tensor(float(obs_batch.shape[0]))
        if float(c1) == 0.0:
            return
        mean1 = obs_batch.mean(dim=0)
        m2_1 = ((obs_batch - mean1) ** 2).sum(dim=0)
        tot = self.count + c1
        delta = mean1 - self.mean
        self.mean += delta * (c1 / tot)
        self.m2 += m2_1 + delta * delta * (self.count * c1 / tot)
        self.count.copy_(tot)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        var = torch.clamp(self.m2 / self.count, min=1e-8)
        out = (x.float() - self.mean) * torch.rsqrt(var)
        return torch.clamp(out, -self.clip, self.clip)
