"""Decomposed population forward: one shared matmul + a streamed noise term.

For a linear layer with shared center weights W and per-member noise E_i,

    z_i = x_i @ (W + c_i E_i)  =  x_i @ W  +  c_i (x_i @ E_i),   c_i = σ s_i

— exact (a reordering of the same contractions, not an approximation).  The
engine's standard path materializes W + c_i E_i per member, so every layer
is a batched per-member matvec.  Decomposed, the W-term of every layer is a
SINGLE dense (population, d) @ (d, h) matmul (W enters vmap un-batched), a
shape the MXU eats whole; only the noise term remains per-member.  On TPU a
Pallas kernel can further stream E_i from the HBM table tile-by-tile
(ROADMAP item 1); this module is the pure-JAX form that already exposes the
big matmul to XLA.

Scope: MLPPolicy-shaped networks (Dense stacks, tanh/… activations,
optional continuous squash).  VBN layers are not yet supported here — the
affine is decomposable too, but stats plumbing is deferred (engine rejects
the combination loudly).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp


def _ordered_dense_names(params: Any) -> list[str]:
    names = sorted(
        (n for n in params if n.startswith("dense_")),
        key=lambda n: int(n.split("_")[1]),
    )
    names.append("head")
    return names


def supports_decomposed(module) -> bool:
    """True for modules whose forward this file can reproduce exactly."""
    from .policies import MLPPolicy

    # exact type: an MLPPolicy SUBCLASS may override __call__, which this
    # file would silently fail to reproduce — fail loudly instead
    return type(module) is MLPPolicy and not module.use_vbn


def mlp_decomposed_apply(
    module, shared_params: Any, noise_params: Any, scale, obs: jnp.ndarray
) -> jnp.ndarray:
    """Exact MLPPolicy forward with weights (shared + scale·noise), never
    materializing the sum.

    ``noise_params`` is the member's ε unraveled into the SAME pytree shape
    as ``shared_params`` (ops/params.py spec.unravel of the raw table
    slice); ``scale`` is σ·sign (a traced scalar).
    """
    names = _ordered_dense_names(shared_params)
    x = obs
    for i, name in enumerate(names):
        w = shared_params[name]["kernel"]
        b = shared_params[name]["bias"]
        nw = noise_params[name]["kernel"]
        nb = noise_params[name]["bias"]
        # x @ w is shared across members (un-batched under vmap → one dense
        # population-wide matmul); x @ nw is the per-member noise term
        x = (x @ w) + scale * (x @ nw) + b + scale * nb
        if name != "head":
            x = module.activation(x)
    if not module.discrete:
        x = jnp.tanh(x) * module.action_scale
    return x


def mlp_lowrank_apply(
    module, shared_params: Any, lr_noise: dict, scale, obs: jnp.ndarray
) -> jnp.ndarray:
    """Exact MLPPolicy forward with weights (shared + scale·A Bᵀ/√r), never
    materializing any dense noise matrix.

    ``lr_noise`` is {name: (A, B, bias_noise)} from LowRankSpec.unpack
    (ops/lowrank.py); ``scale`` is σ·sign.  The noise term costs
    O((m+n)·r) per step instead of O(m·n):
        x @ (W + c·A Bᵀ/√r) = x@W + (c/√r)·((x@A) @ Bᵀ)
    """
    names = _ordered_dense_names(shared_params)
    x = obs
    for name in names:
        w = shared_params[name]["kernel"]
        b = shared_params[name]["bias"]
        a, bt, nb = lr_noise[name]
        if bt is None:
            # dense-fallback layer (rank ≥ min(m, n)): a IS the full E
            noise_term = scale * (x @ a)
        else:
            r = a.shape[-1]
            c = scale / jnp.sqrt(jnp.asarray(r, x.dtype))
            noise_term = c * ((x @ a) @ bt.T)
        x = (x @ w) + noise_term + b + scale * nb
        if name != "head":
            x = module.activation(x)
    if not module.discrete:
        x = jnp.tanh(x) * module.action_scale
    return x
