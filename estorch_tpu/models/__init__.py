from .policies import MLPPolicy, NatureCNN, RecurrentNatureCNN, RecurrentPolicy
from .vbn import VirtualBatchNorm, capture_reference_stats


def __getattr__(name):
    # torch import is deferred: device-path users never pay for it
    if name == "TorchVirtualBatchNorm":
        from .vbn_torch import TorchVirtualBatchNorm

        return TorchVirtualBatchNorm
    raise AttributeError(name)


__all__ = [
    "MLPPolicy",
    "NatureCNN",
    "RecurrentNatureCNN",
    "RecurrentPolicy",
    "VirtualBatchNorm",
    "TorchVirtualBatchNorm",
    "capture_reference_stats",
]
