from .policies import MLPPolicy, NatureCNN, RecurrentNatureCNN, RecurrentPolicy
from .vbn import VirtualBatchNorm, capture_reference_stats


def __getattr__(name):
    # torch imports are deferred: device-path users never pay for them
    if name == "TorchVirtualBatchNorm":
        from .vbn_torch import TorchVirtualBatchNorm

        return TorchVirtualBatchNorm
    if name == "TorchRunningObsNorm":
        from .obsnorm_torch import TorchRunningObsNorm

        return TorchRunningObsNorm
    raise AttributeError(name)


__all__ = [
    "MLPPolicy",
    "NatureCNN",
    "RecurrentNatureCNN",
    "TorchRunningObsNorm",
    "RecurrentPolicy",
    "VirtualBatchNorm",
    "TorchVirtualBatchNorm",
    "capture_reference_stats",
]
