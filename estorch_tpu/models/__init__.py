from .policies import MLPPolicy, NatureCNN
from .vbn import VirtualBatchNorm, capture_reference_stats

__all__ = ["MLPPolicy", "NatureCNN", "VirtualBatchNorm", "capture_reference_stats"]
