"""Centered rank transformation (fitness shaping).

Reference behavior: estorch's rank transform maps raw episode returns to
centered ranks in [-0.5, 0.5] before the gradient estimate, making the update
invariant to reward scale/outliers (reference: ``estorch/estorch.py`` rank
helpers, upstream path — SURVEY.md §2 item 2; Salimans et al. 2017 §2.1).

TPU-native notes: computed on-device with a double argsort so the whole
generation stays one compiled program.  Every device ranks the SAME globally
all-gathered fitness vector, so the resulting weights are bit-identical
everywhere — a precondition for the broadcast-free update.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def compute_ranks(x: jax.Array) -> jax.Array:
    """Integer ranks in [0, n): rank of the smallest element is 0.

    Ties broken by position (stable argsort), matching ``np.argsort`` — the
    same tie behavior a NumPy implementation of the reference has.
    """
    n = x.shape[0]
    order = jnp.argsort(x)
    ranks = jnp.zeros((n,), dtype=jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    return ranks


def centered_rank(x: jax.Array) -> jax.Array:
    """Map fitness to centered ranks in [-0.5, 0.5].

    ``centered_rank(x)_i = rank(x_i)/(n-1) - 0.5``; the result sums to zero,
    so the ES update is invariant to adding a constant to all returns.
    """
    n = x.shape[0]
    if n < 2:
        return jnp.zeros_like(x, dtype=jnp.float32)
    ranks = compute_ranks(x).astype(jnp.float32)
    return ranks / (n - 1) - 0.5


def centered_rank_safe(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Non-finite-tolerant centered ranks — the in-program (jittable) twin of
    ``utils/fault.py::rank_weights_with_failures``.

    ``jnp.argsort`` sorts NaN LAST, so without a guard a member whose rollout
    produced NaN reward would receive the TOP centered rank (+0.5) and its
    noise would dominate a still-finite update — silent corruption.  Here
    invalid (NaN/±inf) members are zero-weighted, valid members are ranked
    among themselves (stable, matching the host scheme), and survivors are
    rescaled by n/n_valid so the engine's static 1/n normalization yields the
    mean over actual contributors.

    Bit-identical to :func:`centered_rank` when all entries are finite (the
    fixed-seed goldens pin this).  Returns ``(weights, n_valid)``; when fewer
    than 2 members are valid the weights are all zero (the host backend
    raises instead — in-program we cannot, so callers surface ``n_valid``).
    """
    n = x.shape[0]
    valid = jnp.isfinite(x)
    n_valid = valid.sum().astype(jnp.int32)
    if n < 2:
        return jnp.zeros_like(x, dtype=jnp.float32), n_valid
    # invalid -> +inf sorts after every finite value (stable among themselves,
    # harmless: they get weight 0); valid members' positions in the sorted
    # order are then exactly their ranks within the valid subset
    order = jnp.argsort(jnp.where(valid, x, jnp.inf))
    pos = jnp.zeros((n,), dtype=jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    denom = jnp.maximum(n_valid - 1, 1).astype(jnp.float32)
    sub = pos.astype(jnp.float32) / denom - 0.5
    scale = jnp.float32(n) / jnp.maximum(n_valid, 1).astype(jnp.float32)
    weights = jnp.where(valid, sub * scale, 0.0)
    weights = jnp.where(n_valid >= 2, weights, jnp.zeros_like(weights))
    return weights.astype(jnp.float32), n_valid


def centered_rank_np(x) -> np.ndarray:
    """NumPy twin of :func:`centered_rank` for host-side weighting (novelty
    family): must match the device version bit-for-bit on tie-free input and
    tie-behavior-for-tie-behavior otherwise (both use stable argsort).

    Known (harmless) divergence: XLA flushes float32 subnormals to zero, so
    two fitness values whose difference is subnormal (<~1.2e-38) tie on
    device but not here.  Ranking always happens on ONE array from ONE
    implementation per generation, so this never mixes — found by the
    property suite (tests/test_properties.py), recorded for posterity.
    """
    x = np.asarray(x)
    n = x.shape[0]
    if n < 2:
        return np.zeros_like(x, dtype=np.float32)
    ranks = np.empty(n, dtype=np.int32)
    ranks[np.argsort(x, kind="stable")] = np.arange(n, dtype=np.int32)
    return (ranks.astype(np.float32) / (n - 1) - 0.5).astype(np.float32)


def normalized_score(x: jax.Array) -> jax.Array:
    """Z-score alternative to rank shaping (exposed for parity/testing)."""
    std = jnp.std(x)
    return (x - jnp.mean(x)) / jnp.where(std > 0, std, 1.0)
