"""Low-rank perturbations: per-layer E = A·Bᵀ/√r noise (ES at hyperscale).

The classic estimator perturbs every weight independently: ε_i is a full
(dim,) table slice, so noise memory/bandwidth per member is O(dim) and the
per-step forward must touch an O(m·n) noise matrix per layer.  The low-rank
family (PAPERS.md "Evolution Strategies at the Hyperscale") replaces each
layer's kernel noise with

    E = A @ Bᵀ / √r,     A ~ N(0,1)^(m×r),  B ~ N(0,1)^(n×r)

whose entries remain zero-mean unit-variance (E[AᵢₖBⱼₖ]=0, Var=r·(1/r)=1),
while the per-member noise state shrinks from Σ m·n to Σ (m+n)·r — at
Humanoid-MLP size (376→256→256→17, r=1) that is ~166k → ~2.4k floats, the
difference between HBM-resident populations of 10k and 700k members — and
the forward's noise term drops from O(m·n) to O((m+n)·r) per step:

    x @ (W + c·A Bᵀ/√r) = x@W + (c/√r)·((x@A) @ Bᵀ)

Layers where factoring would not actually save noise floats
((m+n)·r ≥ m·n — e.g. a 16×1 continuous head at any rank, or a small
square layer at high rank) fall back to exact dense Gaussian noise: the
fallback is exact AND no larger.  Bias noise is always dense (biases are
already O(n)).

The rank-weighted update never materializes any member's E_i:

    ΔW = Σ_i w_i A_i Bᵀ_i / √r = einsum('imr,inr->mn', w·A, B)/√r

one MXU contraction per layer over the whole population.  This is an
APPROXIMATION of isotropic-Gaussian ES (the search distribution is no
longer Gaussian in weight space); the hyperscale paper's result is that
the estimator's performance matches full ES as layer dims grow.

Sampling rides the same shared-noise-table machinery as the full-rank path
(ops/noise.py): one table offset per member/pair, A‖B‖dense‖bias noise
unpacked from a single contiguous (noise_dim,) slice — workers never
exchange noise, exactly as the reference's seed-passing protocol intends
(SURVEY.md §2.8).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LowRankSpec:
    """Static layout of one member's low-rank noise vector.

    ``lr_layers``: tuple of (name, m, n, a_off, b_off) — kernel noise
    factors A (m, r) and B (n, r) at those offsets into the noise vector.
    ``dense_layers``: tuple of (name, m, n, off) — layers where factoring
    would not save ((m+n)·rank ≥ m·n): exact dense kernel noise.
    ``biases``: tuple of (name, n, off) — dense bias noise.
    """

    rank: int
    noise_dim: int
    lr_layers: tuple
    dense_layers: tuple
    biases: tuple

    def unpack(self, noise_vec: jax.Array) -> dict:
        """(noise_dim,) slice → {name: (A, B, bias)} / {name: (E, None, bias)}.

        A 3-tuple per layer: low-rank layers carry (A, B, bias_noise); dense
        -fallback layers carry (E, None, bias_noise).  ``None`` is a pytree
        structural marker, so the dict vmaps/casts cleanly.
        """
        r = self.rank
        out = {}
        for name, m, n, a_off, b_off in self.lr_layers:
            a = jax.lax.dynamic_slice(noise_vec, (a_off,), (m * r,)).reshape(m, r)
            b = jax.lax.dynamic_slice(noise_vec, (b_off,), (n * r,)).reshape(n, r)
            out[name] = [a, b, None]
        for name, m, n, off in self.dense_layers:
            e = jax.lax.dynamic_slice(noise_vec, (off,), (m * n,)).reshape(m, n)
            out[name] = [e, None, None]
        for name, n, off in self.biases:
            nb = jax.lax.dynamic_slice(noise_vec, (off,), (n,))
            out[name][2] = nb
        return {k: tuple(v) for k, v in out.items()}


def make_lowrank_spec(params: Any, rank: int) -> LowRankSpec:
    """Layout from an MLP-shaped param tree ({name: {kernel, bias}})."""
    from ..models.decomposed import _ordered_dense_names

    if rank < 1:
        raise ValueError(f"low_rank must be >= 1, got {rank}")
    names = _ordered_dense_names(params)
    lr_layers, dense_layers, biases = [], [], []
    off = 0
    for name in names:
        m, n = params[name]["kernel"].shape
        # low-rank only where it actually SAVES: (m+n)·r < m·n (this also
        # implies r < min(m, n), since mn/(m+n) < min(m, n)); otherwise the
        # factors would cost more noise floats than exact dense Gaussian —
        # an approximation strictly worse than the thing it approximates
        if rank * (m + n) < m * n:
            lr_layers.append((name, m, n, off, off + m * rank))
            off += (m + n) * rank
        else:
            dense_layers.append((name, m, n, off))
            off += m * n
    for name in names:
        (n,) = params[name]["bias"].shape
        biases.append((name, n, off))
        off += n
    return LowRankSpec(
        rank=rank, noise_dim=off, lr_layers=tuple(lr_layers),
        dense_layers=tuple(dense_layers), biases=tuple(biases),
    )


def lowrank_program_factors(rank: int, m: int, n: int, key: jax.Array):
    """In-program (A, B) factors for one leaf/row — the sharded path's
    table-free twin of :meth:`LowRankSpec.unpack` (parallel/sharded.py):
    instead of unpacking factors from a table slice, they are generated
    from the (key, generation, row, leaf) chain (ops/noise.py).  Same
    statistics (entries of A·Bᵀ/√r are zero-mean unit-variance), same
    savings (the update einsum never materializes dense E)."""
    return (
        jax.random.normal(jax.random.fold_in(key, 0), (m, rank), jnp.float32),
        jax.random.normal(jax.random.fold_in(key, 1), (n, rank), jnp.float32),
    )


def lowrank_program_leaf_noise(rank: int, m: int, n: int, key: jax.Array) -> jax.Array:
    """Dense E = A·Bᵀ/√r from in-program factors (the eval-side form; the
    update side keeps the factors and einsums them — no dense E)."""
    a, b = lowrank_program_factors(rank, m, n, key)
    return (a @ b.T) / jnp.sqrt(jnp.float32(rank))


def dense_kernel(spec_rank: int, a, b):
    """One layer's dense E from its unpacked factors (oracle/snapshot path)."""
    if b is None:
        return a  # dense-fallback layer: a IS E
    return (a @ b.T) / jnp.sqrt(jnp.float32(spec_rank))


def lowrank_noise_tree(lr_spec: LowRankSpec, noise_vec: jax.Array) -> dict:
    """Materialize the DENSE noise pytree {name: {kernel, bias}} one member's
    slice represents — snapshot/debug path (member_params), not the hot path.
    """
    unpacked = lr_spec.unpack(noise_vec)
    return {
        name: {"kernel": dense_kernel(lr_spec.rank, a, b), "bias": nb}
        for name, (a, b, nb) in unpacked.items()
    }


def lowrank_weighted_sum(
    lr_spec: LowRankSpec, noise_mat: jax.Array, weights: jax.Array
) -> dict:
    """Σ_i w_i · dense(noise_i) without materializing any member's dense E.

    ``noise_mat``: (k, noise_dim) stacked member/pair slices;
    ``weights``: (k,) — rank weights (mirrored: already pair-folded w⁺−w⁻,
    exact because a pair shares ONE slice, so ±E share (A, B) and fold like
    full-rank noise).  Returns the dense {name: {kernel, bias}} pytree of
    the weighted sum.
    """
    r = lr_spec.rank
    k = noise_mat.shape[0]
    scale = 1.0 / jnp.sqrt(jnp.float32(r))
    out = {}
    for name, m, n, a_off, b_off in lr_spec.lr_layers:
        a = jax.lax.dynamic_slice(noise_mat, (0, a_off), (k, m * r)).reshape(k, m, r)
        b = jax.lax.dynamic_slice(noise_mat, (0, b_off), (k, n * r)).reshape(k, n, r)
        kernel = jnp.einsum("kmr,knr->mn", a * weights[:, None, None], b) * scale
        out[name] = {"kernel": kernel}
    for name, m, n, off in lr_spec.dense_layers:
        e = jax.lax.dynamic_slice(noise_mat, (0, off), (k, m * n))
        out[name] = {"kernel": (weights @ e).reshape(m, n)}
    for name, n, off in lr_spec.biases:
        nb = jax.lax.dynamic_slice(noise_mat, (0, off), (k, n))
        out[name]["bias"] = weights @ nb
    return out


# ---- generic pytree form (recurrent / arbitrary policies) -----------------
#
# The MLP spec above is keyed by layer NAME because its consumer
# (models/decomposed.py::mlp_lowrank_apply) restructures the MLP forward
# around the layer identity — the per-STEP noise term stays O((m+n)·r).
# Recurrent cells thread a carry through the episode scan, so their forward
# cannot be restructured the same way without reimplementing every cell.
# The tree form instead materializes each member's dense perturbation ONCE
# PER EPISODE (amortized over the horizon's steps — the per-step forward is
# then the standard rollout, carry threading included), while keeping the
# two properties that matter at population scale: the per-member noise
# STATE stays O(noise_dim) (the HBM win — table slices, never dense ε), and
# the update is the same no-materialization einsum per factored leaf.
# Transient per-chunk materialization equals what the standard path already
# does with W + σ·s·ε.
#
# Any 2-D leaf where factoring saves ((m+n)·r < m·n) is factored; all other
# leaves (biases, conv kernels, carry-init vectors) carry exact dense noise.


@dataclasses.dataclass(frozen=True)
class LowRankTreeSpec:
    """Static layout of one member's low-rank noise vector over an
    arbitrary param pytree (leaf order = ``jax.tree_util.tree_flatten``).

    ``lr_leaves``: (leaf_index, m, n, a_off, b_off) — factored 2-D leaves.
    ``dense_leaves``: (leaf_index, shape, size, off) — exact dense noise.
    """

    rank: int
    noise_dim: int
    treedef: Any
    lr_leaves: tuple
    dense_leaves: tuple


def make_lowrank_tree_spec(params: Any, rank: int) -> LowRankTreeSpec:
    """Layout from ANY param pytree — the recurrent-policy entry point."""
    if rank < 1:
        raise ValueError(f"low_rank must be >= 1, got {rank}")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    lr_leaves, dense_leaves = [], []
    off = 0
    for i, leaf in enumerate(leaves):
        shape = tuple(leaf.shape)
        if leaf.ndim == 2 and rank * (shape[0] + shape[1]) < shape[0] * shape[1]:
            m, n = shape
            lr_leaves.append((i, m, n, off, off + m * rank))
            off += (m + n) * rank
        else:
            size = 1
            for s in shape:
                size *= s
            dense_leaves.append((i, shape, size, off))
            off += size
    return LowRankTreeSpec(
        rank=rank, noise_dim=off, treedef=treedef,
        lr_leaves=tuple(lr_leaves), dense_leaves=tuple(dense_leaves),
    )


def lowrank_tree_noise(spec: LowRankTreeSpec, noise_vec: jax.Array) -> Any:
    """Materialize the dense noise pytree one member's slice represents."""
    r = spec.rank
    scale = 1.0 / jnp.sqrt(jnp.float32(r))
    leaves = [None] * (len(spec.lr_leaves) + len(spec.dense_leaves))
    for i, m, n, a_off, b_off in spec.lr_leaves:
        a = noise_vec[a_off:a_off + m * r].reshape(m, r)
        b = noise_vec[b_off:b_off + n * r].reshape(n, r)
        leaves[i] = (a @ b.T) * scale
    for i, shape, size, off in spec.dense_leaves:
        leaves[i] = noise_vec[off:off + size].reshape(shape)
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def lowrank_tree_perturb(
    spec: LowRankTreeSpec, params: Any, noise_vec: jax.Array, scale
) -> Any:
    """``params + scale · dense(noise_vec)`` — one member's perturbed tree,
    materialized once per episode (see the module-section comment)."""
    noise = lowrank_tree_noise(spec, noise_vec)
    return jax.tree_util.tree_map(lambda w, e: w + scale * e, params, noise)


def lowrank_tree_weighted_sum(
    spec: LowRankTreeSpec, noise_mat: jax.Array, weights: jax.Array
) -> Any:
    """Σ_i w_i · dense(noise_i) as a pytree, without materializing any
    member's dense noise — the tree twin of :func:`lowrank_weighted_sum`
    (same pair-folding argument: ±E share (A, B))."""
    r = spec.rank
    k = noise_mat.shape[0]
    scale = 1.0 / jnp.sqrt(jnp.float32(r))
    leaves = [None] * (len(spec.lr_leaves) + len(spec.dense_leaves))
    for i, m, n, a_off, b_off in spec.lr_leaves:
        a = noise_mat[:, a_off:a_off + m * r].reshape(k, m, r)
        b = noise_mat[:, b_off:b_off + n * r].reshape(k, n, r)
        leaves[i] = jnp.einsum("kmr,knr->mn", a * weights[:, None, None], b) * scale
    for i, shape, size, off in spec.dense_leaves:
        e = noise_mat[:, off:off + size]
        leaves[i] = (weights @ e).reshape(shape)
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)
