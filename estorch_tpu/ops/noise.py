"""Shared noise table and antithetic (mirrored) perturbation sampling.

TPU-native replacement for the reference's per-member ``torch.randn_like``
noise draw (reference: ``estorch/estorch.py``, upstream path — see SURVEY.md
§2 item 8; the mount was empty, so no line numbers).  Instead of generating
fresh Gaussian noise per population member — and shipping it (or its seed)
between processes — we keep ONE immutable float32 table in HBM and address
it with per-member integer offsets.  This is the OpenAI-ES shared-noise-table
design and the `north_star` of BASELINE.json:

- noise never crosses the wire: every device derives identical offsets from a
  shared PRNG key, so the update is reconstructed locally and reduced with a
  single ``lax.psum``;
- perturbation is a ``vmap``-ed dynamic-slice + axpy — a contiguous HBM read
  that XLA fuses into the policy matmuls, instead of Python-loop RNG;
- antithetic pairs (mirrored sampling, Salimans et al. 2017 §2) share an
  offset with flipped sign, halving table reads and variance.

All functions are pure and jit/shard_map compatible.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_TABLE_SIZE = 1 << 25  # 32M floats = 128 MiB of HBM; OpenAI-ES used 250M.


@dataclasses.dataclass(frozen=True)
class NoiseTable:
    """An immutable shared Gaussian noise table.

    ``data`` lives in HBM (or host RAM under the CPU backend).  ``size`` is
    static so slice shapes stay known to XLA.
    """

    data: jax.Array  # (size,) float32, ~N(0, 1)
    seed: int
    size: int

    def slice(self, offset: jax.Array, dim: int) -> jax.Array:
        """Noise vector of length ``dim`` starting at ``offset`` (traced ok)."""
        return jax.lax.dynamic_slice(self.data, (offset,), (dim,))


def _tree_flatten(t: NoiseTable):
    return (t.data,), (t.seed, t.size)


def _tree_unflatten(aux, children):
    (data,) = children
    seed, size = aux
    return NoiseTable(data=data, seed=seed, size=size)


jax.tree_util.register_pytree_node(NoiseTable, _tree_flatten, _tree_unflatten)


def make_noise_table(
    size: int = DEFAULT_TABLE_SIZE, seed: int = 0, dtype=jnp.float32
) -> NoiseTable:
    """Build the shared table once, deterministically from ``seed``.

    Every host/device that calls this with the same ``(size, seed)`` holds a
    bit-identical table — the precondition for broadcast-free updates.
    Generated in one XLA call (threefry is counter-based, so this is
    reproducible across backends).
    """
    key = jax.random.key(seed)
    data = jax.random.normal(key, (size,), dtype=dtype)
    return NoiseTable(data=data, seed=seed, size=size)


def sample_pair_offsets(
    key: jax.Array, n_pairs: int, table_size: int, dim: int
) -> jax.Array:
    """Uniform offsets for ``n_pairs`` antithetic pairs, each in [0, size-dim].

    Deterministic in ``key``: all devices compute the identical offset vector
    and slice their own shard — this replaces the reference's parameter
    broadcast entirely (BASELINE.json north_star).
    """
    if dim > table_size:
        raise ValueError(
            f"parameter dim {dim} exceeds noise table size {table_size}; "
            "grow the table (noise_table_size) to at least a few times dim"
        )
    return jax.random.randint(key, (n_pairs,), 0, table_size - dim + 1, dtype=jnp.int32)


def pair_signs(population_size: int) -> jax.Array:
    """Signs (+1, -1, +1, -1, ...) for mirrored sampling.

    Member ``2k`` evaluates ``θ + σ·ε_k``; member ``2k+1`` evaluates
    ``θ - σ·ε_k``.  ``population_size`` must be even.
    """
    if population_size % 2 != 0:
        raise ValueError(f"mirrored sampling needs an even population, got {population_size}")
    return jnp.where(jnp.arange(population_size) % 2 == 0, 1.0, -1.0).astype(jnp.float32)


def member_offsets(pair_offsets: jax.Array) -> jax.Array:
    """Expand per-pair offsets to per-member offsets: (n_pairs,) → (2*n_pairs,)."""
    return jnp.repeat(pair_offsets, 2)


# ---------------------------------------------------------------------------
# in-program noise (the hyperscale sharded path, parallel/sharded.py)
# ---------------------------------------------------------------------------
#
# The table above is the SECOND of three noise representations; at
# param-sharded scale even the table is a liability (128 MiB replicated
# HBM, and table offsets address the FLAT param vector — a layout a
# sharded tree no longer has).  The third representation generates ε
# inside the jitted program, keyed on (key, generation, row, leaf):
# threefry is counter-based, so the values are identical on every mesh
# shape and no ε buffer ever exists host-side or whole on one device —
# under GSPMD each device computes exactly its shard of each normal()
# (the same no-materialization idea as the ops/pallas_noise.py streamed
# kernels, moved from DMA engines into the RNG).  These three helpers
# define THE keying contract in one place so the eval-side perturbation
# and the update-side reduction can never diverge.


def leaf_noise_keys(gen_key: jax.Array, n_leaves: int) -> list[jax.Array]:
    """Per-leaf base keys for one generation's in-program noise.

    ``gen_key`` is the per-generation offset stream key (engine
    ``_gen_keys``); leaf ``i`` of the param tree (tree_flatten order)
    draws from ``fold_in(gen_key, i)``.  Static count → a Python list,
    resolved at trace time."""
    return [jax.random.fold_in(gen_key, i) for i in range(n_leaves)]


def row_noise_key(leaf_key: jax.Array, row: jax.Array) -> jax.Array:
    """Key for noise row ``row`` (pair index when mirrored, member index
    otherwise) of one leaf — the (key, generation, row, leaf) chain's
    last link.  ``row`` may be traced (vmapped over chunks)."""
    return jax.random.fold_in(leaf_key, row)


def program_noise(leaf_key: jax.Array, row: jax.Array, shape) -> jax.Array:
    """One leaf's ε for one noise row, generated in-program: ~N(0,1),
    deterministic in (leaf_key, row), identical on any mesh."""
    return jax.random.normal(row_noise_key(leaf_key, row), shape, jnp.float32)


# ---------------------------------------------------------------------------
# scenario parameter streams (estorch_tpu/scenarios, docs/scenarios.md)
# ---------------------------------------------------------------------------

SCENARIO_STREAM_SALT = 0x5CE7A2  # disjoint from every training stream: the
# engine folds the STATE key with (generation, 0|1) and the rollout key
# with member/center/probe indices; scenario draws fold a FRESH key built
# from the distribution's own integer seed, salted so a user reusing one
# seed integer for both ES and the distribution still gets disjoint streams


def scenario_variant_key(seed: int, variant) -> jax.Array:
    """THE ``(seed, variant)`` key for scenario-parameter draws.

    ``variant`` may be traced (the in-program assignment path draws it
    from the member's rollout key) or a Python int (host-side concrete
    draws for manifests and the sequential bench leg) — threefry is
    counter-based, so both spellings produce identical parameters.
    Deterministic in ``(seed, variant)`` alone: the same variant draws
    the same physics constants in every generation, member, process, and
    mesh shape, which is what makes a scenario REPLAYABLE."""
    base = jax.random.fold_in(jax.random.PRNGKey(seed), SCENARIO_STREAM_SALT)
    return jax.random.fold_in(base, variant)


@partial(jax.jit, static_argnames=("dim",))
def member_noise(table: NoiseTable, offsets: jax.Array, signs: jax.Array, dim: int) -> jax.Array:
    """Materialize signed noise rows for a batch of members: (n, dim).

    Only used for small batches (tests, chunked gradient accumulation);
    the engine never materializes the full population's noise at once.
    """
    rows = jax.vmap(lambda o: table.slice(o, dim))(offsets)
    return rows * signs[:, None]
