"""Parameter pytree <-> flat vector utilities.

The reference flattens ``policy.parameters()`` into a single vector to add
noise and to apply the estimated gradient (reference: ``estorch/estorch.py``
flatten/unflatten helpers — SURVEY.md §2 item 8).  In JAX the policy params
are a pytree; we use ``jax.flatten_util.ravel_pytree`` once at setup to get a
static ``unravel`` closure, then all hot-path math runs on the flat vector —
which is exactly the layout the noise-table slice and the rank-weighted
reduction want.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    """Static description of a policy's parameter pytree."""

    dim: int
    unravel: Callable[[jax.Array], Any]

    def flatten(self, tree: Any) -> jax.Array:
        flat, _ = ravel_pytree(tree)
        return flat


def make_param_spec(params: Any) -> tuple[jax.Array, ParamSpec]:
    """Flatten ``params`` once; return the flat vector and its static spec."""
    flat, unravel = ravel_pytree(params)
    return flat, ParamSpec(dim=int(flat.shape[0]), unravel=unravel)


def count_params(params: Any) -> int:
    return sum(int(jnp.size(p)) for p in jax.tree_util.tree_leaves(params))
