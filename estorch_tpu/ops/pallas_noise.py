"""Pallas TPU kernels that stream ε directly from the HBM noise table.

The pure-JAX paths materialize per-member noise: the update reduction
(ops/gradient.py) gathers (chunk, dim) blocks before contracting, and the
decomposed forward (models/decomposed.py) unravels a full (dim,) noise tree
per member that then lives in HBM for the whole episode — O(population·dim)
resident bytes at config-3 scale (10k × 166k ≈ 6.6 GB, more than a v5e's
HBM).  These kernels never materialize ε: tiles are DMA'd from the table
through double-buffered VMEM and consumed in place (ROADMAP item 1;
SURVEY.md §7 design deltas 1/4).

Two kernels share the grid shape:

- :func:`weighted_noise_sum` — the update reduction Σ_k w_k·ε_k.  Grid over
  noise rows; each row is DMA'd once and FMA'd into a VMEM accumulator that
  is only written back at the end.  Replaces gather→materialize→matvec with
  a single streamed pass (no (chunk, dim) intermediates).
- :func:`population_noise_matvec` — the per-member noise term of the
  decomposed forward, y_i = c_i·(x_i @ E_i), with E_i = the member's table
  slice viewed as a (d, h) matrix.  Grid over (members × row-blocks); each
  row-block is one contiguous B·h-float DMA, consumed as B static AXPYs —
  no reshape, no per-member weight materialization, ever.

Both run in interpret mode on CPU (equivalence-tested against the pure-JAX
paths in tests/test_pallas_noise.py) and compile to Mosaic on TPU.  The
``interpret`` default follows the backend.

Relation to the param-sharded path: these kernels make TABLE noise
never-materialized by streaming DMA; the sharded engine
(parallel/sharded.py) takes the same no-materialization goal one step
further by deleting the table — ε is generated in-program from the
(key, generation, row, leaf) chain (ops/noise.py program family) under
partitionable threefry, so each device's RNG emits exactly its shard of
each noise block straight into the scaled-add/FMA.  Same design
pressure, moved from the DMA engines into the bit generator; these
kernels remain the replicated engine's path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# update reduction: Σ_k w_k · table[o_k : o_k + dim]
# --------------------------------------------------------------------------


def _weighted_sum_kernel(dim: int):
    """Kernel body factory (dim is static)."""

    def kernel(offs_ref, w_ref, table_ref, out_ref, buf, sem):
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def dma(slot, row):
            return pltpu.make_async_copy(
                table_ref.at[pl.ds(offs_ref[row], dim)],
                buf.at[slot],
                sem.at[slot],
            )

        @pl.when(i == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
            dma(0, 0).start()

        # double buffering: next row's DMA flies while this row is consumed
        @pl.when(i + 1 < n)
        def _prefetch():
            dma((i + 1) % 2, i + 1).start()

        slot = jax.lax.rem(i, 2)
        dma(slot, i).wait()
        out_ref[...] += w_ref[i] * buf[slot, :]

    return kernel


@partial(jax.jit, static_argnames=("dim", "interpret"))
def weighted_noise_sum(
    table_data: jax.Array,  # (table_size,) float32 — NoiseTable.data
    offsets: jax.Array,  # (n,) int32 row offsets
    weights: jax.Array,  # (n,) float32 weight per row
    dim: int,
    interpret: bool | None = None,
) -> jax.Array:
    """Streamed Σ_k w_k·ε_k: one DMA per noise row, zero materialization.

    Drop-in for ops/gradient.py::rank_weighted_noise_sum (same contract);
    VMEM cost is 3·dim floats (double buffer + accumulator), so it suits
    dims up to ~1M params.  Callers with larger dims should keep the
    chunked pure-JAX path.
    """
    if interpret is None:
        interpret = _default_interpret()
    n = int(offsets.shape[0])
    if n == 0:
        return jnp.zeros((dim,), table_data.dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # offsets, weights
        grid=(n,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # table stays in HBM
        out_specs=pl.BlockSpec((dim,), lambda i, *_: (0,), memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, dim), table_data.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        _weighted_sum_kernel(dim),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((dim,), table_data.dtype),
        interpret=interpret,
    )(offsets.astype(jnp.int32), weights.astype(table_data.dtype), table_data)


# --------------------------------------------------------------------------
# decomposed-forward noise term: y_i = c_i · (x_i @ E_i)
# --------------------------------------------------------------------------


def _pick_row_block(d: int, h: int, budget_floats: int = 64 * 1024) -> int:
    """Largest divisor of d whose B·h DMA fits the per-buffer budget.

    Capped at 128 rows: the AXPY loop below unrolls B times, so an
    unbounded B (e.g. a wide layer feeding a 1-unit head) would balloon
    Mosaic compile time for no bandwidth gain.
    """
    best = 1
    for b in range(1, d + 1):
        if d % b == 0 and b * h <= budget_floats and b <= 128:
            best = b
    return best


def _noise_matvec_kernel(d: int, h: int, block_rows: int, layer_offset: int):
    n_blocks = d // block_rows

    def kernel(offs_ref, c_ref, x_ref, table_ref, y_ref, buf, sem):
        i = pl.program_id(0)  # member
        k = pl.program_id(1)  # row block (inner axis)
        n_i = pl.num_programs(0)

        def dma(slot, member, blk):
            start = offs_ref[member] + layer_offset + blk * (block_rows * h)
            return pltpu.make_async_copy(
                table_ref.at[pl.ds(start, block_rows * h)],
                buf.at[slot],
                sem.at[slot],
            )

        step = i * n_blocks + k

        @pl.when(step == 0)
        def _warmup():
            dma(0, 0, 0).start()

        # prefetch the NEXT grid step's block (possibly the next member's
        # first block) while this one is consumed
        nxt = step + 1

        @pl.when(nxt < n_i * n_blocks)
        def _prefetch():
            dma(
                jax.lax.rem(nxt, 2),
                nxt // n_blocks,
                jax.lax.rem(nxt, n_blocks),
            ).start()

        @pl.when(k == 0)
        def _init():
            y_ref[...] = jnp.zeros_like(y_ref)

        slot = jax.lax.rem(step, 2)
        dma(slot, i, k).wait()

        # B static AXPYs against contiguous h-float views of the DMA'd
        # block — the (B, h) matrix view never needs a reshape
        acc = jnp.zeros((h,), y_ref.dtype)
        for r in range(block_rows):
            acc = acc + x_ref[0, r] * buf[slot, pl.ds(r * h, h)]
        y_ref[0, :] += c_ref[i] * acc

    return kernel


@partial(
    jax.jit,
    static_argnames=("d", "h", "layer_offset", "interpret", "block_rows"),
)
def population_noise_matvec(
    table_data: jax.Array,  # (table_size,) float32
    offsets: jax.Array,  # (n,) int32 — each member's flat-ε start offset
    c: jax.Array,  # (n,) float32 — σ·sign per member
    x: jax.Array,  # (n, d) float32 — the layer's input batch
    layer_offset: int,  # this layer's kernel start WITHIN the member ε vector
    d: int,
    h: int,
    interpret: bool | None = None,
    block_rows: int | None = None,
) -> jax.Array:
    """y[i] = c[i] · (x[i] @ E_i) with E_i streamed from the table.

    ``E_i = table[offsets[i]+layer_offset : …+d·h]`` viewed row-major as
    (d, h) — exactly the layout ops/params.py's unravel gives a Dense
    kernel, so this reproduces models/decomposed.py's noise term without
    materializing any member's noise tree.
    """
    if interpret is None:
        interpret = _default_interpret()
    n = int(x.shape[0])
    if block_rows is None:
        block_rows = _pick_row_block(d, h)
    if d % block_rows != 0:
        raise ValueError(f"block_rows {block_rows} must divide d {d}")
    if block_rows > 512:
        raise ValueError(
            f"block_rows {block_rows} would unroll {block_rows} AXPYs into "
            "the kernel body; keep it <= 512 (auto-pick caps at 128)"
        )
    n_blocks = d // block_rows
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # offsets, c
        grid=(n, n_blocks),
        in_specs=[
            # x: one member's row-block per grid step — (1, B) in VMEM
            pl.BlockSpec(
                (1, block_rows), lambda i, k, *_: (i, k), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),  # table stays in HBM
        ],
        out_specs=pl.BlockSpec(
            (1, h), lambda i, k, *_: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, block_rows * h), table_data.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        _noise_matvec_kernel(d, h, block_rows, layer_offset),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, h), table_data.dtype),
        interpret=interpret,
    )(
        offsets.astype(jnp.int32),
        c.astype(table_data.dtype),
        x.astype(table_data.dtype),
        table_data,
    )


# --------------------------------------------------------------------------
# full streamed MLP forward (population-batched)
# --------------------------------------------------------------------------


def flat_layer_offsets(params) -> dict[str, dict[str, int]]:
    """Each leaf's start offset within the ravel_pytree flat vector.

    ravel_pytree flattens in tree order (sorted dict keys), each leaf
    row-major — the layout every table slice is unraveled with, so these
    offsets address a member's ε exactly like spec.unravel does.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    offsets: dict[str, dict[str, int]] = {}
    pos = 0
    for path, leaf in flat:
        layer = path[0].key
        name = path[1].key
        offsets.setdefault(layer, {})[name] = pos
        pos += int(leaf.size)
    return offsets


def mlp_streamed_apply(
    module,
    shared_params,
    table_data: jax.Array,
    offsets: jax.Array,  # (n,) member ε start offsets
    c: jax.Array,  # (n,) σ·sign
    obs: jax.Array,  # (n, obs_dim) population observation batch
    layer_offsets: dict[str, dict[str, int]],
    interpret: bool | None = None,
) -> jax.Array:
    """Population-batched MLPPolicy forward, weights (shared + c·ε) with ε
    streamed from the table.

    The shared-W term of every layer is one dense (n, d) @ (d, h) matmul
    (MXU); the noise term streams through :func:`population_noise_matvec`;
    bias noise is a tiny (n, h) gather.  Bit-for-bit this reorders the same
    contractions as models/decomposed.py::mlp_decomposed_apply, which the
    tests pin to float tolerance.
    """
    from ..models.decomposed import _ordered_dense_names

    names = _ordered_dense_names(shared_params)
    x = obs
    for name in names:
        w = shared_params[name]["kernel"]
        b = shared_params[name]["bias"]
        d, h = int(w.shape[0]), int(w.shape[1])
        noise_term = population_noise_matvec(
            table_data, offsets, c, x,
            layer_offset=layer_offsets[name]["kernel"],
            d=d, h=h, interpret=interpret,
        )
        # bias noise: h floats per member — a tiny gather, not worth a DMA
        bias_off = layer_offsets[name]["bias"]
        nb = jax.vmap(
            lambda o: jax.lax.dynamic_slice(table_data, (o + bias_off,), (h,))
        )(offsets)
        x = x @ w + noise_term + b + c[:, None] * nb
        if name != "head":
            x = module.activation(x)
    if not module.discrete:
        x = jnp.tanh(x) * module.action_scale
    return x
