"""ES gradient estimator: rank-weighted noise reduction.

Reference math (``estorch/estorch.py`` — SURVEY.md §2 item 1; Salimans et al.
2017 eq. 1): given fitness-shaped weights w_i for perturbations ε_i,

    ∇̂_θ E[f] = (1 / (n·σ)) Σ_i w_i · ε_i

The reference materializes every ε_i and loops in Python on the master after
an MPI gather.  TPU-native design: each device regenerates its own members'
ε_i from the shared noise table (ops/noise.py) and accumulates a LOCAL
partial sum as a single (chunk, dim) matvec — an MXU-friendly contraction —
then one ``lax.psum`` over the population mesh axis produces the global sum
on every device simultaneously.  No gather, no broadcast.

Mirrored sampling is folded: members 2k (+ε_k) and 2k+1 (−ε_k) share table
row k, so  Σ_i w_i·s_i·ε_i = Σ_k (w_{2k} − w_{2k+1})·ε_k  — half the table
gathers and half the contraction size of a naive per-member reduction.

Memory: the (chunk, dim) noise block is re-sliced from the table per chunk
(scan), so peak memory is O(chunk·dim), not O(population·dim).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .noise import NoiseTable


@partial(jax.jit, static_argnames=("dim", "chunk"))
def rank_weighted_noise_sum(
    table: NoiseTable,
    offsets: jax.Array,  # (n,) int32 table offsets (one per noise row)
    weights: jax.Array,  # (n,) float32 weight per noise row
    dim: int,
    chunk: int = 256,
) -> jax.Array:
    """Σ_i weights_i · ε_i without materializing all n noise rows.

    Scans over ⌈n/chunk⌉ blocks; within a block, a vmap of dynamic slices
    builds (chunk, dim) and a single matvec contracts it.  Any ``n`` works:
    non-multiples of ``chunk`` are zero-padded internally (zero-weight rows
    contribute nothing, so the padding offsets just re-read row 0).
    """
    n = offsets.shape[0]
    if n <= chunk:
        rows = jax.vmap(lambda o: table.slice(o, dim))(offsets)
        return weights @ rows

    if n % chunk != 0:
        pad = chunk - n % chunk
        offsets = jnp.concatenate([offsets, jnp.zeros((pad,), offsets.dtype)])
        weights = jnp.concatenate([weights, jnp.zeros((pad,), weights.dtype)])
        n = n + pad

    offsets = offsets.reshape(-1, chunk)
    weights = weights.reshape(-1, chunk)

    def body(acc, ow):
        o, w = ow
        rows = jax.vmap(lambda off: table.slice(off, dim))(o)  # (chunk, dim)
        return acc + w @ rows, None

    acc0 = jnp.zeros((dim,), dtype=table.data.dtype)
    acc, _ = jax.lax.scan(body, acc0, (offsets, weights))
    return acc


def fold_mirrored_weights(rank_weights: jax.Array) -> jax.Array:
    """Per-pair weights (w_{2k} − w_{2k+1}) from per-member rank weights.

    Valid for the mirrored layout where member 2k uses +ε_k and member 2k+1
    uses −ε_k (ops/noise.py pair_signs / member_offsets).
    """
    return rank_weights[0::2] - rank_weights[1::2]


def es_gradient(
    table: NoiseTable,
    pair_offsets: jax.Array,  # (n_pairs,) int32 — ONE offset per antithetic pair
    rank_weights: jax.Array,  # (2*n_pairs,) float32 per-member weights
    sigma: float,
    population_size: int,
    dim: int,
    chunk: int = 256,
) -> jax.Array:
    """Ascent direction ∇̂ = (1/(n·σ)) Σ_i w_i·s_i·ε_i (NEGATE for optax descent).

    Takes per-PAIR offsets and per-MEMBER weights in the mirrored layout and
    folds the antithetic signs into per-pair weights, so only ``n_pairs``
    noise rows are gathered.  ``pair_offsets``/``rank_weights`` may be the
    local shard only — the caller psums the result over the population axis,
    and ``population_size`` is the GLOBAL population for correct scaling.
    """
    pw = fold_mirrored_weights(rank_weights)
    total = rank_weighted_noise_sum(table, pair_offsets, pw, dim=dim, chunk=chunk)
    return total / (population_size * sigma)
