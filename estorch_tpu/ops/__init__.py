from .noise import (
    DEFAULT_TABLE_SIZE,
    NoiseTable,
    make_noise_table,
    member_noise,
    member_offsets,
    pair_signs,
    sample_pair_offsets,
)
from .params import ParamSpec, count_params, make_param_spec
from .ranks import (
    centered_rank,
    centered_rank_np,
    centered_rank_safe,
    compute_ranks,
    normalized_score,
)
from .gradient import es_gradient, fold_mirrored_weights, rank_weighted_noise_sum

__all__ = [
    "DEFAULT_TABLE_SIZE",
    "NoiseTable",
    "make_noise_table",
    "member_noise",
    "member_offsets",
    "pair_signs",
    "sample_pair_offsets",
    "ParamSpec",
    "count_params",
    "make_param_spec",
    "centered_rank",
    "centered_rank_np",
    "centered_rank_safe",
    "compute_ranks",
    "normalized_score",
    "es_gradient",
    "fold_mirrored_weights",
    "rank_weighted_noise_sum",
]
