"""Whole-program view: cross-module linking for the lockset rules.

The per-file :class:`~estorch_tpu.analysis.context.ModuleContext` is
blind to the bug class that actually corrupts async-folded updates:
data races.  ``serve/router.py`` writes ``rep.health`` from a poll
thread while ``serve/fleet.py``'s monitor thread respawns the replica
behind it — no single file shows both sides.  This module adds the
cross-module layer:

* :func:`build_summary` distills one ModuleContext into a picklable
  :class:`ModuleSummary` — attribute writes with their lexical lockset,
  lock-acquisition edges, blocking calls under locks, thread creations
  and joins, call sites, and concurrency roots (``threading.Thread``
  targets, ``do_*`` HTTP handler methods, callback kwargs,
  ``signal.signal`` handlers).  Summaries are what the process-pool
  workers ship back to the parent, so every field is a frozen
  dataclass of strings and ints.
* :class:`ProjectContext` links summaries into the whole-program view:
  a name-resolved call graph, the set of functions reachable from a
  concurrency root, and per-callee locksets ("is this helper ALWAYS
  called under a lock?").

The lockset model is deliberately lexical (a ``with lock:`` block in
the same function body) plus ONE level of call expansion for lock-order
edges.  That misses locks held across deep call chains — accepted, per
the R02/R03 philosophy: a missed race is recoverable via the
interleaving harness; a false "race" on correct code teaches people to
ignore the analyzer.

Lock identity is spelling-based: ``self.X`` inside ``class C`` is
``C.X``, anything else is its dotted spelling.  An expression counts as
a lock when the module assigns it from ``threading.Lock/RLock/
Condition/Semaphore`` anywhere, or when its last segment ends in
``lock``/``mutex`` (the fleet's ``rep.lock``, ``self._canary_lock``).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .context import ModuleContext, dotted_name
from .findings import Finding

_LOCK_FACTORY_TAILS = {"Lock", "RLock", "Condition", "Semaphore",
                       "BoundedSemaphore"}
_LOCKISH_NAME = re.compile(r"(?i)(lock|mutex)$")
_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# R21 fodder: calls that block indefinitely by default.  The first set
# blocks regardless of arguments (recv takes a size, not a timeout);
# the second only when called with no args and no timeout=/block= kwarg
# (so dict.get(k), t.join(5), proc.wait(timeout=10) stay silent).
_ALWAYS_BLOCKING_TAILS = {"accept", "recv", "recv_into", "recvfrom",
                          "getresponse"}
_ZERO_ARG_BLOCKING_TAILS = {"wait", "join", "communicate", "get"}

# kwarg names whose callable value is a concurrency root: the function
# will run on someone else's thread/timer/request, not the caller's.
# `target=` deliberately ABSENT: threading.Thread targets are rooted by
# the Thread-specific path, and a multiprocessing.Process target runs
# in its own address space — its writes cannot race this process
_CALLBACK_KWARG = re.compile(r"^(callback|on_[a-z0-9_]+"
                             r"|[a-z0-9_]+_(?:cb|callback|hook))$")


@dataclass(frozen=True)
class Site:
    """Where a record was extracted — enough to build a Finding later."""
    line: int
    col: int
    snippet: str


@dataclass(frozen=True)
class AttrWrite:
    kind: str  # "self" | "foreign"
    owner: str  # class name for self-writes, receiver spelling otherwise
    attr: str
    symbol: str  # qualname of the writing function
    locks: tuple[str, ...]  # lexically held locks at the write
    in_init: bool
    site: Site


@dataclass(frozen=True)
class LockEdge:
    outer: str
    inner: str
    symbol: str
    site: Site


@dataclass(frozen=True)
class BlockingCall:
    desc: str  # "conn.recv()" — the spelled call head
    locks: tuple[str, ...]
    receiver_is_held_lock: bool  # `with cond: cond.wait()` — exempt
    symbol: str
    site: Site


@dataclass(frozen=True)
class ThreadCreate:
    daemon: bool
    target: str  # resolved target ident ("C._poll_loop", "fn") or ""
    stored: str  # storage ident, "list:xs" for appends, "" if dropped
    symbol: str
    site: Site


@dataclass(frozen=True)
class CallSite:
    caller: str  # qualname of the calling function
    callee: str  # raw spelling: "self.m", "f", "mod.f"
    cls: str  # enclosing class of the caller ("" at module level)
    locks: tuple[str, ...]
    site: Site


@dataclass(frozen=True)
class ModuleSummary:
    """Everything the project pass needs from one module — picklable."""
    path: str
    module: str  # dotted module name guessed from the path
    aliases: dict[str, str] = field(default_factory=dict)
    attr_writes: tuple[AttrWrite, ...] = ()
    lock_edges: tuple[LockEdge, ...] = ()
    acquires: dict[str, tuple[str, ...]] = field(default_factory=dict)
    blocking_calls: tuple[BlockingCall, ...] = ()
    thread_creates: tuple[ThreadCreate, ...] = ()
    joined: frozenset[str] = frozenset()
    daemon_marked: frozenset[str] = frozenset()
    call_sites: tuple[CallSite, ...] = ()
    roots: frozenset[str] = frozenset()
    lock_defs: dict[str, str] = field(default_factory=dict)
    functions: frozenset[str] = frozenset()
    classes: frozenset[str] = frozenset()


def module_name_of(path: str) -> str:
    name = path.replace("\\", "/")
    if name.endswith(".py"):
        name = name[:-3]
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    return name.strip("/").replace("/", ".")


def _collect_lock_defs(ctx: ModuleContext) -> dict[str, str]:
    """ident -> factory tail for every ``X = threading.Lock()``-shaped
    assignment, regardless of where it appears (class body order must
    not matter: methods above ``__init__`` still see ``self._lock``).
    Scans the call-valued assigns the context pass already collected."""
    lock_defs: dict[str, str] = {}
    for assign, cls in ctx.call_assigns:
        resolved = ctx.resolve(assign.value.func) or ""
        tail = resolved.rsplit(".", 1)[-1]
        if tail in _LOCK_FACTORY_TAILS:
            for t in assign.targets:
                ident = _ident(t, cls)
                if ident:
                    lock_defs[ident] = tail
    return lock_defs


def _ident(expr: ast.AST, cls: str) -> str | None:
    """Canonical spelling of a name/attribute: ``self.X`` in class C
    becomes ``C.X`` so locks and thread targets match across methods."""
    d = dotted_name(expr)
    if d is None:
        return None
    if cls and (d == "self" or d.startswith("self.")):
        rest = d[5:]
        return f"{cls}.{rest}" if rest else cls
    return d


def build_summary(ctx: ModuleContext) -> ModuleSummary:
    lock_defs = _collect_lock_defs(ctx)
    attr_writes: list[AttrWrite] = []
    lock_edges: list[LockEdge] = []
    acquires: dict[str, set[str]] = {}
    blocking: list[BlockingCall] = []
    threads: list[ThreadCreate] = []
    joined: set[str] = set()
    daemon_marked: set[str] = set()
    call_sites: list[CallSite] = []
    roots: set[str] = set()
    classes: set[str] = set()
    handled_calls: set[ast.Call] = set()  # Thread() calls already recorded
    # spawn-helper indirection: `def spawn(name, target): Thread(target=
    # target)` makes every callable argument at spawn() call sites a root
    spawner_syms: set[str] = set()
    call_args: list[tuple[str, tuple[str, ...]]] = []
    # `for target, name in ((self._poll_loop, "poll"), ...)` — idents
    # mentioned in literal loop iterables, per function, so a spawner
    # looping over (callable, name) pairs still roots the callables
    literal_loop_idents: dict[str, set[str]] = {}

    def site(node: ast.AST) -> Site:
        line = getattr(node, "lineno", 0)
        return Site(line, getattr(node, "col_offset", 0), ctx.line_at(line))

    def is_lock(ident: str | None) -> bool:
        if not ident:
            return False
        return ident in lock_defs or bool(
            _LOCKISH_NAME.search(ident.rsplit(".", 1)[-1]))

    def is_thread_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and (ctx.resolve(node.func) or "").endswith(
                    "threading.Thread"))

    def value_is_foreign(value: ast.AST, scope: dict) -> bool:
        """Does this expression yield an object someone else may hold?
        Calls are fresh (constructor/copy results); anything referencing
        ``self`` or a foreign name (param, shared-iterable loop var) is
        foreign."""
        if isinstance(value, ast.Call):
            return False
        for n in ast.walk(value):
            if isinstance(n, ast.Name) and (
                    n.id == "self" or n.id in scope["foreign"]):
                return True
        return False

    def scoped(ident: str, symbol: str) -> str:
        """Bare local names are per-function: `t` in start() and `t` in
        an unrelated helper must not satisfy each other's join."""
        if ident and "." not in ident and not ident.startswith("list:"):
            return f"{symbol}:{ident}"
        return ident

    def record_thread(call: ast.Call, stored: str, symbol: str,
                      cls: str) -> None:
        handled_calls.add(call)
        stored = scoped(stored, symbol)
        daemon = False
        target = ""
        for kw in call.keywords:
            if kw.arg == "daemon":
                daemon = (isinstance(kw.value, ast.Constant)
                          and kw.value.value is True)
            elif kw.arg == "target":
                target = _ident(kw.value, cls) or ""
        if target:
            roots.add(target)
            # target is a bare name with no matching def: the enclosing
            # function is a spawn helper and ITS callers supply the real
            # target — their callable arguments become roots (post-pass)
            if "." not in target and target not in ctx.defs_by_name:
                spawner_syms.add(symbol)
        threads.append(ThreadCreate(daemon=daemon, target=target,
                                    stored=stored, symbol=symbol,
                                    site=site(call)))

    seen_calls: set[ast.Call] = set()  # one record per Call node

    def handle_call(call: ast.Call, symbol: str, cls: str,
                    locks: tuple[str, ...]) -> None:
        if call in seen_calls:
            return
        seen_calls.add(call)
        func = call.func
        resolved = ctx.resolve(func) or ""
        if (isinstance(func, ast.Attribute) and func.attr == "append"
                and call.args and is_thread_call(call.args[0])
                and call.args[0] not in handled_calls):
            recv = _ident(func.value, cls)
            record_thread(call.args[0], f"list:{recv}" if recv else "",
                          symbol, cls)
        if call not in handled_calls and is_thread_call(call):
            record_thread(call, "", symbol, cls)
        # callback kwargs / signal handlers are concurrency roots
        for kw in call.keywords:
            if (kw.arg and _CALLBACK_KWARG.match(kw.arg)
                    and isinstance(kw.value, (ast.Name, ast.Attribute))):
                ident = _ident(kw.value, cls)
                if ident:
                    roots.add(ident)
        if resolved == "signal.signal" and len(call.args) >= 2:
            ident = _ident(call.args[1], cls)
            if ident:
                roots.add(ident)
        if isinstance(func, ast.Attribute):
            tail = func.attr
            recv = _ident(func.value, cls)
            # thread joins: X.join() / X.join(t) — sep.join(parts) has a
            # non-timeout positional and is excluded by the arg shapes
            if tail == "join" and recv and len(call.args) <= 1:
                joined.add(scoped(recv, symbol))
            has_timeout = any(kw.arg in ("timeout", "block")
                              for kw in call.keywords)
            blocking_shape = (
                tail in _ALWAYS_BLOCKING_TAILS and not has_timeout
            ) or (
                tail in _ZERO_ARG_BLOCKING_TAILS
                and not call.args and not has_timeout
            ) or resolved == "time.sleep" or (
                resolved.endswith("urlopen") and not has_timeout
            )
            if blocking_shape and locks:
                blocking.append(BlockingCall(
                    desc=f"{dotted_name(func) or tail}()", locks=locks,
                    receiver_is_held_lock=recv in locks,
                    symbol=symbol, site=site(call)))
        spelled = dotted_name(func)
        if spelled:
            call_sites.append(CallSite(caller=symbol, callee=spelled,
                                       cls=cls, locks=locks,
                                       site=site(call)))
            arg_idents = tuple(
                i for i in (
                    _ident(a, cls) for a in call.args
                    if isinstance(a, (ast.Name, ast.Attribute)))
                if i)
            if arg_idents:
                call_args.append((spelled, arg_idents))

    def record_attr_write(target: ast.Attribute, symbol: str, cls: str,
                          locks: tuple[str, ...], scope: dict,
                          at: ast.AST) -> None:
        base = target.value
        base_dotted = dotted_name(base) or ""
        if target.attr == "daemon":
            recv = _ident(base, cls)
            if recv:
                daemon_marked.add(scoped(recv, symbol))
            return
        if base_dotted == "self" or base_dotted.startswith("self."):
            attr_writes.append(AttrWrite(
                kind="self", owner=cls or "<module>", attr=target.attr,
                symbol=symbol, locks=locks,
                in_init=symbol.endswith("__init__"), site=site(at)))
        elif value_is_foreign(base, scope):
            attr_writes.append(AttrWrite(
                kind="foreign", owner=base_dotted or "<expr>",
                attr=target.attr, symbol=symbol, locks=locks,
                in_init=False, site=site(at)))

    def walk(node: ast.AST, symbol: str, cls: str,
             locks: tuple[str, ...], scope: dict) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                classes.add(child.name)
                # HTTP handler classes: every do_* method runs on the
                # server's request thread — each is a concurrency root.
                # ctx.qualnames carries the full nesting prefix, so
                # handler classes built inside factory closures root as
                # "_make_handler.RouterHandler.do_GET"
                if any((dotted_name(b) or "").rsplit(".", 1)[-1]
                       .endswith("HTTPRequestHandler")
                       for b in child.bases):
                    for item in child.body:
                        if (isinstance(item, _FN_NODES)
                                and item.name.startswith("do_")):
                            roots.add(ctx.qualnames.get(item, item.name))
                walk(child, symbol, child.name, locks, scope)
            elif isinstance(child, _FN_NODES):
                # a nested def does not hold the caller's locks at
                # runtime, and gets its own fresh/foreign tracking
                params = {a.arg for a in child.args.args
                          + child.args.posonlyargs + child.args.kwonlyargs
                          if a.arg not in ("self", "cls")}
                inner = {"foreign": set(params), "fresh": set()}
                walk(child, ctx.qualnames.get(child, child.name),
                     cls, (), inner)
            elif isinstance(child, ast.With):
                new_locks = locks
                for item in child.items:
                    ident = _ident(item.context_expr, cls)
                    if is_lock(ident):
                        for outer in new_locks:
                            if outer != ident:
                                lock_edges.append(LockEdge(
                                    outer=outer, inner=ident,
                                    symbol=symbol, site=site(child)))
                        acquires.setdefault(symbol, set()).add(ident)
                        new_locks = new_locks + (ident,)
                    for n in ast.walk(item.context_expr):
                        if isinstance(n, ast.Call):
                            handle_call(n, symbol, cls, locks)
                walk(child, symbol, cls, new_locks, scope)
            elif isinstance(child, (ast.Assign, ast.AugAssign,
                                    ast.AnnAssign)):
                targets = (child.targets if isinstance(child, ast.Assign)
                           else [child.target])
                value = child.value
                if value is not None and is_thread_call(value):
                    stored = ""
                    if targets and not isinstance(child, ast.AugAssign):
                        stored = _ident(targets[0], cls) or ""
                    record_thread(value, stored, symbol, cls)
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        record_attr_write(t, symbol, cls, locks, scope,
                                          child)
                    elif isinstance(t, ast.Name) and value is not None:
                        if value_is_foreign(value, scope):
                            scope["foreign"].add(t.id)
                        else:
                            scope["foreign"].discard(t.id)
                            scope["fresh"].add(t.id)
                    elif isinstance(t, ast.Tuple):
                        for el in t.elts:
                            if isinstance(el, ast.Attribute):
                                record_attr_write(el, symbol, cls, locks,
                                                  scope, child)
                if value is not None:
                    for n in ast.walk(value):
                        if isinstance(n, ast.Call):
                            handle_call(n, symbol, cls, locks)
            elif isinstance(child, (ast.For, ast.AsyncFor)):
                if isinstance(child.iter, (ast.Tuple, ast.List)):
                    for n in ast.walk(child.iter):
                        if isinstance(n, (ast.Name, ast.Attribute)):
                            el = _ident(n, cls)
                            if el:
                                literal_loop_idents.setdefault(
                                    symbol, set()).add(el)
                if isinstance(child.target, ast.Name):
                    if value_is_foreign(child.iter, scope):
                        scope["foreign"].add(child.target.id)
                        it = _ident(child.iter, cls)
                        if it:
                            scope.setdefault("loop_src", {})[
                                child.target.id] = f"list:{it}"
                    else:
                        scope["fresh"].add(child.target.id)
                    # `for t in xs: t.join()` joins every thread stored
                    # via xs.append(...) — match the "list:xs" ident that
                    # appended threads are stored under
                    it = _ident(child.iter, cls)
                    if it:
                        tvar = child.target.id
                        for n in ast.walk(child):
                            if (isinstance(n, ast.Call)
                                    and isinstance(n.func, ast.Attribute)
                                    and n.func.attr == "join"
                                    and isinstance(n.func.value, ast.Name)
                                    and n.func.value.id == tvar
                                    and len(n.args) <= 1):
                                joined.add(f"list:{it}")
                                break
                for n in ast.walk(child.iter):
                    if isinstance(n, ast.Call):
                        handle_call(n, symbol, cls, locks)
                walk(child, symbol, cls, locks, scope)
            elif isinstance(child, ast.Call):
                handle_call(child, symbol, cls, locks)
                walk(child, symbol, cls, locks, scope)
            else:
                walk(child, symbol, cls, locks, scope)

    module_scope = {"foreign": set(), "fresh": set()}
    walk(ctx.tree, "<module>", "", (), module_scope)

    # spawn-helper call sites: their callable args are the real targets
    spawner_tails = {sym.rsplit(".", 1)[-1] for sym in spawner_syms}
    for spelled, arg_idents in call_args:
        if spelled.rsplit(".", 1)[-1] in spawner_tails:
            roots.update(arg_idents)
    for sym in spawner_syms:
        roots.update(literal_loop_idents.get(sym, ()))

    return ModuleSummary(
        path=ctx.path,
        module=module_name_of(ctx.path),
        aliases=dict(ctx.aliases),
        attr_writes=tuple(attr_writes),
        lock_edges=tuple(lock_edges),
        acquires={k: tuple(sorted(v)) for k, v in acquires.items()},
        blocking_calls=tuple(blocking),
        thread_creates=tuple(threads),
        joined=frozenset(joined),
        daemon_marked=frozenset(daemon_marked),
        call_sites=tuple(call_sites),
        roots=frozenset(roots),
        lock_defs=lock_defs,
        functions=frozenset(ctx.qualnames.values()),
        classes=frozenset(classes),
    )


class ProjectContext:
    """The linked whole-program view the R18–R22 checks run against."""

    def __init__(self, summaries: list[ModuleSummary]):
        self.summaries = sorted(summaries, key=lambda s: s.path)
        self.by_module = {s.module: s for s in self.summaries}
        self._resolved_sites: list[tuple[ModuleSummary, CallSite,
                                         tuple[str, str] | None]] = []
        for s in self.summaries:
            for cs in s.call_sites:
                self._resolved_sites.append(
                    (s, cs, self._resolve_callee(s, cs)))
        # callee -> locksets at every known call site (for "is this
        # helper always called under a lock?")
        self.callee_locksets: dict[tuple[str, str],
                                   list[tuple[str, ...]]] = {}
        for _, cs, node in self._resolved_sites:
            if node is not None:
                self.callee_locksets.setdefault(node, []).append(cs.locks)
        self.reachable = self._compute_reachable()

    # -- name resolution ----------------------------------------------

    def _resolve_callee(self, s: ModuleSummary,
                        cs: CallSite) -> tuple[str, str] | None:
        c = cs.callee
        if c.startswith("self."):
            meth = c[5:]
            if cs.cls and f"{cs.cls}.{meth}" in s.functions:
                return (s.module, f"{cs.cls}.{meth}")
            return None
        head, _, rest = c.partition(".")
        canon = s.aliases.get(head, head)
        full = canon + ("." + rest if rest else "")
        if "." not in full:
            if full in s.functions:
                return (s.module, full)
            return None
        mod_part, _, fn = full.rpartition(".")
        mod_part = mod_part.lstrip(".")
        if not fn:
            return None
        for m, summ in self.by_module.items():
            if fn not in summ.functions:
                continue
            if (m == mod_part or m.endswith("." + mod_part)
                    or (mod_part and mod_part.endswith(m))):
                return (m, fn)
        return None

    def _root_nodes(self) -> set[tuple[str, str]]:
        nodes: set[tuple[str, str]] = set()
        for s in self.summaries:
            for r in s.roots:
                # same module first — exact qualname or nested-def tail
                # ("run" matches "Router._hedge.run")
                local = [q for q in s.functions
                         if q == r or q.endswith("." + r)]
                if local:
                    nodes.update((s.module, q) for q in local)
                    continue
                # dotted spelling of a function in another module
                mod_part, _, fn = r.rpartition(".")
                for m, summ in self.by_module.items():
                    if fn in summ.functions and (
                            m == mod_part or m.endswith("." + mod_part)):
                        nodes.add((m, fn))
        return nodes

    def _compute_reachable(self) -> set[tuple[str, str]]:
        edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for s, cs, node in self._resolved_sites:
            if node is not None:
                edges.setdefault((s.module, cs.caller), set()).add(node)
        seen = set(self._root_nodes())
        stack = list(seen)
        while stack:
            cur = stack.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return seen

    def is_reachable(self, module: str, symbol: str) -> bool:
        """Reachable from a concurrency root — including lexically
        nested defs, which run inside their reachable parent."""
        parts = symbol.split(".")
        for i in range(len(parts), 0, -1):
            if (module, ".".join(parts[:i])) in self.reachable:
                return True
        return False

    def always_called_locked(self, module: str, symbol: str) -> bool:
        sites = self.callee_locksets.get((module, symbol))
        return bool(sites) and all(locks for locks in sites)


def project_finding(rule_, summary: ModuleSummary, site: Site,
                    message: str, hint: str, symbol: str,
                    severity: str | None = None) -> Finding:
    return Finding(
        rule=rule_.id, file=summary.path, line=site.line, col=site.col,
        severity=severity or rule_.severity, message=message, hint=hint,
        symbol=symbol, snippet=site.snippet)
