"""Trace-time rules: R02 host-sync-in-hot-path, R03 impure-jit,
R04 missing-donation.

All three key off the traced-function set computed in
:mod:`~estorch_tpu.analysis.context`: code the module can prove runs
under ``jit``/``vmap``/``pmap``/``shard_map``/``lax.scan``.  Host code
is never flagged by R02/R03 — ``float(x)`` in a logging helper is fine;
the same call inside a jitted body either retraces per value or drags a
device sync into the hot path, which is exactly the throughput leak the
hyperscale-ES setting cannot afford.
"""

from __future__ import annotations

import ast

from .context import ModuleContext
from .engine import get_rule, make_finding, rule, scope_nodes

_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# ---------------------------------------------------------------------
# R02 host-sync-in-hot-path
# ---------------------------------------------------------------------

_SYNC_METHODS = {"item", "tolist", "block_until_ready", "numpy"}
_SYNC_CALLS = {  # resolved dotted names that materialize on host
    "numpy.array", "numpy.asarray", "numpy.asanyarray", "numpy.copy",
    "jax.device_get",
}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}  # trace-time constants


def _is_static_expr(node: ast.AST) -> bool:
    """``x.shape[0]``-style expressions are Python ints at trace time —
    casting them is shape arithmetic, not a host sync."""
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.Call):  # len(x.shape), min(x.shape, ...)
        res = node.func
        return (isinstance(res, ast.Name)
                and res.id in ("len", "min", "max", "prod")
                and all(_is_static_expr(a) or isinstance(a, ast.Constant)
                        for a in node.args))
    return isinstance(node, ast.Constant)


def _touches_traced_value(node: ast.AST) -> bool:
    """Whether a cast argument references any plain name other than
    ``self``/``cls`` — ``float(self.config.clip)`` reads static Python
    config and is fine; ``float(loss)`` concretizes traced data."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id not in ("self", "cls"):
            return True
    return False


def _traced_fns(ctx: ModuleContext):
    for fn, qualname in ctx.qualnames.items():
        if ctx.is_traced(fn):
            yield fn, qualname


@rule("R02", "host-sync-in-hot-path", "error",
      "host synchronization inside jit/vmap/scan-traced code")
def check_host_sync(ctx: ModuleContext):
    r = get_rule("R02")
    out = []
    for fn, qualname in _traced_fns(ctx):
        for node in scope_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _SYNC_METHODS and not node.args):
                out.append(make_finding(
                    ctx, r, node,
                    f"`.{func.attr}()` forces a host sync inside traced "
                    "code",
                    "keep values on device; move host reads outside the "
                    "jitted region",
                    qualname))
                continue
            resolved = ctx.resolve(func)
            if resolved in _SYNC_CALLS:
                out.append(make_finding(
                    ctx, r, node,
                    f"`{resolved}` materializes a device value on host "
                    "inside traced code",
                    "use jnp inside traced code; convert to numpy only "
                    "after the jitted call returns",
                    qualname))
                continue
            if (resolved in _CAST_BUILTINS and len(node.args) == 1
                    and not isinstance(node.args[0], ast.Constant)
                    and not _is_static_expr(node.args[0])
                    and _touches_traced_value(node.args[0])):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{resolved}(...)` on a traced value concretizes it "
                    "(host sync or ConcretizationTypeError)",
                    "keep it as a jax scalar, or hoist the cast out of "
                    "the traced function",
                    qualname, severity="warning"))
    return out


# ---------------------------------------------------------------------
# R03 impure-jit
# ---------------------------------------------------------------------

_IMPURE_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow", "builtins.open",
    "open", "input",
}


def _is_impure_call(resolved: str | None) -> str | None:
    if resolved is None:
        return None
    if resolved in _IMPURE_CALLS:
        return resolved
    if resolved == "print":
        return "print"
    head = resolved.rsplit(".", 1)[0]
    if head in ("numpy.random", "random"):
        return resolved
    return None


def _local_bindings(fn: ast.AST) -> set[str]:
    args = fn.args
    bound = {a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)}
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    for node in scope_nodes(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
        elif isinstance(node, _FN_NODES):
            bound.add(node.name)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    return bound


@rule("R03", "impure-jit", "error",
      "side effect or hidden host state inside jit-traced code")
def check_impure_jit(ctx: ModuleContext):
    r = get_rule("R03")
    out = []
    for fn, qualname in _traced_fns(ctx):
        local = _local_bindings(fn)
        for node in scope_nodes(fn):
            if isinstance(node, ast.Call):
                impure = _is_impure_call(ctx.resolve(node.func))
                if impure is not None:
                    what = ("runs once at trace time, not per step"
                            if impure == "print"
                            else "is host state the trace bakes in")
                    out.append(make_finding(
                        ctx, r, node,
                        f"`{impure}` under jit {what}",
                        "use jax.debug.print / jax.random inside traced "
                        "code; do host I/O outside the jitted region",
                        qualname))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                    f" {', '.join(node.names)}` mutated under jit only "
                    "mutates at trace time",
                    "thread the value through the function's inputs and "
                    "outputs instead",
                    qualname))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    base = tgt
                    while isinstance(base, (ast.Attribute, ast.Subscript)):
                        base = base.value
                    if (tgt is not base and isinstance(base, ast.Name)
                            and base.id not in local
                            and base.id not in ctx.aliases):
                        out.append(make_finding(
                            ctx, r, node,
                            f"mutation of closed-over `{base.id}` under "
                            "jit happens at trace time only",
                            "return the updated value from the traced "
                            "function instead of mutating the closure",
                            qualname))
    return out


# ---------------------------------------------------------------------
# R04 missing-donation
# ---------------------------------------------------------------------

_STATEFUL_PARAMS = {
    "params", "state", "opt_state", "optimizer_state", "theta", "weights",
    "params_flat", "es_state",
}
_NEW_PREFIXES = ("new_", "next_", "updated_")


def _donates(kwargs: list[ast.keyword]) -> bool:
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in kwargs)


def _jit_head(ctx: ModuleContext, node: ast.AST) -> bool:
    resolved = ctx.resolve(node)
    return resolved is not None and resolved.rsplit(".", 1)[-1] == "jit"


def _jitted_without_donation(ctx: ModuleContext):
    """Yield (def_node, report_node) for jit applications lacking
    donate_argnums: decorator form and ``jax.jit(fname)`` call form."""
    for fn in ctx.qualnames:
        for dec in getattr(fn, "decorator_list", []):
            if isinstance(dec, ast.Call):
                head = ctx.resolve(dec.func)
                is_partial = (head is not None
                              and head.rsplit(".", 1)[-1] == "partial")
                if is_partial and dec.args and _jit_head(ctx, dec.args[0]):
                    if not _donates(dec.keywords):
                        yield fn, fn
                elif _jit_head(ctx, dec.func) and not _donates(dec.keywords):
                    yield fn, fn
            elif _jit_head(ctx, dec):
                yield fn, fn
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call) and _jit_head(ctx, node.func)
                and not _donates(node.keywords)
                and node.args and isinstance(node.args[0], ast.Name)):
            for fn in ctx.defs_by_name.get(node.args[0].id, []):
                yield fn, node


def _updates_stateful(fn: ast.AST) -> str | None:
    """Param name when fn takes AND returns a params/opt-state pytree."""
    args = fn.args
    params = {a.arg for a in (args.posonlyargs + args.args
                              + args.kwonlyargs)}
    stateful = params & _STATEFUL_PARAMS
    if not stateful:
        return None
    returned: set[str] = set()
    for node in scope_nodes(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            vals = (node.value.elts
                    if isinstance(node.value, (ast.Tuple, ast.List))
                    else [node.value])
            for v in vals:
                if isinstance(v, ast.Name):
                    returned.add(v.id)
    for p in stateful:
        if p in returned:
            return p
        for pre in _NEW_PREFIXES:
            if f"{pre}{p}" in returned:
                return p
        if {f"{p}_new", f"{p}_next"} & returned:
            return p
    return None


@rule("R04", "missing-donation", "info",
      "jitted update takes and returns a state pytree without donation")
def check_missing_donation(ctx: ModuleContext):
    r = get_rule("R04")
    out = []
    seen: set[tuple[ast.AST, int]] = set()
    for fn, report in _jitted_without_donation(ctx):
        param = _updates_stateful(fn)
        if param is None:
            continue
        key = (fn, getattr(report, "lineno", 0))
        if key in seen:
            continue
        seen.add(key)
        out.append(make_finding(
            ctx, r, report,
            f"jitted `{ctx.qualnames[fn]}` takes and returns `{param}` "
            "without donate_argnums — the old buffer stays live through "
            "the update",
            f"pass donate_argnums for `{param}` (safe when the caller "
            "drops the old value, as update loops do)",
            ctx.qualnames[fn]))
    return out
