"""Trace-time rules: R02 host-sync-in-hot-path, R03 impure-jit,
R04 missing-donation.

All three key off the traced-function set computed in
:mod:`~estorch_tpu.analysis.context`: code the module can prove runs
under ``jit``/``vmap``/``pmap``/``shard_map``/``lax.scan``.  Host code
is never flagged by R02/R03 — ``float(x)`` in a logging helper is fine;
the same call inside a jitted body either retraces per value or drags a
device sync into the hot path, which is exactly the throughput leak the
hyperscale-ES setting cannot afford.
"""

from __future__ import annotations

import ast

from .context import ModuleContext
from .engine import (enclosing_defs, get_rule, iter_scopes, make_finding, rule, scope_nodes, walk_tree)

_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# ---------------------------------------------------------------------
# R02 host-sync-in-hot-path
# ---------------------------------------------------------------------

_SYNC_METHODS = {"item", "tolist", "block_until_ready", "numpy"}
_SYNC_CALLS = {  # resolved dotted names that materialize on host
    "numpy.array", "numpy.asarray", "numpy.asanyarray", "numpy.copy",
    "jax.device_get",
}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}  # trace-time constants


def _is_static_expr(node: ast.AST) -> bool:
    """``x.shape[0]``-style expressions are Python ints at trace time —
    casting them is shape arithmetic, not a host sync."""
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.Call):  # len(x.shape), min(x.shape, ...)
        res = node.func
        return (isinstance(res, ast.Name)
                and res.id in ("len", "min", "max", "prod")
                and all(_is_static_expr(a) or isinstance(a, ast.Constant)
                        for a in node.args))
    return isinstance(node, ast.Constant)


def _touches_traced_value(node: ast.AST) -> bool:
    """Whether a cast argument references any plain name other than
    ``self``/``cls`` — ``float(self.config.clip)`` reads static Python
    config and is fine; ``float(loss)`` concretizes traced data."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id not in ("self", "cls"):
            return True
    return False


def _traced_fns(ctx: ModuleContext):
    for fn, qualname in ctx.qualnames.items():
        if ctx.is_traced(fn):
            yield fn, qualname


@rule("R02", "host-sync-in-hot-path", "error",
      "host synchronization inside jit/vmap/scan-traced code")
def check_host_sync(ctx: ModuleContext):
    r = get_rule("R02")
    out = []
    for fn, qualname in _traced_fns(ctx):
        for node in scope_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _SYNC_METHODS and not node.args):
                out.append(make_finding(
                    ctx, r, node,
                    f"`.{func.attr}()` forces a host sync inside traced "
                    "code",
                    "keep values on device; move host reads outside the "
                    "jitted region",
                    qualname))
                continue
            resolved = ctx.resolve(func)
            if resolved in _SYNC_CALLS:
                out.append(make_finding(
                    ctx, r, node,
                    f"`{resolved}` materializes a device value on host "
                    "inside traced code",
                    "use jnp inside traced code; convert to numpy only "
                    "after the jitted call returns",
                    qualname))
                continue
            if (resolved in _CAST_BUILTINS and len(node.args) == 1
                    and not isinstance(node.args[0], ast.Constant)
                    and not _is_static_expr(node.args[0])
                    and _touches_traced_value(node.args[0])):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{resolved}(...)` on a traced value concretizes it "
                    "(host sync or ConcretizationTypeError)",
                    "keep it as a jax scalar, or hoist the cast out of "
                    "the traced function",
                    qualname, severity="warning"))
    return out


# ---------------------------------------------------------------------
# R03 impure-jit
# ---------------------------------------------------------------------

_IMPURE_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow", "builtins.open",
    "open", "input",
}


def _is_impure_call(resolved: str | None) -> str | None:
    if resolved is None:
        return None
    if resolved in _IMPURE_CALLS:
        return resolved
    if resolved == "print":
        return "print"
    head = resolved.rsplit(".", 1)[0]
    if head in ("numpy.random", "random"):
        return resolved
    return None


def _local_bindings(fn: ast.AST) -> set[str]:
    args = fn.args
    bound = {a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)}
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    for node in scope_nodes(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
        elif isinstance(node, _FN_NODES):
            bound.add(node.name)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    return bound


@rule("R03", "impure-jit", "error",
      "side effect or hidden host state inside jit-traced code")
def check_impure_jit(ctx: ModuleContext):
    r = get_rule("R03")
    out = []
    for fn, qualname in _traced_fns(ctx):
        local = _local_bindings(fn)
        for node in scope_nodes(fn):
            if isinstance(node, ast.Call):
                impure = _is_impure_call(ctx.resolve(node.func))
                if impure is not None:
                    what = ("runs once at trace time, not per step"
                            if impure == "print"
                            else "is host state the trace bakes in")
                    out.append(make_finding(
                        ctx, r, node,
                        f"`{impure}` under jit {what}",
                        "use jax.debug.print / jax.random inside traced "
                        "code; do host I/O outside the jitted region",
                        qualname))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                    f" {', '.join(node.names)}` mutated under jit only "
                    "mutates at trace time",
                    "thread the value through the function's inputs and "
                    "outputs instead",
                    qualname))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    base = tgt
                    while isinstance(base, (ast.Attribute, ast.Subscript)):
                        base = base.value
                    if (tgt is not base and isinstance(base, ast.Name)
                            and base.id not in local
                            and base.id not in ctx.aliases):
                        out.append(make_finding(
                            ctx, r, node,
                            f"mutation of closed-over `{base.id}` under "
                            "jit happens at trace time only",
                            "return the updated value from the traced "
                            "function instead of mutating the closure",
                            qualname))
    return out


# ---------------------------------------------------------------------
# R04 missing-donation
# ---------------------------------------------------------------------

_STATEFUL_PARAMS = {
    "params", "state", "opt_state", "optimizer_state", "theta", "weights",
    "params_flat", "es_state",
}
_NEW_PREFIXES = ("new_", "next_", "updated_")


def _donates(kwargs: list[ast.keyword]) -> bool:
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in kwargs)


def _jit_head(ctx: ModuleContext, node: ast.AST) -> bool:
    resolved = ctx.resolve(node)
    return resolved is not None and resolved.rsplit(".", 1)[-1] == "jit"


def _jitted_without_donation(ctx: ModuleContext):
    """Yield (def_node, report_node) for jit applications lacking
    donate_argnums: decorator form and ``jax.jit(fname)`` call form."""
    for fn in ctx.qualnames:
        for dec in getattr(fn, "decorator_list", []):
            if isinstance(dec, ast.Call):
                head = ctx.resolve(dec.func)
                is_partial = (head is not None
                              and head.rsplit(".", 1)[-1] == "partial")
                if is_partial and dec.args and _jit_head(ctx, dec.args[0]):
                    if not _donates(dec.keywords):
                        yield fn, fn
                elif _jit_head(ctx, dec.func) and not _donates(dec.keywords):
                    yield fn, fn
            elif _jit_head(ctx, dec):
                yield fn, fn
    for node in walk_tree(ctx.tree):
        if (isinstance(node, ast.Call) and _jit_head(ctx, node.func)
                and not _donates(node.keywords)
                and node.args and isinstance(node.args[0], ast.Name)):
            for fn in ctx.defs_by_name.get(node.args[0].id, []):
                yield fn, node


def _updates_stateful(fn: ast.AST) -> str | None:
    """Param name when fn takes AND returns a params/opt-state pytree."""
    args = fn.args
    params = {a.arg for a in (args.posonlyargs + args.args
                              + args.kwonlyargs)}
    stateful = params & _STATEFUL_PARAMS
    if not stateful:
        return None
    returned: set[str] = set()
    for node in scope_nodes(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            vals = (node.value.elts
                    if isinstance(node.value, (ast.Tuple, ast.List))
                    else [node.value])
            for v in vals:
                if isinstance(v, ast.Name):
                    returned.add(v.id)
    for p in stateful:
        if p in returned:
            return p
        for pre in _NEW_PREFIXES:
            if f"{pre}{p}" in returned:
                return p
        if {f"{p}_new", f"{p}_next"} & returned:
            return p
    return None


@rule("R04", "missing-donation", "info",
      "jitted update takes and returns a state pytree without donation")
def check_missing_donation(ctx: ModuleContext):
    r = get_rule("R04")
    out = []
    seen: set[tuple[ast.AST, int]] = set()
    for fn, report in _jitted_without_donation(ctx):
        param = _updates_stateful(fn)
        if param is None:
            continue
        key = (fn, getattr(report, "lineno", 0))
        if key in seen:
            continue
        seen.add(key)
        out.append(make_finding(
            ctx, r, report,
            f"jitted `{ctx.qualnames[fn]}` takes and returns `{param}` "
            "without donate_argnums — the old buffer stays live through "
            "the update",
            f"pass donate_argnums for `{param}` (safe when the caller "
            "drops the old value, as update loops do)",
            ctx.qualnames[fn]))
    return out


# ---------------------------------------------------------------------
# R10 unsharded-capture
# ---------------------------------------------------------------------
#
# A jit application that spells out in_shardings/out_shardings is a
# SHARDED program: its operands are placed per an explicit mesh layout.
# A host-materialized array (np.random output, a large np constant, a
# file load) closed over by such a program bypasses that placement — it
# lowers as a baked-in constant, REPLICATED on every device (at
# hyperscale sizes that is the exact per-device copy the sharding
# exists to avoid), bloats the serialized executable past the
# persistent-cache ceiling, and — for np.random — freezes untracked
# host RNG into the trace.  Pass it as an operand (device_put with a
# NamedSharding) or generate it in-program (ops/noise.py).
#
# Conservative by the R02/R03 philosophy: only provable host
# materializations are flagged (np.random.*, np.load/loadtxt/fromfile,
# and sized constructors whose LITERAL element count is large); jnp
# arrays, small constants, and anything reaching the program as an
# argument stay silent.

_HOST_LOAD_CALLS = {"numpy.load", "numpy.loadtxt", "numpy.fromfile"}
_HOST_SIZED_CTORS = {"numpy.zeros", "numpy.ones", "numpy.full",
                     "numpy.empty", "numpy.arange"}
_LARGE_ELEMENTS = 1 << 16  # 64k floats = 256 KiB — replicate-worthy


def _const_int(node: ast.AST):
    """Best-effort literal integer evaluation (Constant / unary / binop
    arithmetic incl. shifts — the `1 << 20` idiom); None when unknown."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        lv, rv = _const_int(node.left), _const_int(node.right)
        if lv is None or rv is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return lv + rv
            if isinstance(node.op, ast.Sub):
                return lv - rv
            if isinstance(node.op, ast.Mult):
                return lv * rv
            if isinstance(node.op, ast.FloorDiv):
                return lv // rv
            if isinstance(node.op, ast.Pow):
                return lv ** rv
            if isinstance(node.op, ast.LShift):
                return lv << rv
        except Exception:  # noqa: BLE001 — overflow/zero-div in user code
            return None
    return None


def _literal_elements(call: ast.Call):
    """Element count of a sized-constructor call when its shape argument
    is fully literal; None otherwise (stays silent — R02/R03 philosophy)."""
    if not call.args:
        return None
    shape = call.args[0]
    if isinstance(shape, (ast.Tuple, ast.List)):
        total = 1
        for el in shape.elts:
            v = _const_int(el)
            if v is None:
                return None
            total *= v
        return total
    return _const_int(shape)


def _host_array_bindings(ctx: ModuleContext) -> dict[str, str]:
    """{name: why} for MODULE-LEVEL names bound to provably
    host-materialized arrays.

    Module-level only, by the conservative contract: a bare name is not a
    scope — recording function-local assigns would flag any jitted
    function whose parameter or enclosing-scope operand merely SHARES a
    name with some unrelated local elsewhere in the file (e.g. a helper's
    own `table = np.random...` poisoning a legitimate `table` operand
    parameter in another function).  Module-level constants are the
    capture pattern the rule exists for, and their names are unambiguous."""
    from .engine import enclosing_defs

    enclosing = enclosing_defs(ctx.tree)
    out: dict[str, str] = {}
    for node in walk_tree(ctx.tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        if enclosing.get(node) is not None:
            continue  # function-local binding: not a module constant
        resolved = ctx.resolve(node.value.func)
        if resolved is None:
            continue
        why = None
        if resolved.startswith("numpy.random."):
            why = f"`{resolved}` output (host RNG, untracked by jax)"
        elif resolved in _HOST_LOAD_CALLS:
            why = f"`{resolved}` result"
        elif resolved in _HOST_SIZED_CTORS:
            n = _literal_elements(node.value)
            if n is not None and n >= _LARGE_ELEMENTS:
                why = f"`{resolved}` constant of {n:,} elements"
        if why is None:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = why
    return out


def _has_sharding_kwargs(keywords: list[ast.keyword]) -> bool:
    return any(kw.arg in ("in_shardings", "out_shardings")
               for kw in keywords)


def _sharded_jit_targets(ctx: ModuleContext):
    """Yield (fn_or_lambda, report_node) for every function a
    sharding-spelling jit application provably traces: ``jax.jit(f,
    in_shardings=...)`` with a Name/attribute/lambda argument, plus the
    ``@partial(jax.jit, out_shardings=...)`` decorator form."""
    for node in walk_tree(ctx.tree):
        if (isinstance(node, ast.Call) and _jit_head(ctx, node.func)
                and _has_sharding_kwargs(node.keywords) and node.args):
            tgt = node.args[0]
            if isinstance(tgt, ast.Lambda):
                yield tgt, node
                continue
            name = (tgt.id if isinstance(tgt, ast.Name)
                    else tgt.attr if isinstance(tgt, ast.Attribute)
                    else None)
            if name:
                for fn in ctx.defs_by_name.get(name, []):
                    yield fn, node
    for fn in ctx.qualnames:
        for dec in getattr(fn, "decorator_list", []):
            if not isinstance(dec, ast.Call):
                continue
            head = ctx.resolve(dec.func)
            is_partial = (head is not None
                          and head.rsplit(".", 1)[-1] == "partial")
            if (is_partial and dec.args and _jit_head(ctx, dec.args[0])
                    and _has_sharding_kwargs(dec.keywords)):
                yield fn, fn
            elif _jit_head(ctx, dec.func) and _has_sharding_kwargs(dec.keywords):
                yield fn, fn


def _bound_names(fn: ast.AST) -> set[str]:
    """Names the function body binds (params + stores anywhere inside,
    nested defs included — a capture must come from OUTSIDE)."""
    args = fn.args
    bound = {a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)}
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
        elif isinstance(node, _FN_NODES):
            bound.add(node.name)
            for a in (node.args.posonlyargs + node.args.args
                      + node.args.kwonlyargs):
                bound.add(a.arg)
        elif isinstance(node, ast.Lambda):
            for a in (node.args.posonlyargs + node.args.args
                      + node.args.kwonlyargs):
                bound.add(a.arg)
    return bound


@rule("R10", "unsharded-capture", "warning",
      "host-materialized array closed over by a sharded jitted program")
def check_unsharded_capture(ctx: ModuleContext):
    r = get_rule("R10")
    host = _host_array_bindings(ctx)
    if not host:
        return []
    out = []
    seen: set[tuple[int, str]] = set()
    for fn, report in _sharded_jit_targets(ctx):
        bound = _bound_names(fn)
        for node in ast.walk(fn):
            if (not isinstance(node, ast.Name)
                    or not isinstance(node.ctx, ast.Load)
                    or node.id not in host or node.id in bound):
                continue
            key = (getattr(fn, "lineno", 0), node.id)
            if key in seen:
                continue
            seen.add(key)
            qualname = ctx.qualnames.get(fn, "<lambda>")
            out.append(make_finding(
                ctx, r, node,
                f"`{node.id}` ({host[node.id]}) is closed over by a "
                "sharded jitted program — it lowers as a constant, "
                "replicated on every device despite the explicit "
                "shardings",
                "pass it as an operand (jax.device_put with a "
                "NamedSharding, listed in in_shardings) or generate it "
                "in-program (jax.random)",
                qualname))
    return out


# ---------------------------------------------------------------------
# R16 scenario-constant-closure
# ---------------------------------------------------------------------
#
# The scenario suite's one-program contract (estorch_tpu/scenarios,
# docs/scenarios.md): per-variant physics constants must enter the
# jitted rollout as TRACED OPERANDS (riding the env state / function
# arguments), never as Python closures — a closed-over per-scenario
# scalar/array bakes into the HLO as a constant, so N variants lower N
# distinct programs and the compile ledger fills with near-identical
# builds (the recompile-per-variant smell).  Unlike R14 (which exempts
# load-time builder scopes, where a ladder of programs is legitimate),
# this rule fires in EVERY scope: building one program per scenario is
# the thing the suite exists to avoid, even at load time.
#
# Shape detected: a loop (or comprehension) whose target/iterable names
# read scenario-ish ("scenario"/"variant"/"domain"), whose per-iteration
# subtree constructs a traced program — jit/pmap/shard_map, or one of
# the envs/rollout.py builders — with the loop variable (or a value
# derived from it inside the loop) referenced anywhere in the
# construction.  Calling an ALREADY-jitted program with per-variant
# arguments is the fix, and stays silent.

_SCENARIO_TOKENS = ("scenario", "variant", "domain")
_ROLLOUT_BUILDERS = {"make_rollout", "make_population_rollout",
                     "make_batched_rollout"}


def _scenarioish_names(*nodes: ast.AST) -> bool:
    for node in nodes:
        if node is None:
            continue
        for sub in ast.walk(node):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name and any(t in name.lower() for t in _SCENARIO_TOKENS):
                return True
    return False


def _target_names(target: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


def _is_program_ctor(ctx: ModuleContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = ctx.resolve(node.func)
    if resolved is None:
        return False
    tail = resolved.rsplit(".", 1)[-1]
    return tail in ("jit", "pmap", "shard_map") or tail in _ROLLOUT_BUILDERS


def _derived_names(body: list[ast.AST], seeds: set[str]) -> set[str]:
    """Seeds plus names bound (one straight-line pass, iterated to a
    fixpoint) from expressions referencing a seed — `p = scenario.g`
    makes `p` per-scenario too."""
    names = set(seeds)
    changed = True
    while changed:
        changed = False
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                refs = {n.id for n in ast.walk(node.value)
                        if isinstance(n, ast.Name)}
                if refs & names:
                    for t in node.targets:
                        new = _target_names(t) - names
                        if new:
                            names |= new
                            changed = True
    return names


def _loop_sites(scope: ast.AST):
    """(per-iteration body nodes, target names, scenario-ish?) for every
    for-loop and comprehension in one scope."""
    for node in scope_nodes(scope):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield (list(node.body) + list(node.orelse),
                   _target_names(node.target),
                   _scenarioish_names(node.target, node.iter))
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            parts = ([node.key, node.value]
                     if isinstance(node, ast.DictComp) else [node.elt])
            targets: set[str] = set()
            scenarioish = False
            for gen in node.generators:
                targets |= _target_names(gen.target)
                scenarioish = scenarioish or _scenarioish_names(
                    gen.target, gen.iter)
            yield [p for p in parts if p is not None], targets, scenarioish


@rule("R16", "scenario-constant-closure", "warning",
      "per-scenario constant closed over by a jitted rollout/step program "
      "— one XLA program per variant instead of one traced operand")
def check_scenario_constant_closure(ctx: ModuleContext):
    r = get_rule("R16")
    out = []
    seen: set[int] = set()
    enclosing = enclosing_defs(ctx.tree)  # once per module, not per finding
    for _symbol, scope in iter_scopes(ctx):
        for body, targets, scenarioish in _loop_sites(scope):
            if not scenarioish or not targets:
                continue
            per_variant = _derived_names(body, targets)
            for stmt in body:
                ctors = [n for n in ast.walk(stmt)
                         if _is_program_ctor(ctx, n)]
                # one finding per construction SITE: jit(make_rollout(..,
                # variant)) is one smell, not two — drop ctors nested
                # inside another ctor's subtree
                nested = {id(inner) for outer in ctors
                          for inner in ast.walk(outer)
                          if inner is not outer
                          and _is_program_ctor(ctx, inner)}
                for node in ctors:
                    if id(node) in nested:
                        continue
                    refs = {n.id for n in ast.walk(node)
                            if isinstance(n, ast.Name)
                            and isinstance(n.ctx, ast.Load)}
                    if not (refs & per_variant) or id(node) in seen:
                        continue
                    seen.add(id(node))
                    names = sorted(refs & per_variant)
                    qualname = ctx.qualnames.get(
                        enclosing.get(node) or ctx.tree, "<module>")
                    out.append(make_finding(
                        ctx, r, node,
                        f"per-scenario value(s) {names} are closed over "
                        "by a traced-program construction inside a "
                        "scenario loop — every variant lowers its own "
                        "XLA program (recompile-per-variant)",
                        "make the scenario constants traced operands: a "
                        "ScenarioParams pytree riding the env state "
                        "(estorch_tpu/scenarios) or an explicit argument "
                        "of ONE jitted program called per variant",
                        qualname))
    return out
