"""esguard: JAX-aware static analysis for the estorch_tpu framework.

The failure modes that matter here — reused PRNG keys breaking mirrored
sampling, host syncs and impurity inside jitted hot paths, unbounded
subprocess waits wedging a pod worker — are invisible to unit tests
until real hardware makes them expensive.  esguard catches them at
AST level, on CPU, in seconds:

    python -m estorch_tpu.analysis estorch_tpu/                # human
    python -m estorch_tpu.analysis --format=json estorch_tpu/  # machine
    python -m estorch_tpu.analysis --changed origin/main...HEAD  # PR path

Rules (docs/analysis.md has the full rationale per rule):

* R01 prng-key-reuse          — same key consumed by >1 random op
* R02 host-sync-in-hot-path   — .item()/np.array()/float() under trace
* R03 impure-jit              — print/time/np.random/closure mutation under jit
* R04 missing-donation        — jitted update without donate_argnums
* R05 untimed-subprocess-wait — proc.wait()/communicate() without timeout
* R06 signature-probe-default — inspect.signature fallback that guesses
* R07 unfenced-device-timing  — perf_counter delta around jitted dispatch
                                without a block_until_ready fence
* R08 swallowed-fault         — pass-only except outside teardown/probes
* R09 nonmonotonic-span-clock — wall-clock deltas timing spans/ages
* R10 unsharded-capture       — host arrays closed over by sharded jit
* R11 blocking-wait-in-scheduler — unbounded queue.get/thread.join/
                                conn.recv in an event-loop hot path
* R12 gauge-shaped-latency    — perf_counter/monotonic duration recorded
                                via a last-write-wins gauge (tail erased;
                                observe into a histogram instead)
* R13 untimed-network-call    — urlopen/HTTPConnection/create_connection
                                without timeout= (block-forever default)
* R14 jit-in-request-path     — jit/pmap/shard_map constructed inside a
                                request handler or non-load-time loop
* R15 unbounded-retry         — network retry loop with no attempt bound
                                or no backoff between attempts
* R16 scenario-constant-closure — per-scenario constant closed over by
                                a jitted rollout/step construction
                                (recompile-per-variant; traced-operand
                                contract of estorch_tpu/scenarios)
* R17 unfenced-cross-host-barrier — jax.distributed.initialize without
                                initialization_timeout, or an untimed
                                coordinator-socket accept/recv(n)
                                (one silent peer wedges the fleet)

The R18–R22 lockset family runs at PROJECT scope — per-file summaries
are linked into a whole-program view (import graph, call graph,
shared-mutable-state inventory) before the checks fire, because no
single file shows both sides of a data race:

* R18 unguarded-shared-write  — attribute guarded by a lock somewhere,
                                written bare somewhere else
* R19 lock-order-inversion    — two locks taken in both orders
                                (lexically or one call level deep)
* R20 callback-mutates-foreign-state — thread/callback/handler root
                                mutating another object's state lockless
* R21 await-under-lock        — indefinitely-blocking call while a
                                lock is held
* R22 daemon-thread-orphan    — non-daemon thread never joined, or
                                started and dropped

Nothing in this package imports jax or the analyzed modules — analysis
is pure ``ast`` and safe to run where no accelerator exists.
"""

from .baseline import (ApplyResult, Baseline, BaselineEntry, load_baseline,
                       save_baseline)
from .config import EsguardConfig, load_config
from .engine import (Rule, all_rules, analyze_paths, analyze_source,
                     default_jobs, get_rule, iter_py_files,
                     render_rule_table, rule)
from .findings import Finding, findings_to_json, sort_findings
from .project import ModuleSummary, ProjectContext, build_summary
from .ratchet import (RatchetResult, check_ratchet, count_findings,
                      load_ratchet, save_ratchet)

__all__ = [
    "ApplyResult", "Baseline", "BaselineEntry", "EsguardConfig", "Finding",
    "ModuleSummary", "ProjectContext", "RatchetResult", "Rule",
    "all_rules", "analyze_paths", "analyze_source", "build_summary",
    "check_ratchet", "count_findings", "default_jobs", "findings_to_json",
    "get_rule", "iter_py_files", "load_baseline", "load_config",
    "load_ratchet", "render_rule_table", "rule", "save_baseline",
    "save_ratchet", "sort_findings",
]
