"""esguard rule engine: registry, per-file driver, path expansion.

A rule is a function ``(ModuleContext) -> Iterable[Finding]`` registered
with :func:`rule`.  The driver parses each ``.py`` file once, builds one
:class:`~estorch_tpu.analysis.context.ModuleContext`, and feeds it to
every enabled rule — so adding a rule costs one function, not a new
traversal pipeline.

The engine itself never imports the analyzed code: everything is
``ast``-level, runs on CPU in milliseconds, and is safe to point at
modules whose import would grab an accelerator.
"""

from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator

from .context import ModuleContext, build_context
from .findings import Finding


@dataclass(frozen=True)
class Rule:
    id: str  # "R01"
    name: str  # "prng-key-reuse"
    severity: str  # default severity for findings it emits
    description: str
    check: Callable[[ModuleContext], Iterable[Finding]]


_REGISTRY: dict[str, Rule] = {}


def rule(id: str, name: str, severity: str, description: str):
    """Register ``check(ctx) -> Iterable[Finding]`` under a rule id."""

    def deco(check: Callable[[ModuleContext], Iterable[Finding]]):
        if id in _REGISTRY:
            raise ValueError(f"duplicate rule id {id}")
        _REGISTRY[id] = Rule(id, name, severity, description, check)
        return check

    return deco


def all_rules() -> list[Rule]:
    _load_builtin_rules()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    _load_builtin_rules()
    return _REGISTRY[rule_id]


def _load_builtin_rules() -> None:
    # import for side effect: each module registers its rules on import
    from . import (rules_host, rules_perf, rules_prng,  # noqa: F401
                   rules_resilience, rules_trace)


def _rebase(path: str) -> str:
    """Cwd-relative spelling when the path lives under cwd, else as-is.
    Findings, baseline identities, and exclude globs all see THIS form,
    so `analysis /abs/repo/pkg` and `analysis pkg` (from the repo root)
    exclude and suppress identically."""
    rel = os.path.relpath(path)
    return path if rel.startswith("..") else rel


def iter_py_files(paths: Iterable[str],
                  exclude: Iterable[str] = ()) -> Iterator[str]:
    """Expand files/dirs to ``.py`` paths (cwd-relative where possible,
    see :func:`_rebase`), skipping ``exclude`` globs (matched against the
    normalized relative path AND its basename)."""
    exclude = list(exclude)

    def excluded(p: str) -> bool:
        norm = _rebase(p).replace(os.sep, "/")
        return any(
            fnmatch.fnmatch(norm, pat) or fnmatch.fnmatch(
                os.path.basename(norm), pat)
            for pat in exclude
        )

    paths = [_rebase(p) for p in paths]
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py") and not excluded(path):
                yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__"
                    and not excluded(os.path.join(root, d)))
                for f in sorted(files):
                    full = os.path.join(root, f)
                    if f.endswith(".py") and not excluded(full):
                        yield full


def analyze_source(path: str, source: str,
                   rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Run rules over one module's source.  Syntax errors become a single
    parse-error finding instead of aborting the whole run."""
    if rules is None:
        rules = all_rules()
    try:
        ctx = build_context(path, source)
    except SyntaxError as e:
        return [Finding(
            rule="R00", file=path, line=e.lineno or 0, col=e.offset or 0,
            severity="error", message=f"file does not parse: {e.msg}",
            hint="fix the syntax error; esguard skipped this file",
            symbol="<module>", snippet=(e.text or "").strip(),
        )]
    findings: list[Finding] = []
    for r in rules:
        findings.extend(r.check(ctx))
    return findings


def analyze_paths(paths: Iterable[str],
                  rules: Iterable[Rule] | None = None,
                  exclude: Iterable[str] = ()) -> list[Finding]:
    if rules is None:
        rules = all_rules()
    rules = list(rules)
    findings: list[Finding] = []
    for path in iter_py_files(paths, exclude):
        with open(path, encoding="utf-8") as fh:
            findings.extend(analyze_source(path, fh.read(), rules))
    return findings


# ---------------------------------------------------------------------
# shared helpers for the rule modules
# ---------------------------------------------------------------------

def enclosing_defs(tree: ast.Module) -> dict[ast.AST, ast.AST | None]:
    """node -> nearest enclosing function def (None at module level)."""
    parent_fn: dict[ast.AST, ast.AST | None] = {}

    def walk(node: ast.AST, fn: ast.AST | None) -> None:
        for child in ast.iter_child_nodes(node):
            parent_fn[child] = fn
            walk(child, child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn)

    walk(tree, None)
    return parent_fn


def scope_nodes(scope: ast.AST):
    """Nodes belonging to one function (or module) scope: walks the body
    without descending into nested function defs, so a rule iterating
    per-scope never double-reports a nested function's body."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def iter_scopes(ctx: ModuleContext):
    """All (symbol, scope_node) pairs: the module plus every function."""
    yield "<module>", ctx.tree
    for fn, qualname in ctx.qualnames.items():
        yield qualname, fn


def make_finding(ctx: ModuleContext, rule_: Rule, node: ast.AST,
                 message: str, hint: str, symbol: str,
                 severity: str | None = None) -> Finding:
    line = getattr(node, "lineno", 0)
    return Finding(
        rule=rule_.id, file=ctx.path, line=line,
        col=getattr(node, "col_offset", 0),
        severity=severity or rule_.severity, message=message, hint=hint,
        symbol=symbol, snippet=ctx.line_at(line),
    )
