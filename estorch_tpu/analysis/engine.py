"""esguard rule engine: registry, per-file driver, path expansion.

A rule is a function ``(ModuleContext) -> Iterable[Finding]`` registered
with :func:`rule`.  The driver parses each ``.py`` file once, builds one
:class:`~estorch_tpu.analysis.context.ModuleContext`, and feeds it to
every enabled rule — so adding a rule costs one function, not a new
traversal pipeline.

Rules come in two scopes.  ``scope="module"`` (the default) sees one
file at a time.  ``scope="project"`` rules (the R18–R22 lockset family)
receive a :class:`~estorch_tpu.analysis.project.ProjectContext` linking
every analyzed module — import aliases, call graph, shared-state
inventory — built from per-file :class:`ModuleSummary` records.  The
per-file work (parse + module rules + summary extraction) fans out
across a fork-based process pool; the cheap project pass links the
returned summaries in the parent.

The engine itself never imports the analyzed code: everything is
``ast``-level, runs on CPU in milliseconds, and is safe to point at
modules whose import would grab an accelerator.
"""

from __future__ import annotations

import ast
import concurrent.futures
import fnmatch
import multiprocessing
import os
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator

from .context import ModuleContext, build_context
from .findings import Finding


@dataclass(frozen=True)
class Rule:
    id: str  # "R01"
    name: str  # "prng-key-reuse"
    severity: str  # default severity for findings it emits
    description: str
    check: Callable[..., Iterable[Finding]]
    scope: str = "module"  # "module" -> ModuleContext, "project" -> ProjectContext


_REGISTRY: dict[str, Rule] = {}


def rule(id: str, name: str, severity: str, description: str,
         scope: str = "module"):
    """Register ``check(ctx) -> Iterable[Finding]`` under a rule id."""

    def deco(check: Callable[..., Iterable[Finding]]):
        if id in _REGISTRY:
            raise ValueError(f"duplicate rule id {id}")
        _REGISTRY[id] = Rule(id, name, severity, description, check, scope)
        return check

    return deco


def all_rules() -> list[Rule]:
    _load_builtin_rules()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    _load_builtin_rules()
    return _REGISTRY[rule_id]


def _load_builtin_rules() -> None:
    # import for side effect: each module registers its rules on import
    from . import (rules_host, rules_perf, rules_prng,  # noqa: F401
                   rules_races, rules_resilience, rules_trace)


def render_rule_table() -> str:
    """The registry as a markdown table — docs/analysis.md embeds this
    between markers so the catalog cannot drift from the code (a test
    diffs the two)."""
    rows = [
        "| id | name | severity | scope | description |",
        "| --- | --- | --- | --- | --- |",
    ]
    for r in all_rules():
        rows.append(f"| {r.id} | `{r.name}` | {r.severity} | {r.scope} "
                    f"| {r.description} |")
    return "\n".join(rows) + "\n"


def _rebase(path: str) -> str:
    """Cwd-relative spelling when the path lives under cwd, else as-is.
    Findings, baseline identities, and exclude globs all see THIS form,
    so `analysis /abs/repo/pkg` and `analysis pkg` (from the repo root)
    exclude and suppress identically."""
    rel = os.path.relpath(path)
    return path if rel.startswith("..") else rel


def iter_py_files(paths: Iterable[str],
                  exclude: Iterable[str] = ()) -> Iterator[str]:
    """Expand files/dirs to ``.py`` paths (cwd-relative where possible,
    see :func:`_rebase`), skipping ``exclude`` globs (matched against the
    normalized relative path AND its basename)."""
    exclude = list(exclude)

    def excluded(p: str) -> bool:
        norm = _rebase(p).replace(os.sep, "/")
        return any(
            fnmatch.fnmatch(norm, pat) or fnmatch.fnmatch(
                os.path.basename(norm), pat)
            for pat in exclude
        )

    paths = [_rebase(p) for p in paths]
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py") and not excluded(path):
                yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__"
                    and not excluded(os.path.join(root, d)))
                for f in sorted(files):
                    full = os.path.join(root, f)
                    if f.endswith(".py") and not excluded(full):
                        yield full


def _syntax_finding(path: str, e: SyntaxError) -> Finding:
    return Finding(
        rule="R00", file=path, line=e.lineno or 0, col=e.offset or 0,
        severity="error", message=f"file does not parse: {e.msg}",
        hint="fix the syntax error; esguard skipped this file",
        symbol="<module>", snippet=(e.text or "").strip(),
    )


def _split_rules(rules: list[Rule]) -> tuple[list[Rule], list[Rule]]:
    return ([r for r in rules if r.scope == "module"],
            [r for r in rules if r.scope == "project"])


def analyze_source(path: str, source: str,
                   rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Run rules over one module's source.  Syntax errors become a single
    parse-error finding instead of aborting the whole run.  Project
    rules see a single-module ProjectContext — a one-file "program" —
    so fixtures and single-file invocations still exercise R18–R22."""
    from .project import ProjectContext, build_summary
    if rules is None:
        rules = all_rules()
    mod_rules, proj_rules = _split_rules(list(rules))
    try:
        ctx = build_context(path, source)
    except SyntaxError as e:
        return [_syntax_finding(path, e)]
    findings: list[Finding] = []
    for r in mod_rules:
        findings.extend(r.check(ctx))
    if proj_rules:
        pctx = ProjectContext([build_summary(ctx)])
        for r in proj_rules:
            findings.extend(r.check(pctx))
    return findings


def _analyze_one(task: tuple[str, tuple[str, ...], bool]):
    """Process-pool unit: one file -> (module-rule findings, summary).
    Top-level so it pickles; rules rehydrate from the registry by id
    (the fork start method means workers inherit a loaded registry)."""
    from .project import build_summary
    path, rule_ids, need_summary = task
    mod_rules = [get_rule(i) for i in rule_ids]
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    try:
        ctx = build_context(path, source)
    except SyntaxError as e:
        return [_syntax_finding(path, e)], None
    findings: list[Finding] = []
    for r in mod_rules:
        findings.extend(r.check(ctx))
    summary = build_summary(ctx) if need_summary else None
    return findings, summary


def default_jobs() -> int:
    return max(1, min(os.cpu_count() or 1, 8))


def analyze_paths(paths: Iterable[str],
                  rules: Iterable[Rule] | None = None,
                  exclude: Iterable[str] = (),
                  jobs: int | None = None) -> list[Finding]:
    """Analyze every file under ``paths``: module rules per file (in a
    fork process pool when it pays off), then the whole-program pass
    over the linked summaries.  ``jobs<=1`` forces the serial path; any
    pool failure falls back to it too — the analyzer must never be the
    thing that breaks CI."""
    from .project import ProjectContext
    if rules is None:
        rules = all_rules()
    mod_rules, proj_rules = _split_rules(list(rules))
    files = list(iter_py_files(paths, exclude))
    tasks = [(p, tuple(r.id for r in mod_rules), bool(proj_rules))
             for p in files]
    if jobs is None:
        jobs = default_jobs()
    results = None
    if (jobs > 1 and len(tasks) >= 16
            and "fork" in multiprocessing.get_all_start_methods()):
        try:
            mp_ctx = multiprocessing.get_context("fork")
            with concurrent.futures.ProcessPoolExecutor(
                    max_workers=jobs, mp_context=mp_ctx) as pool:
                results = list(pool.map(
                    _analyze_one, tasks,
                    chunksize=max(1, len(tasks) // (jobs * 4))))
        except Exception:
            results = None  # serial fallback below
    if results is None:
        results = [_analyze_one(t) for t in tasks]
    findings: list[Finding] = []
    summaries = []
    for file_findings, summary in results:
        findings.extend(file_findings)
        if summary is not None:
            summaries.append(summary)
    if proj_rules:
        pctx = ProjectContext(summaries)
        for r in proj_rules:
            findings.extend(r.check(pctx))
    return findings


# ---------------------------------------------------------------------
# shared helpers for the rule modules
# ---------------------------------------------------------------------

def walk_tree(tree: ast.Module) -> tuple[ast.AST, ...]:
    """``ast.walk(tree)`` flattened once and cached on the tree — the
    traversal itself (deque + iter_child_nodes per node) costs more than
    most rules' per-node work, and every rule repeats it."""
    cached = getattr(tree, "_esguard_all_nodes", None)
    if cached is None:
        cached = tuple(ast.walk(tree))
        tree._esguard_all_nodes = cached
    return cached


def enclosing_defs(tree: ast.Module) -> dict[ast.AST, ast.AST | None]:
    """node -> nearest enclosing function def (None at module level).
    Cached on the tree: a dozen rules ask for this map per file, and on
    a single-core runner rebuilding it dominated the whole-tree wall
    time (the ~2s run_lint budget)."""
    cached = getattr(tree, "_esguard_parent_fn", None)
    if cached is not None:
        return cached
    parent_fn: dict[ast.AST, ast.AST | None] = {}

    def walk(node: ast.AST, fn: ast.AST | None) -> None:
        for child in ast.iter_child_nodes(node):
            parent_fn[child] = fn
            walk(child, child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn)

    walk(tree, None)
    tree._esguard_parent_fn = parent_fn
    return parent_fn


def scope_nodes(scope: ast.AST):
    """Nodes belonging to one function (or module) scope: walks the body
    without descending into nested function defs, so a rule iterating
    per-scope never double-reports a nested function's body.  Cached on
    the scope node — every iter_scopes-driven rule re-enumerates the
    same scopes."""
    cached = getattr(scope, "_esguard_scope_nodes", None)
    if cached is not None:
        return cached
    out = []
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        out.append(node)
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))
    scope._esguard_scope_nodes = out
    return out


def iter_scopes(ctx: ModuleContext):
    """All (symbol, scope_node) pairs: the module plus every function."""
    yield "<module>", ctx.tree
    for fn, qualname in ctx.qualnames.items():
        yield qualname, fn


def symbol_map(ctx: ModuleContext) -> dict:
    """node -> qualname of its own scope, cached on the tree (the
    iter_scopes × scope_nodes product is the same for every rule)."""
    cached = getattr(ctx.tree, "_esguard_symbol_of", None)
    if cached is None:
        cached = {}
        for symbol, scope in iter_scopes(ctx):
            for node in scope_nodes(scope):
                cached.setdefault(node, symbol)
        ctx.tree._esguard_symbol_of = cached
    return cached


def make_finding(ctx: ModuleContext, rule_: Rule, node: ast.AST,
                 message: str, hint: str, symbol: str,
                 severity: str | None = None) -> Finding:
    line = getattr(node, "lineno", 0)
    return Finding(
        rule=rule_.id, file=ctx.path, line=line,
        col=getattr(node, "col_offset", 0),
        severity=severity or rule_.severity, message=message, hint=hint,
        symbol=symbol, snippet=ctx.line_at(line),
    )
