"""Race rules R18–R22: the lockset pass over the whole-program view.

These are the first ``scope="project"`` rules: their check receives a
:class:`~estorch_tpu.analysis.project.ProjectContext` (every module's
summary, linked), not a single ModuleContext.  The bug class is the one
CPU pytest can never see — writes that are correct in every unit test
and corrupt state only when the fleet's poll/monitor/rollout threads
interleave just wrong.

The noise budget follows R02/R03: every heuristic errs toward silence.

* R18 unguarded-shared-write — an attribute written under a lock on
  some paths and bare on others.  The locked write is the module's own
  testimony that the attribute is shared; the bare write is the race.
  Suppressed when the bare writer's name says ``locked`` (caller-holds-
  lock convention) or when every known call site of the bare writer
  already holds a lock.
* R19 lock-order-inversion — locks A and B acquired as A→B on one path
  and B→A on another (lexical nesting plus one level of call
  expansion).  Classic deadlock; reported once per unordered pair.
* R20 callback-mutates-foreign-state — a function reachable from a
  concurrency root (Thread target, HTTP ``do_*`` handler, callback
  kwarg, signal handler) writes an attribute of an object it does not
  own (a parameter or shared loop variable, not ``self``) with no lock
  held.  Locals built from calls are fresh and exempt.
* R21 await-under-lock — a blocking call (``recv``/``accept``/zero-arg
  ``wait``/``join``/``get``/``communicate``, ``time.sleep``, untimed
  ``urlopen``) while holding a lock: every other thread that wants the
  lock now waits on a socket it never sees.  ``with cond: cond.wait()``
  is the Condition protocol and exempt.
* R22 daemon-thread-orphan — a non-daemon thread that no shutdown path
  ever joins: interpreter exit blocks on it forever.  Either mark it
  ``daemon=True`` (this repo's convention for service loops) or join it
  in ``close``/``shutdown``.
"""

from __future__ import annotations

from .engine import get_rule, rule
from .project import ProjectContext, project_finding


def _locked_by_convention(pctx: ProjectContext, module: str,
                          symbol: str) -> bool:
    """The two sanctioned ways a function writes shared state bare:
    its name declares the caller holds the lock, or every known call
    site actually does."""
    tail = symbol.rsplit(".", 1)[-1]
    if "locked" in tail:
        return True
    return pctx.always_called_locked(module, symbol)


@rule("R18", "unguarded-shared-write", "warning",
      "attribute written under a lock on some paths, bare on others",
      scope="project")
def check_unguarded_shared_write(pctx: ProjectContext):
    r = get_rule("R18")
    out = []
    for s in pctx.summaries:
        # group writes per attribute; self-writes additionally keyed by
        # class so two classes' unrelated `self.x` never merge
        groups: dict[tuple[str, str], list] = {}
        for w in s.attr_writes:
            key = (f"self:{w.owner}" if w.kind == "self" else "foreign",
                   w.attr)
            groups.setdefault(key, []).append(w)
        # a locked foreign write vouches for same-attr self-writes too
        # (Replica.__init__ sets self.health; the router writes
        # rep.health under its lock) — merge self groups into a foreign
        # group for the same attr when the foreign group has evidence
        merged: dict[tuple[str, str], list] = {}
        for key, writes in groups.items():
            kind, attr = key
            if kind != "foreign" and ("foreign", attr) in groups:
                merged.setdefault(("foreign", attr), []).extend(writes)
            else:
                merged.setdefault(key, []).extend(writes)
        for (kind, attr), writes in sorted(merged.items()):
            locked = [w for w in writes if w.locks]
            bare = [w for w in writes if not w.locks and not w.in_init]
            if not locked or not bare:
                continue
            guard = sorted({l for w in locked for l in w.locks})
            seen_sites = set()
            for w in bare:
                if _locked_by_convention(pctx, s.module, w.symbol):
                    continue
                sk = (w.site.line, w.site.col)
                if sk in seen_sites:
                    continue
                seen_sites.add(sk)
                out.append(project_finding(
                    r, s, w.site,
                    f"`.{attr}` is written under {'/'.join(guard)} "
                    f"elsewhere in this module but bare here — "
                    f"torn/stale reads on the locked paths",
                    f"hold {guard[0]} for this write too (or rename the "
                    f"helper *_locked and acquire at every call site)",
                    w.symbol))
    return out


@rule("R19", "lock-order-inversion", "error",
      "two locks acquired in opposite orders on different paths",
      scope="project")
def check_lock_order_inversion(pctx: ProjectContext):
    r = get_rule("R19")
    # edge -> first (summary, symbol, site) that exhibits it
    edges: dict[tuple[str, str], tuple] = {}
    for s in pctx.summaries:
        for e in s.lock_edges:
            edges.setdefault((e.outer, e.inner), (s, e.symbol, e.site))
        # one level of call expansion: f holds L and calls g; g acquires
        # M at any depth of its own body -> edge L->M at the call site
        for cs in s.call_sites:
            if not cs.locks:
                continue
            node = pctx._resolve_callee(s, cs)
            if node is None:
                continue
            callee_summary = pctx.by_module[node[0]]
            for inner in callee_summary.acquires.get(node[1], ()):
                for outer in cs.locks:
                    if outer != inner:
                        edges.setdefault((outer, inner),
                                         (s, cs.caller, cs.site))
    out = []
    reported = set()
    for (a, b), (s, symbol, site) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].path, kv[1][2].line)):
        if (b, a) not in edges or frozenset((a, b)) in reported:
            continue
        reported.add(frozenset((a, b)))
        s2, sym2, site2 = edges[(b, a)]
        out.append(project_finding(
            r, s, site,
            f"lock order inversion: {a} → {b} here, but "
            f"{b} → {a} at {s2.path}:{site2.line} ({sym2}) — "
            f"two threads on these paths deadlock",
            f"pick one global order for {a} and {b} and acquire them "
            f"in that order on every path",
            symbol))
    return out


@rule("R20", "callback-mutates-foreign-state", "warning",
      "thread/handler-reachable code writes another object's attribute "
      "with no lock held", scope="project")
def check_callback_mutates_foreign_state(pctx: ProjectContext):
    r = get_rule("R20")
    out = []
    for s in pctx.summaries:
        for w in s.attr_writes:
            if w.kind != "foreign" or w.locks:
                continue
            if not pctx.is_reachable(s.module, w.symbol):
                continue
            if _locked_by_convention(pctx, s.module, w.symbol):
                continue
            out.append(project_finding(
                r, s, w.site,
                f"`{w.owner}.{w.attr}` written from thread/handler-"
                f"reachable code with no lock — the owner's other "
                f"threads see a torn update",
                f"acquire the lock that owns `{w.owner}` (or publish "
                f"via a queue/atomic swap instead of in-place mutation)",
                w.symbol))
    return out


@rule("R21", "await-under-lock", "warning",
      "blocking socket/subprocess/queue wait while holding a lock",
      scope="project")
def check_await_under_lock(pctx: ProjectContext):
    r = get_rule("R21")
    out = []
    for s in pctx.summaries:
        for b in s.blocking_calls:
            if b.receiver_is_held_lock:
                continue  # `with cond: cond.wait()` — Condition protocol
            out.append(project_finding(
                r, s, b.site,
                f"{b.desc} can block indefinitely while holding "
                f"{'/'.join(b.locks)} — every thread contending that "
                f"lock wedges behind this wait",
                "move the blocking call outside the with-block (snapshot "
                "under the lock, wait outside) or give it a timeout",
                b.symbol))
    return out


@rule("R22", "daemon-thread-orphan", "warning",
      "non-daemon thread that no shutdown path ever joins",
      scope="project")
def check_daemon_thread_orphan(pctx: ProjectContext):
    r = get_rule("R22")
    out = []
    for s in pctx.summaries:
        for t in s.thread_creates:
            if t.daemon:
                continue
            if t.stored and (t.stored in s.daemon_marked
                             or t.stored in s.joined):
                continue
            if t.stored:
                msg = (f"non-daemon thread stored as {t.stored} is never "
                       f"joined on any shutdown path — interpreter exit "
                       f"blocks on it forever")
            else:
                msg = ("non-daemon thread started and dropped — nothing "
                       "can ever join it, interpreter exit blocks on it "
                       "forever")
            out.append(project_finding(
                r, s, t.site, msg,
                "pass daemon=True (the service-loop convention here) or "
                "keep the handle and join it in close()/shutdown()",
                t.symbol))
    return out
