"""esguard CLI: ``python -m estorch_tpu.analysis [paths...]``.

Exit codes: 0 clean; 1 unsuppressed findings or a ratchet regression;
2 ledger problems only (stale/unjustified baseline entries or a stale
ratchet count with an otherwise-clean tree); 3 bad invocation.

``--format=json`` (or the legacy ``--json`` flag) emits the full
machine-readable report CI archives as an artifact.  ``--changed
<git-range>`` analyzes only the ``.py`` files touched in that range —
the fast PR path — and skips the ratchet plus stale-entry checks, which
are only meaningful against the whole tree.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .baseline import Baseline, load_baseline, save_baseline
from .config import load_config
from .engine import all_rules, analyze_paths, default_jobs
from .findings import sort_findings
from .ratchet import (RatchetResult, check_ratchet, count_findings,
                      load_ratchet, save_ratchet)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.analysis",
        description="esguard: JAX-aware static analysis "
                    "(PRNG/trace/host/lockset hazards)")
    p.add_argument("paths", nargs="*", default=["estorch_tpu"],
                   help="files or directories (default: estorch_tpu)")
    p.add_argument("--changed", default=None, metavar="GIT_RANGE",
                   help="analyze only .py files changed in this git "
                        "range (e.g. origin/main...HEAD); skips the "
                        "ratchet and stale-baseline checks")
    p.add_argument("--format", default=None, dest="fmt",
                   choices=["text", "json"],
                   help="report format (default: text)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format=json")
    p.add_argument("--jobs", type=int, default=None, metavar="N",
                   help="process-pool width for per-file analysis "
                        f"(default: min(cpus, 8) = {default_jobs()})")
    p.add_argument("--config", default=None, metavar="PYPROJECT",
                   help="pyproject.toml with [tool.esguard] "
                        "(default: ./pyproject.toml)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline JSON (overrides config)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any configured baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline and exit 0")
    p.add_argument("--ratchet", default=None, metavar="PATH",
                   help="ratchet JSON (overrides config)")
    p.add_argument("--no-ratchet", action="store_true",
                   help="ignore any configured ratchet")
    p.add_argument("--write-ratchet", action="store_true",
                   help="pin current per-rule totals for the rules the "
                        "ratchet file already lists (all active rules "
                        "when the file is new) and exit 0")
    p.add_argument("--select", default=None, metavar="IDS",
                   help="comma-separated rule ids to run (e.g. R01,R05)")
    p.add_argument("--ignore", default=None, metavar="IDS",
                   help="comma-separated rule ids to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    return p


def changed_files(git_range: str) -> list[str] | None:
    """``.py`` files touched in the range that still exist (deletions
    have nothing to analyze).  None on git failure -> exit 3 upstream."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "-z", git_range, "--", "*.py"],
            capture_output=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    names = [n for n in out.stdout.decode("utf-8", "replace").split("\0")
             if n]
    return [n for n in names if os.path.exists(n)]


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    rules = all_rules()

    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:26s} [{r.severity}/{r.scope}] "
                  f"{r.description}")
        return 0

    cfg = load_config(args.config)
    ids = cfg.rule_ids([r.id for r in rules])
    if args.select:
        ids = [i for i in ids if i in args.select.split(",")]
    if args.ignore:
        ids = [i for i in ids if i not in args.ignore.split(",")]
    active = [r for r in rules if r.id in ids]
    if not active:
        print("esguard: no rules selected", file=sys.stderr)
        return 3

    fmt = "json" if args.as_json else (args.fmt or "text")

    paths = args.paths
    if args.changed is not None:
        paths = changed_files(args.changed)
        if paths is None:
            print(f"esguard: git diff failed for range "
                  f"{args.changed!r}", file=sys.stderr)
            return 3
        if not paths:
            if fmt == "json":
                print(json.dumps({"rules": ids, "findings": [],
                                  "suppressed": [], "stale_baseline": [],
                                  "unjustified_baseline": [],
                                  "ratchet": None, "changed": []},
                                 indent=2, sort_keys=True))
            else:
                print("esguard: no changed python files in "
                      f"{args.changed}")
            return 0

    findings = sort_findings(analyze_paths(
        paths, rules=active, exclude=cfg.exclude, jobs=args.jobs))

    baseline_path = args.baseline or cfg.baseline_path()
    if args.no_baseline:
        baseline_path = None
    ratchet_path = args.ratchet or cfg.ratchet_path()
    if args.no_ratchet or args.changed is not None:
        ratchet_path = None

    if args.write_baseline:
        if baseline_path is None:
            print("esguard: --write-baseline needs --baseline or a "
                  "[tool.esguard] baseline entry", file=sys.stderr)
            return 3
        save_baseline(baseline_path, findings)
        print(f"esguard: wrote {len(findings)} entr"
              f"{'y' if len(findings) == 1 else 'ies'} to {baseline_path} "
              "— add a `reason` to each before committing")
        return 0

    if args.write_ratchet:
        ratchet_path = args.ratchet or cfg.ratchet_path()
        if ratchet_path is None:
            print("esguard: --write-ratchet needs --ratchet or a "
                  "[tool.esguard] ratchet entry", file=sys.stderr)
            return 3
        recorded = load_ratchet(ratchet_path)
        pin_ids = sorted(recorded) if recorded else ids
        counts = count_findings(findings, pin_ids)
        save_ratchet(ratchet_path, counts)
        print(f"esguard: pinned {len(counts)} rule count"
              f"{'' if len(counts) == 1 else 's'} in {ratchet_path}")
        return 0

    baseline = (load_baseline(baseline_path)
                if baseline_path is not None else Baseline())
    res = baseline.apply(findings)
    unjustified = baseline.unjustified()
    # a partial tree makes every untouched baseline entry look stale
    if args.changed is not None:
        res.stale = []
        unjustified = []

    ratchet_res = RatchetResult()
    if ratchet_path is not None:
        ratchet_res = check_ratchet(load_ratchet(ratchet_path), findings)

    if fmt == "json":
        print(json.dumps({
            "rules": ids,
            "findings": [f.to_dict() for f in res.unsuppressed],
            "suppressed": [f.to_dict() for f in res.suppressed],
            "stale_baseline": [vars(e) for e in res.stale],
            "unjustified_baseline": [vars(e) for e in unjustified],
            "ratchet": None if ratchet_path is None else {
                "path": ratchet_path,
                "regressions": [
                    {"rule": r, "recorded": a, "actual": b}
                    for r, a, b in ratchet_res.regressions],
                "stale": [
                    {"rule": r, "recorded": a, "actual": b}
                    for r, a, b in ratchet_res.stale],
            },
            "changed": (paths if args.changed is not None else None),
        }, indent=2, sort_keys=True))
    else:
        for f in res.unsuppressed:
            print(f.render())
        for e in res.stale:
            print(f"STALE baseline entry: {e.rule} {e.file} [{e.symbol}] "
                  f"`{e.snippet}` — the finding is gone; delete the entry")
        for e in unjustified:
            print(f"UNJUSTIFIED baseline entry: {e.rule} {e.file} "
                  f"[{e.symbol}] — add a `reason`")
        for rid, allow, have in ratchet_res.regressions:
            print(f"RATCHET regression: {rid} has {have} finding"
                  f"{'' if have == 1 else 's'}, ceiling is {allow} — "
                  "fix the new ones; the count cannot grow")
        for rid, allow, have in ratchet_res.stale:
            print(f"STALE ratchet count: {rid} has {have}, recorded "
                  f"{allow} — lock the improvement in with "
                  "--write-ratchet")
        n = len(res.unsuppressed)
        print(f"esguard: {n} finding{'' if n == 1 else 's'} "
              f"({len(res.suppressed)} baselined, {len(res.stale)} stale, "
              f"{len(findings)} total) across rules {','.join(ids)}")

    if res.unsuppressed or ratchet_res.regressions:
        return 1
    if res.stale or unjustified or ratchet_res.stale:
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
