"""esguard CLI: ``python -m estorch_tpu.analysis [paths...]``.

Exit codes: 0 clean; 1 unsuppressed findings; 2 baseline problems only
(stale or unjustified entries with an otherwise-clean tree); 3 bad
invocation.  ``--json`` emits a machine-readable report for CI.
"""

from __future__ import annotations

import argparse
import json
import sys

from .baseline import Baseline, load_baseline, save_baseline
from .config import load_config
from .engine import all_rules, analyze_paths
from .findings import sort_findings


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.analysis",
        description="esguard: JAX-aware static analysis "
                    "(PRNG/trace/host hazards)")
    p.add_argument("paths", nargs="*", default=["estorch_tpu"],
                   help="files or directories (default: estorch_tpu)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="JSON report on stdout")
    p.add_argument("--config", default=None, metavar="PYPROJECT",
                   help="pyproject.toml with [tool.esguard] "
                        "(default: ./pyproject.toml)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline JSON (overrides config)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any configured baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline and exit 0")
    p.add_argument("--select", default=None, metavar="IDS",
                   help="comma-separated rule ids to run (e.g. R01,R05)")
    p.add_argument("--ignore", default=None, metavar="IDS",
                   help="comma-separated rule ids to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    rules = all_rules()

    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:26s} [{r.severity}] {r.description}")
        return 0

    cfg = load_config(args.config)
    ids = cfg.rule_ids([r.id for r in rules])
    if args.select:
        ids = [i for i in ids if i in args.select.split(",")]
    if args.ignore:
        ids = [i for i in ids if i not in args.ignore.split(",")]
    active = [r for r in rules if r.id in ids]
    if not active:
        print("esguard: no rules selected", file=sys.stderr)
        return 3

    findings = sort_findings(
        analyze_paths(args.paths, rules=active, exclude=cfg.exclude))

    baseline_path = args.baseline or cfg.baseline_path()
    if args.no_baseline:
        baseline_path = None

    if args.write_baseline:
        if baseline_path is None:
            print("esguard: --write-baseline needs --baseline or a "
                  "[tool.esguard] baseline entry", file=sys.stderr)
            return 3
        save_baseline(baseline_path, findings)
        print(f"esguard: wrote {len(findings)} entr"
              f"{'y' if len(findings) == 1 else 'ies'} to {baseline_path} "
              "— add a `reason` to each before committing")
        return 0

    baseline = (load_baseline(baseline_path)
                if baseline_path is not None else Baseline())
    res = baseline.apply(findings)
    unjustified = baseline.unjustified()

    if args.as_json:
        print(json.dumps({
            "rules": ids,
            "findings": [f.to_dict() for f in res.unsuppressed],
            "suppressed": [f.to_dict() for f in res.suppressed],
            "stale_baseline": [vars(e) for e in res.stale],
            "unjustified_baseline": [vars(e) for e in unjustified],
        }, indent=2, sort_keys=True))
    else:
        for f in res.unsuppressed:
            print(f.render())
        for e in res.stale:
            print(f"STALE baseline entry: {e.rule} {e.file} [{e.symbol}] "
                  f"`{e.snippet}` — the finding is gone; delete the entry")
        for e in unjustified:
            print(f"UNJUSTIFIED baseline entry: {e.rule} {e.file} "
                  f"[{e.symbol}] — add a `reason`")
        n = len(res.unsuppressed)
        print(f"esguard: {n} finding{'' if n == 1 else 's'} "
              f"({len(res.suppressed)} baselined, {len(res.stale)} stale, "
              f"{len(findings)} total) across rules {','.join(ids)}")

    if res.unsuppressed:
        return 1
    if res.stale or unjustified:
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
