"""Host-side robustness rules: R05 untimed-subprocess-wait,
R06 signature-probe-default.

R05 is the wedge class ``doctor.py`` exists to detect after the fact:
a ``proc.wait()`` / ``proc.communicate()`` with no timeout turns a hung
child into a hung training job — on a TPU pod that's a wedged tunnel
window, not a stack trace.  Every wait on a subprocess must bound its
patience and escalate (kill, requeue, raise) itself.

R06 is the bug family from rollout's ``_ci_takes_params``: when
``inspect.signature`` fails on an exotic callable, falling back to a
*guessed* constant silently picks a calling convention; the wrong guess
crashes at trace time far from the cause.  The fallback must PROBE
(call the zero-arg form under ``except TypeError``) instead of guessing.
"""

from __future__ import annotations

import ast
import re

from .context import ModuleContext
from .engine import get_rule, iter_scopes, make_finding, rule, scope_nodes

# ---------------------------------------------------------------------
# R05 untimed-subprocess-wait
# ---------------------------------------------------------------------

_PROC_CTORS = {"subprocess.Popen", "multiprocessing.Process"}
# one-shot helpers in the same hazard class: block until the child exits
_RUN_HELPERS = {"subprocess.run", "subprocess.call", "subprocess.check_call",
                "subprocess.check_output"}
_PROCISH_NAME = re.compile(r"(^|_)(proc|process|popen|child)(es|s)?($|_)",
                           re.IGNORECASE)


def _has_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    # Popen.wait(timeout) may be positional; communicate(input, timeout)
    # positional timeout is arg index 1
    if isinstance(call.func, ast.Attribute):
        if call.func.attr == "wait" and len(call.args) >= 1:
            return True
        if call.func.attr == "communicate" and len(call.args) >= 2:
            return True
    return False


def _receiver_tail(func: ast.Attribute) -> str | None:
    """Last name component of the receiver: `self.proc.wait` -> "proc"."""
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return None


@rule("R05", "untimed-subprocess-wait", "error",
      "subprocess wait/communicate without a timeout can wedge the host")
def check_untimed_wait(ctx: ModuleContext):
    r = get_rule("R05")
    out = []
    for symbol, scope in iter_scopes(ctx):
        proc_names: set[str] = set()
        # pass 1: names bound from Popen/Process constructors in this scope
        for node in scope_nodes(scope):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                resolved = ctx.resolve(node.value.func)
                tail = (resolved or "").rsplit(".", 1)[-1]
                if resolved in _PROC_CTORS or tail == "Popen":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            proc_names.add(tgt.id)
        # pass 2: unbounded waits on those names (or proc-ish receivers)
        for node in scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved in _RUN_HELPERS and not any(
                    kw.arg == "timeout"
                    and not (isinstance(kw.value, ast.Constant)
                             and kw.value.value is None)
                    for kw in node.keywords):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{resolved}` without timeout — a hung child wedges "
                    "this host forever",
                    "pass timeout=... and handle "
                    "subprocess.TimeoutExpired",
                    symbol))
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            method = node.func.attr
            if method not in ("wait", "communicate"):
                continue
            if _has_timeout(node):
                continue
            tail = _receiver_tail(node.func)
            known = (isinstance(node.func.value, ast.Name)
                     and node.func.value.id in proc_names)
            procish = tail is not None and _PROCISH_NAME.search(tail)
            # bare `.communicate()` is Popen-specific; `.wait()` needs a
            # proc-ish receiver so DMA/thread/event waits stay quiet
            if not (known or procish or method == "communicate"):
                continue
            out.append(make_finding(
                ctx, r, node,
                f"`.{method}()` without timeout — a hung child wedges "
                "this host forever",
                f"call `.{method}(timeout=...)` and kill/escalate on "
                "subprocess.TimeoutExpired",
                symbol))
    return out


# ---------------------------------------------------------------------
# R06 signature-probe-default
# ---------------------------------------------------------------------

def _calls_signature(ctx: ModuleContext, stmts: list[ast.stmt]) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                resolved = ctx.resolve(node.func)
                if resolved in ("inspect.signature",
                                "inspect.getfullargspec"):
                    return True
    return False


def _guessing_assign(handler: ast.ExceptHandler) -> ast.stmt | None:
    """The handler's constant-assignment, when the handler does nothing
    but guess (assignments of constants, pass, or a comment)."""
    guess: ast.stmt | None = None
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Constant)):
            guess = guess or stmt
            continue
        if (isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.value, ast.Constant)):
            guess = guess or stmt
            continue
        return None  # handler does real work (probes, raises, logs...)
    return guess


@rule("R06", "signature-probe-default", "warning",
      "inspect.signature failure falls back to a guessed constant")
def check_signature_probe(ctx: ModuleContext):
    r = get_rule("R06")
    parent_symbol = {}
    for symbol, scope in iter_scopes(ctx):
        for node in scope_nodes(scope):
            parent_symbol[node] = symbol
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        if not _calls_signature(ctx, node.body):
            continue
        for handler in node.handlers:
            guess = _guessing_assign(handler)
            if guess is None:
                continue
            out.append(make_finding(
                ctx, r, guess,
                "signature introspection failed and the fallback GUESSES "
                "a calling convention",
                "probe once at build time instead: call the zero-arg form "
                "under `except TypeError` and record which form worked",
                parent_symbol.get(node, "<module>")))
    return out
