"""Host-side robustness rules: R05 untimed-subprocess-wait,
R06 signature-probe-default, R11 blocking-wait-in-scheduler,
R13 untimed-network-call, R15 unbounded-retry,
R17 unfenced-cross-host-barrier, R23 dropped-trace-context.

R05 is the wedge class ``doctor.py`` exists to detect after the fact:
a ``proc.wait()`` / ``proc.communicate()`` with no timeout turns a hung
child into a hung training job — on a TPU pod that's a wedged tunnel
window, not a stack trace.  Every wait on a subprocess must bound its
patience and escalate (kill, requeue, raise) itself.

R06 is the bug family from rollout's ``_ci_takes_params``: when
``inspect.signature`` fails on an exotic callable, falling back to a
*guessed* constant silently picks a calling convention; the wrong guess
crashes at trace time far from the cause.  The fallback must PROBE
(call the zero-arg form under ``except TypeError``) instead of guessing.

R11 is R05 generalized to IN-PROCESS queues and threads — the hazard
class the async scheduler (algo/scheduler.py) introduced: an event loop
that blocks unbounded on ``queue.get()``, ``thread.join()``, or a pipe
``recv()`` turns one silent producer (a straggler that never wakes, a
worker that died mid-message) into a wedged scheduler, invisible to the
heartbeat because the loop never reaches its next beat.  Every blocking
point in an event-driven hot path must wake on a bounded slice.

R13 is the R05 discipline lifted to SOCKETS — the hazard class the
fleet collector (obs/agg/) made systemic: a ``urllib.request.urlopen``
or ``http.client.HTTPConnection`` without ``timeout=`` inherits the
global socket default (None: block forever), so one replica that
accepts the TCP connection and then goes silent wedges the scraper,
the client, or the doctor probe that called it.  CPython's own default
timeouts are None throughout; the bound must be at the call site.

R17 is the R05/R11/R13 family lifted to the HOST layer — the hazard
class the elastic multi-host work (parallel/elastic.py, multihost.py)
made systemic: a cross-host rendezvous with no deadline.  Two shapes:
(1) ``jax.distributed.initialize`` without ``initialization_timeout`` —
the cluster barrier where a peer that never dials in hangs every host
in the job, indefinitely and identically, so no survivor can even name
the missing peer; (2) a raw coordinator-socket blocking wait —
``.accept()`` or a buffer-sized ``.recv(n)``/``.recvfrom(n)`` on a
socket-ish receiver — in a scope that never bounds it (no
``settimeout``, no ``select``-style readiness wait, and no
``socket.timeout``/``TimeoutError`` handler, which only ever fires on a
timed socket).  The zero-arg pipe ``recv()`` stays R11's; socket
CONSTRUCTION timeouts stay R13's; R17 owns the per-wait fence on an
accepted/long-lived connection.

R15 is the retry half of the same failure story: a loop that catches a
network call's exception and tries again with NO attempt bound (``while
True``) turns a dead peer into an infinite hammer, and one with no
backoff/sleep between attempts turns a mass failover into a stampede
that finishes off the survivors.  The front router's budgeted retry
(serve/router.py: ``for attempt in range(1 + retry_budget)`` with
exponential backoff + jitter) is the prescribed shape.  Scope is
syntactic: the network call must be visible inside the loop's try body
(a retry that delegates to a helper is judged where the helper makes
its calls), and a handler that contains any ``raise`` is treated as
escalating, not retrying — the single stale-keep-alive reconnect idiom
(serve/client.py) raises on its second failure and stays clean.

R23 is trace-context PROPAGATION as a static contract
(docs/observability.md "Distributed tracing"): a handler that read the
inbound ``X-Trace-Id`` header (``self.headers.get`` — the
BaseHTTPRequestHandler receiver; a client reading a RESPONSE header is
the opposite direction and out of scope) and then makes an outbound
HTTP hop (``urlopen`` / ``conn.request``) in the same scope must put
the header on that hop; otherwise every process behind this one mints
fresh trace ids and the fleet-wide assembly (``obs trace --fleet``)
ends here with no arrow out.  Forwarding sites: the header as a
dict-literal key, an ``add_header``/``putheader``/``setdefault`` first
argument, or a subscript-store key.  The front router's
``_upstream_predict`` headers dict (serve/router.py) is the prescribed
shape.
"""

from __future__ import annotations

import ast
import re

from .context import ModuleContext
from .engine import get_rule, iter_scopes, make_finding, rule, scope_nodes, walk_tree

# ---------------------------------------------------------------------
# R05 untimed-subprocess-wait
# ---------------------------------------------------------------------

_PROC_CTORS = {"subprocess.Popen", "multiprocessing.Process"}
# one-shot helpers in the same hazard class: block until the child exits
_RUN_HELPERS = {"subprocess.run", "subprocess.call", "subprocess.check_call",
                "subprocess.check_output"}
_PROCISH_NAME = re.compile(r"(^|_)(proc|process|popen|child)(es|s)?($|_)",
                           re.IGNORECASE)


def _has_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    # Popen.wait(timeout) may be positional; communicate(input, timeout)
    # positional timeout is arg index 1
    if isinstance(call.func, ast.Attribute):
        if call.func.attr == "wait" and len(call.args) >= 1:
            return True
        if call.func.attr == "communicate" and len(call.args) >= 2:
            return True
    return False


def _receiver_tail(func: ast.Attribute) -> str | None:
    """Last name component of the receiver: `self.proc.wait` -> "proc"."""
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return None


@rule("R05", "untimed-subprocess-wait", "error",
      "subprocess wait/communicate without a timeout can wedge the host")
def check_untimed_wait(ctx: ModuleContext):
    r = get_rule("R05")
    out = []
    for symbol, scope in iter_scopes(ctx):
        proc_names: set[str] = set()
        # pass 1: names bound from Popen/Process constructors in this scope
        for node in scope_nodes(scope):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                resolved = ctx.resolve(node.value.func)
                tail = (resolved or "").rsplit(".", 1)[-1]
                if resolved in _PROC_CTORS or tail == "Popen":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            proc_names.add(tgt.id)
        # pass 2: unbounded waits on those names (or proc-ish receivers)
        for node in scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved in _RUN_HELPERS and not any(
                    kw.arg == "timeout"
                    and not (isinstance(kw.value, ast.Constant)
                             and kw.value.value is None)
                    for kw in node.keywords):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{resolved}` without timeout — a hung child wedges "
                    "this host forever",
                    "pass timeout=... and handle "
                    "subprocess.TimeoutExpired",
                    symbol))
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            method = node.func.attr
            if method not in ("wait", "communicate"):
                continue
            if _has_timeout(node):
                continue
            tail = _receiver_tail(node.func)
            known = (isinstance(node.func.value, ast.Name)
                     and node.func.value.id in proc_names)
            procish = tail is not None and _PROCISH_NAME.search(tail)
            # bare `.communicate()` is Popen-specific; `.wait()` needs a
            # proc-ish receiver so DMA/thread/event waits stay quiet
            if not (known or procish or method == "communicate"):
                continue
            out.append(make_finding(
                ctx, r, node,
                f"`.{method}()` without timeout — a hung child wedges "
                "this host forever",
                f"call `.{method}(timeout=...)` and kill/escalate on "
                "subprocess.TimeoutExpired",
                symbol))
    return out


# ---------------------------------------------------------------------
# R11 blocking-wait-in-scheduler
# ---------------------------------------------------------------------

# receiver-name heuristics, same approach as R05's _PROCISH_NAME: the
# names people actually give queues / worker threads / pipe connections
_QUEUEISH_NAME = re.compile(
    r"(^|_)(queue|q|events?|inbox|outbox|results?|tasks?|mailbox)(s)?($|_)",
    re.IGNORECASE)
_THREADISH_NAME = re.compile(
    r"(^|_)(thread|worker|pump|collector|consumer|producer)(s)?($|_)",
    re.IGNORECASE)
_CONNISH_NAME = re.compile(
    r"(^|_)(conn|connection|pipe|sock|socket|channel)(s)?($|_)",
    re.IGNORECASE)


def _kw(call: ast.Call, name: str) -> ast.keyword | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def _untimed_get(call: ast.Call) -> bool:
    """queue.get() blocking forever: no positional args (dict.get(key)
    and protocol gets always pass one), no timeout, and not the
    non-blocking form (block=False / get_nowait is a different name)."""
    if call.args:
        return False
    kw = _kw(call, "timeout")
    if kw is not None and not (isinstance(kw.value, ast.Constant)
                               and kw.value.value is None):
        return False
    block = _kw(call, "block")
    if block is not None and isinstance(block.value, ast.Constant) \
            and block.value.value is False:
        return False
    return True


def _untimed_join(call: ast.Call) -> bool:
    """thread.join() with no bound: str.join(iterable) always has an
    argument, Thread.join(timeout) may be positional."""
    if call.args:
        return False
    kw = _kw(call, "timeout")
    return kw is None or (isinstance(kw.value, ast.Constant)
                          and kw.value.value is None)


def _scope_establishes_readiness(ctx: ModuleContext, scope) -> bool:
    """True when the scope bounds its pipe waits before recv(): a
    ``poll(timeout)`` probe or a ``wait(..., timeout=...)`` select-style
    call — the procpool idiom (conn.poll(slice) / mpc.wait(conns,
    timeout=...)), after which recv() only ever reads buffered data."""
    for node in scope_nodes(scope):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "poll" \
                and node.args:
            return True
        name = (node.func.attr if isinstance(node.func, ast.Attribute)
                else node.func.id if isinstance(node.func, ast.Name)
                else None)
        if name == "wait" and _kw(node, "timeout") is not None:
            return True
    return False


@rule("R11", "blocking-wait-in-scheduler", "error",
      "unbounded in-process wait (queue.get/thread.join/conn.recv) can "
      "wedge an event loop")
def check_blocking_wait(ctx: ModuleContext):
    r = get_rule("R11")
    out = []
    for symbol, scope in iter_scopes(ctx):
        ready = None  # lazy: computed only when a recv() shows up
        for node in scope_nodes(scope):
            if not isinstance(node, ast.Call) or not isinstance(
                    node.func, ast.Attribute):
                continue
            method = node.func.attr
            tail = _receiver_tail(node.func)
            if tail is None:
                continue
            if method == "get" and _QUEUEISH_NAME.search(tail) \
                    and _untimed_get(node):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{tail}.get()` without timeout — a producer that "
                    "never answers wedges this loop forever",
                    "call `.get(timeout=...)` in a bounded slice and "
                    "handle queue.Empty (re-check liveness, then retry)",
                    symbol))
            elif method == "join" and _THREADISH_NAME.search(tail) \
                    and _untimed_join(node):
                out.append(make_finding(
                    ctx, r, node,
                    f"`{tail}.join()` without timeout — a worker stuck "
                    "in a straggler sleep or dead lock never joins",
                    "call `.join(timeout=...)` and escalate (flag, "
                    "abandon a daemon thread, raise) when it misses",
                    symbol))
            elif method == "recv" and _CONNISH_NAME.search(tail) \
                    and not node.args:
                if ready is None:
                    ready = _scope_establishes_readiness(ctx, scope)
                if not ready:
                    out.append(make_finding(
                        ctx, r, node,
                        f"`{tail}.recv()` with no readiness guard — a "
                        "silent peer wedges this end forever",
                        "probe `.poll(timeout)` (or select via "
                        "multiprocessing.connection.wait with a timeout) "
                        "before recv, so the wait is bounded",
                        symbol))
    return out


# ---------------------------------------------------------------------
# R13 untimed-network-call
# ---------------------------------------------------------------------

# resolved dotted name -> positional index where `timeout` lands
# (urlopen(url, data, timeout); HTTPConnection(host, port, timeout);
# HTTPSConnection(host, port, key_file, cert_file, timeout) — the
# deprecated TLS params sit BEFORE timeout; create_connection(address,
# timeout, ...))
_NET_CALLS = {
    "urllib.request.urlopen": 2,
    "http.client.HTTPConnection": 2,
    "http.client.HTTPSConnection": 4,
    "socket.create_connection": 1,
}


def _net_has_timeout(call: ast.Call, pos_index: int) -> bool:
    kw = _kw(call, "timeout")
    if kw is not None:
        return not (isinstance(kw.value, ast.Constant)
                    and kw.value.value is None)
    if len(call.args) <= pos_index:
        return False
    # a positional literal None is spelling the unbounded default,
    # exactly like timeout=None
    arg = call.args[pos_index]
    return not (isinstance(arg, ast.Constant) and arg.value is None)


@rule("R13", "untimed-network-call", "error",
      "network connect/read without a timeout can wedge the host on one "
      "silent peer")
def check_untimed_network(ctx: ModuleContext):
    r = get_rule("R13")
    out = []
    for symbol, scope in iter_scopes(ctx):
        for node in scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved not in _NET_CALLS:
                continue
            if _net_has_timeout(node, _NET_CALLS[resolved]):
                continue
            out.append(make_finding(
                ctx, r, node,
                f"`{resolved}` without timeout — the global socket "
                "default is None (block forever), so one peer that "
                "accepts and goes silent wedges this host",
                "pass timeout=... at the call site and handle the "
                "TimeoutError/OSError (count it, retry, or mark the "
                "peer down)",
                symbol))
    return out


# ---------------------------------------------------------------------
# R15 unbounded-retry
# ---------------------------------------------------------------------

def _is_net_call(ctx: ModuleContext, node: ast.Call) -> bool:
    """The calls whose failure a retry loop plausibly retries: the R13
    connect/request layer (urlopen / HTTP[S]Connection /
    create_connection) plus ``.request()``/``.getresponse()`` on a
    conn-ish receiver."""
    resolved = ctx.resolve(node.func)
    if resolved in _NET_CALLS or (resolved or "").endswith(".urlopen"):
        return True
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("request", "getresponse"):
        tail = _receiver_tail(node.func)
        return tail is not None and bool(_CONNISH_NAME.search(tail))
    return False


def _loop_is_unbounded(loop: ast.While | ast.For,
                       ctx: ModuleContext) -> bool:
    if isinstance(loop, ast.While):
        t = loop.test
        return isinstance(t, ast.Constant) and bool(t.value)
    resolved = (ctx.resolve(loop.iter.func)
                if isinstance(loop.iter, ast.Call) else None)
    return resolved == "itertools.count"


def _has_backoff(loop: ast.While | ast.For, ctx: ModuleContext) -> bool:
    """Any sleep-shaped call in the loop body: ``time.sleep``, a
    ``.sleep()`` method, or an event-style ``.wait(timeout)`` — all
    space attempts out."""
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func)
        if resolved == "time.sleep":
            return True
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "sleep":
                return True
            if node.func.attr == "wait" and (node.args or node.keywords):
                return True
    return False


def _retrying_handlers(try_node: ast.Try) -> list[ast.ExceptHandler]:
    """Handlers that swallow the failure back into the loop: no
    ``raise`` anywhere in the handler body.  A handler that re-raises
    (even conditionally, like the client's second-attempt escalation)
    is bounding its own patience."""
    out = []
    for handler in try_node.handlers:
        if not any(isinstance(n, ast.Raise)
                   for stmt in handler.body for n in ast.walk(stmt)):
            out.append(handler)
    return out


def _walk_own_body(loop: ast.While | ast.For):
    """Nodes of ``loop`` WITHOUT descending into nested loops: a
    bounded, backed-off retry inside an outer ``while True`` dispatcher
    must be judged as its own (innermost) loop, not pinned on the
    outer one."""
    stack = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.While, ast.For, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


@rule("R15", "unbounded-retry", "error",
      "network retry loop with no attempt bound or no backoff between "
      "attempts")
def check_unbounded_retry(ctx: ModuleContext):
    r = get_rule("R15")
    out = []
    for symbol, scope in iter_scopes(ctx):
        for loop in scope_nodes(scope):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            # the retry shape: a try in THIS loop's own body (nested
            # loops are judged separately as their own retry loops)
            # whose body makes a network call and whose handler
            # swallows the failure into the next iteration
            retries_net = False
            for node in _walk_own_body(loop):
                if not isinstance(node, ast.Try):
                    continue
                if not _retrying_handlers(node):
                    continue
                if any(_is_net_call(ctx, c)
                       for stmt in node.body
                       for c in ast.walk(stmt)
                       if isinstance(c, ast.Call)):
                    retries_net = True
                    break
            if not retries_net:
                continue
            if _loop_is_unbounded(loop, ctx):
                out.append(make_finding(
                    ctx, r, loop,
                    "unbounded network retry: this loop catches the "
                    "failure and tries again forever — a dead peer "
                    "becomes an infinite hammer",
                    "bound the attempts (`for attempt in range(1 + "
                    "budget)`) and back off exponentially with jitter "
                    "between them (serve/router.py is the shape)",
                    symbol))
            elif not _has_backoff(loop, ctx):
                out.append(make_finding(
                    ctx, r, loop,
                    "network retry loop with no backoff: immediate "
                    "re-attempts turn a mass failover into a stampede "
                    "on the survivors",
                    "sleep between attempts (exponential backoff + "
                    "jitter, `time.sleep(base * 2**attempt * jitter)`) "
                    "or escalate after the first failure",
                    symbol))
    return out


# ---------------------------------------------------------------------
# R17 unfenced-cross-host-barrier
# ---------------------------------------------------------------------

_SOCKISH_NAME = re.compile(
    r"(^|_)(sock|socket|srv|server|listener|conn|connection|peer)"
    r"(s)?($|_)",
    re.IGNORECASE)
_SELECTISH_NAME = re.compile(
    r"(^|_)(sel|selector|selectors|select|poller|epoll|kqueue)(s)?($|_)",
    re.IGNORECASE)
_TIMEOUTISH_EXC = ("timeout", "TimeoutError")


def _scope_bounds_socket_waits(ctx: ModuleContext, scope,
                               wait_tail: str) -> bool:
    """True when the scope provably fences a wait on the receiver named
    ``wait_tail``: a ``settimeout(x)`` with a non-None bound on the SAME
    receiver (a timeout on some other socket bounds nothing here), a
    readiness wait on a selector-ish receiver (``sel.select(...)``/
    ``select.select(...)`` — the socket itself was registered elsewhere,
    so no receiver match is possible; a ``.select()`` on a non-selector
    receiver, e.g. an ORM query or a soup, is not a fence), or an
    ``except socket.timeout / TimeoutError`` handler — which only ever
    fires on a socket that HAS a timeout, so catching it is evidence one
    was set upstream (the elastic protocol helpers' shape: the
    connect/accept site sets the timeout, the recv loop catches)."""
    for node in scope_nodes(scope):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if (node.func.attr == "settimeout" and node.args
                    and _receiver_tail(node.func) == wait_tail
                    and not (isinstance(node.args[0], ast.Constant)
                             and node.args[0].value is None)):
                return True
            if node.func.attr == "select" and (node.args or node.keywords):
                recv = _receiver_tail(node.func)
                if recv is not None and _SELECTISH_NAME.search(recv):
                    return True
        if isinstance(node, ast.ExceptHandler) and node.type is not None:
            types = (node.type.elts
                     if isinstance(node.type, ast.Tuple) else [node.type])
            for t in types:
                name = (t.attr if isinstance(t, ast.Attribute)
                        else t.id if isinstance(t, ast.Name) else None)
                if name in _TIMEOUTISH_EXC:
                    return True
    return False


@rule("R17", "unfenced-cross-host-barrier", "error",
      "cross-host rendezvous (jax.distributed init / coordinator-socket "
      "wait) with no deadline hangs the whole fleet on one silent peer")
def check_unfenced_cross_host_barrier(ctx: ModuleContext):
    r = get_rule("R17")
    out = []
    for symbol, scope in iter_scopes(ctx):
        bounded: dict[str, bool] = {}  # per waited receiver, lazily
        for node in scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved == "jax.distributed.initialize":
                kw = _kw(node, "initialization_timeout")
                if kw is None or (isinstance(kw.value, ast.Constant)
                                  and kw.value.value is None):
                    out.append(make_finding(
                        ctx, r, node,
                        "`jax.distributed.initialize` without "
                        "`initialization_timeout` — one peer that never "
                        "dials in hangs EVERY host in the job, "
                        "indefinitely and identically",
                        "pass initialization_timeout=... (seconds) so "
                        "the barrier becomes a timed error naming the "
                        "wedge (parallel/multihost.py is the shape)",
                        symbol))
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            method = node.func.attr
            tail = _receiver_tail(node.func)
            if tail is None or not _SOCKISH_NAME.search(tail):
                continue
            # .accept() is argless; socket .recv/.recvfrom carry a
            # buffer size (the argless pipe recv() is R11's territory)
            wait = (method == "accept" and not node.args) or (
                method in ("recv", "recvfrom", "recv_into") and node.args)
            if not wait:
                continue
            if tail not in bounded:
                bounded[tail] = _scope_bounds_socket_waits(ctx, scope,
                                                           tail)
            if not bounded[tail]:
                out.append(make_finding(
                    ctx, r, node,
                    f"`{tail}.{method}()` with no deadline — a silent "
                    "peer (wedged host, half-open TCP) blocks this end "
                    "of the fleet forever",
                    "settimeout(...) the socket (or select with a "
                    "timeout) and loop on socket.timeout in bounded "
                    "slices, re-checking liveness each slice",
                    symbol))
    return out


# ---------------------------------------------------------------------
# R06 signature-probe-default
# ---------------------------------------------------------------------

def _calls_signature(ctx: ModuleContext, stmts: list[ast.stmt]) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                resolved = ctx.resolve(node.func)
                if resolved in ("inspect.signature",
                                "inspect.getfullargspec"):
                    return True
    return False


def _guessing_assign(handler: ast.ExceptHandler) -> ast.stmt | None:
    """The handler's constant-assignment, when the handler does nothing
    but guess (assignments of constants, pass, or a comment)."""
    guess: ast.stmt | None = None
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Constant)):
            guess = guess or stmt
            continue
        if (isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.value, ast.Constant)):
            guess = guess or stmt
            continue
        return None  # handler does real work (probes, raises, logs...)
    return guess


@rule("R06", "signature-probe-default", "warning",
      "inspect.signature failure falls back to a guessed constant")
def check_signature_probe(ctx: ModuleContext):
    r = get_rule("R06")
    parent_symbol = {}
    for symbol, scope in iter_scopes(ctx):
        for node in scope_nodes(scope):
            parent_symbol[node] = symbol
    out = []
    for node in walk_tree(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        if not _calls_signature(ctx, node.body):
            continue
        for handler in node.handlers:
            guess = _guessing_assign(handler)
            if guess is None:
                continue
            out.append(make_finding(
                ctx, r, guess,
                "signature introspection failed and the fallback GUESSES "
                "a calling convention",
                "probe once at build time instead: call the zero-arg form "
                "under `except TypeError` and record which form worked",
                parent_symbol.get(node, "<module>")))
    return out


# ---------------------------------------------------------------------
# R23 dropped-trace-context
# ---------------------------------------------------------------------

_TRACE_HEADER_LITERAL = "X-Trace-Id"
# header-constant names from obs/tracing.py: a resolved name ending in
# one of these IS the trace header, however the module imported it
_TRACE_HEADER_NAMES = {"TRACE_HEADER"}


def _is_trace_token(ctx: ModuleContext, node: ast.AST) -> bool:
    """Is this expression the trace-id header key — the literal
    "X-Trace-Id" or the TRACE_HEADER constant (any import spelling)?"""
    if isinstance(node, ast.Constant):
        return node.value == _TRACE_HEADER_LITERAL
    resolved = ctx.resolve(node)
    return bool(resolved) and \
        resolved.rsplit(".", 1)[-1] in _TRACE_HEADER_NAMES


def _reads_inbound_trace(ctx: ModuleContext, node: ast.AST) -> bool:
    """``self.headers.get(<trace token>)`` / ``self.headers[<token>]`` —
    the BaseHTTPRequestHandler read that makes this scope a RECEIVER of
    trace context (a ``resp.headers.get`` on a client response is the
    opposite direction and stays out of scope)."""
    def _self_headers(base: ast.AST) -> bool:
        return (isinstance(base, ast.Attribute) and base.attr == "headers"
                and isinstance(base.value, ast.Name)
                and base.value.id == "self")

    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and _self_headers(node.func.value)
            and node.args and _is_trace_token(ctx, node.args[0])):
        return True
    return (isinstance(node, ast.Subscript) and _self_headers(node.value)
            and _is_trace_token(ctx, node.slice))


def _is_outbound_http(ctx: ModuleContext, call: ast.Call) -> bool:
    """An outbound HTTP hop: ``urllib.request.urlopen`` or the
    ``conn.request(method, path, ...)`` HTTPConnection idiom."""
    if ctx.resolve(call.func) == "urllib.request.urlopen":
        return True
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "request" and len(call.args) >= 2)


def _scope_forwards_trace(ctx: ModuleContext, nodes) -> bool:
    """Any forwarding site in the scope: the trace header as a dict-
    literal key, an ``add_header``/``putheader``/``setdefault`` first
    argument, or a subscript-store key (``headers[TRACE_HEADER] = ...``)."""
    for node in nodes:
        if isinstance(node, ast.Dict):
            if any(k is not None and _is_trace_token(ctx, k)
                   for k in node.keys):
                return True
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("add_header", "putheader",
                                       "setdefault")
                and node.args and _is_trace_token(ctx, node.args[0])):
            return True
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Subscript)
                   and _is_trace_token(ctx, t.slice)
                   for t in node.targets):
                return True
    return False


@rule("R23", "dropped-trace-context", "warning",
      "handler received X-Trace-Id but its outbound HTTP hop does not "
      "forward it — the assembled trace ends here")
def check_dropped_trace_context(ctx: ModuleContext):
    r = get_rule("R23")
    out = []
    for symbol, scope in iter_scopes(ctx):
        nodes = scope_nodes(scope)
        if not any(_reads_inbound_trace(ctx, n) for n in nodes):
            continue
        outbound = [n for n in nodes
                    if isinstance(n, ast.Call)
                    and _is_outbound_http(ctx, n)]
        if not outbound or _scope_forwards_trace(ctx, nodes):
            continue
        for call in outbound:
            out.append(make_finding(
                ctx, r, call,
                "this scope read the inbound `X-Trace-Id` header but "
                "its outbound HTTP call never forwards it — every hop "
                "behind this one becomes a separate, unjoinable trace",
                "put the trace id on the outbound request (a "
                '`{"X-Trace-Id": trace}` headers entry or '
                "`add_header(TRACE_HEADER, trace)`) — and forward "
                "`X-Parent-Span` beside it so the assembly keeps "
                "parentage (docs/observability.md 'Distributed "
                "tracing')",
                symbol))
    return out
