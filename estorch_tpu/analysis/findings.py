"""Finding: one diagnostic emitted by an esguard rule.

A finding pins a (rule, file, line) triple plus everything a reader needs
to act on it without re-running the analyzer: severity, the offending
source line, a one-line message, and a concrete fix hint.  The identity
used for baseline suppression is deliberately line-number-free —
``(rule, file, symbol, snippet)`` — so unrelated edits above a
grandfathered finding don't invalidate the baseline entry.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable

# ordered weakest → strongest; CLI sorts strongest first
SEVERITIES = ("info", "warning", "error")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # "R01"
    file: str  # path as given to the analyzer (repo-relative in CI)
    line: int  # 1-based
    col: int  # 0-based (ast convention)
    severity: str  # one of SEVERITIES
    message: str  # what is wrong, one line
    hint: str  # how to fix it, one line
    symbol: str  # enclosing function qualname ("<module>" at top level)
    snippet: str  # stripped source line — part of the baseline identity

    def key(self) -> tuple[str, str, str, str]:
        """Baseline identity: stable across pure line-number drift."""
        return (self.rule, self.file, self.symbol, self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.file}:{self.line}:{self.col}: {self.rule} "
            f"[{self.severity}] {self.message}\n"
            f"    {self.snippet}\n"
            f"    hint: {self.hint}"
        )


def sort_findings(findings: Iterable[Finding]) -> list[Finding]:
    """Strongest severity first, then file/line for stable output."""
    return sorted(
        findings,
        key=lambda f: (
            -SEVERITIES.index(f.severity), f.file, f.line, f.rule),
    )


def findings_to_json(findings: Iterable[Finding]) -> str:
    return json.dumps(
        [f.to_dict() for f in findings], indent=2, sort_keys=True)
