"""R01 prng-key-reuse: one PRNG key, at most one consuming random op.

The ES correctness contract (Salimans et al. 2017 mirrored sampling, and
this repo's offset-derivation scheme) depends on every ``jax.random``
consumer seeing a distinct key: feeding the same key to two consuming
ops makes their "independent" noise identical, which silently breaks
antithetic pairs and cross-member independence without any exception.

The rule runs a small per-function abstract interpretation:

* a name becomes a TRACKED key when assigned from ``PRNGKey``/``key``/
  ``split``/``fold_in`` (tuple unpacking of ``split`` included) or when
  it is a parameter with a key-ish name (``key``, ``rng``, ...);
* a consuming ``jax.random.*`` call (``split``, ``normal``, ``uniform``,
  anything except the constructors and ``fold_in``) marks its key
  argument USED — a second consumption without re-assignment is the
  finding;
* passing a tracked key to any non-``jax.random`` call forfeits
  tracking (ownership moved to the callee — the callee is analyzed on
  its own), keeping helper-function plumbing quiet;
* loop bodies are interpreted twice, so a key created outside a loop
  and consumed inside it (the classic "same noise every iteration" bug)
  is caught even though each textual consumption appears once.

``fold_in`` is a deriver, not a consumer: ``fold_in(key, i)`` inside a
loop is the idiomatic per-iteration stream and must stay clean.
"""

from __future__ import annotations

import ast
import re

from .context import ModuleContext
from .engine import get_rule, make_finding, rule

# constructors / derivers: produce keys, never flagged as consumption
_PRODUCER_TAILS = {"PRNGKey", "key", "wrap_key_data", "fold_in", "clone"}
_KEY_PARAM_RE = re.compile(
    r"^(key|rng|rng_key|prng_key|prngkey|subkey|sub_key|random_key)$")


def _random_call_tail(ctx: ModuleContext, call: ast.Call) -> str | None:
    """'split' for a call resolving under jax.random, else None."""
    resolved = ctx.resolve(call.func)
    if resolved is None:
        return None
    head, _, tail = resolved.rpartition(".")
    if head in ("jax.random", "jax._src.random") or (
            head.endswith(".random") and head.startswith("jax")):
        return tail
    return None


def _names_in(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub


class _Interp:
    """Linear abstract interpreter over one function body."""

    def __init__(self, ctx: ModuleContext, symbol: str, out: list):
        self.ctx = ctx
        self.symbol = symbol
        self.out = out
        self.seen: set[tuple[int, str]] = set()  # dedup (line, name)
        # name -> mutable status cell (["fresh"] / ["used"]); aliases share
        self.state: dict[str, list[str]] = {}

    # ---- events ------------------------------------------------------

    def _flag(self, node: ast.Call, name: str) -> None:
        if (node.lineno, name) in self.seen:
            return
        self.seen.add((node.lineno, name))
        r = get_rule("R01")
        self.out.append(make_finding(
            self.ctx, r, node,
            f"PRNG key `{name}` already consumed by an earlier random op",
            f"split first: `{name}, sub = jax.random.split({name})` and "
            "consume the fresh half",
            self.symbol,
        ))

    def _consume(self, call: ast.Call, arg: ast.AST) -> None:
        if isinstance(arg, ast.Name) and arg.id in self.state:
            cell = self.state[arg.id]
            if cell[0] == "used":
                self._flag(call, arg.id)
            cell[0] = "used"

    def _forfeit(self, node: ast.AST) -> None:
        """Untrack keys handed DIRECTLY to an unknown callee (the callee
        owns them now).  Names inside nested calls stay tracked — in
        ``outs.append(normal(key))`` the key was consumed by ``normal``,
        not given away to ``append``."""
        if isinstance(node, ast.Name):
            self.state.pop(node.id, None)
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set, ast.Starred)):
            for child in ast.iter_child_nodes(node):
                self._forfeit(child)

    # ---- expressions -------------------------------------------------

    def eval_expr(self, node: ast.AST) -> None:
        """Post-order walk emitting consume/forfeit events for calls."""
        if isinstance(node, ast.Call):
            for child in ast.iter_child_nodes(node):
                if child is not node.func:
                    self.eval_expr(child)
            tail = _random_call_tail(self.ctx, node)
            if tail is not None:
                if tail not in _PRODUCER_TAILS:
                    key_arg = node.args[0] if node.args else None
                    for kw in node.keywords:
                        if kw.arg == "key":
                            key_arg = kw.value
                    if key_arg is not None:
                        self._consume(node, key_arg)
            else:
                # unknown callee: it now owns any key we hand it
                for arg in node.args:
                    self._forfeit(arg)
                for kw in node.keywords:
                    self._forfeit(kw.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return  # separate scope; analyzed on its own
        else:
            for child in ast.iter_child_nodes(node):
                self.eval_expr(child)

    # ---- statements --------------------------------------------------

    def _bind_targets(self, targets: list[ast.AST], value: ast.AST) -> None:
        producing = (isinstance(value, ast.Call)
                     and (_random_call_tail(self.ctx, value) is not None))
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                if producing:
                    self.state[tgt.id] = ["fresh"]
                elif isinstance(value, ast.Name) and value.id in self.state:
                    self.state[tgt.id] = self.state[value.id]  # alias
                else:
                    self.state.pop(tgt.id, None)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    if isinstance(el, ast.Name):
                        if producing:
                            self.state[el.id] = ["fresh"]
                        else:
                            self.state.pop(el.id, None)
                    elif isinstance(el, ast.Starred) and isinstance(
                            el.value, ast.Name):
                        self.state.pop(el.value.id, None)

    def exec_block(self, stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt)

    def _snapshot(self) -> dict[str, list[str]]:
        return {k: list(v) for k, v in self.state.items()}

    def _merge(self, a: dict[str, list[str]],
               b: dict[str, list[str]]) -> None:
        merged: dict[str, list[str]] = {}
        for name in set(a) & set(b):
            # differing branch outcomes: assume the consuming path ran
            merged[name] = ["used" if "used" in (a[name][0], b[name][0])
                            else "fresh"]
        self.state = merged

    def exec_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign):
            self.eval_expr(stmt.value)
            self._bind_targets(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.eval_expr(stmt.value)
                self._bind_targets([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            self.eval_expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                self.state.pop(stmt.target.id, None)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.eval_expr(stmt.iter)
            if isinstance(stmt.target, ast.Name):
                self.state.pop(stmt.target.id, None)
            # two passes: catches out-of-loop keys consumed every iteration
            self.exec_block(stmt.body)
            self.exec_block(stmt.body)
            self.exec_block(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self.eval_expr(stmt.test)
            self.exec_block(stmt.body)
            self.exec_block(stmt.body)
            self.exec_block(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self.eval_expr(stmt.test)
            before = self._snapshot()
            self.exec_block(stmt.body)
            after_body = self._snapshot()
            self.state = before
            self.exec_block(stmt.orelse)
            self._merge(after_body, self._snapshot())
        elif isinstance(stmt, ast.Try):
            self.exec_block(stmt.body)
            for handler in stmt.handlers:
                self.exec_block(handler.body)
            self.exec_block(stmt.orelse)
            self.exec_block(stmt.finalbody)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.eval_expr(item.context_expr)
            self.exec_block(stmt.body)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            return  # separate scope
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.eval_expr(stmt.value)
        elif isinstance(stmt, ast.Expr):
            self.eval_expr(stmt.value)
        else:
            for child in ast.iter_child_nodes(stmt):
                self.eval_expr(child)


@rule("R01", "prng-key-reuse", "error",
      "the same PRNG key is consumed by more than one random op")
def check_prng_reuse(ctx: ModuleContext):
    out: list = []
    scopes: list[tuple[str, list[ast.stmt], list[str]]] = [
        ("<module>", ctx.tree.body, [])]
    for fn, qualname in ctx.qualnames.items():
        args = fn.args
        params = [a.arg for a in (
            args.posonlyargs + args.args + args.kwonlyargs)]
        key_params = [p for p in params if _KEY_PARAM_RE.match(p)]
        scopes.append((qualname, fn.body, key_params))
    for symbol, body, key_params in scopes:
        interp = _Interp(ctx, symbol, out)
        for p in key_params:
            interp.state[p] = ["fresh"]
        interp.exec_block(body)
    return out
