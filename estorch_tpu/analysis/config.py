"""esguard configuration: the ``[tool.esguard]`` table in pyproject.toml.

Python 3.10 has no ``tomllib`` and this image deliberately installs
nothing, so a tiny TOML-SUBSET reader lives here: one ``[tool.esguard]``
table of ``key = value`` pairs where value is a string, bool, int, or a
(possibly multi-line) array of strings.  That subset is the whole config
language on purpose — if the config ever needs more TOML than this, it
should become Python, not grow a parser.

Recognized keys::

    [tool.esguard]
    enable   = ["R01", "R02"]   # default: all registered rules
    disable  = ["R04"]          # subtracted after `enable`
    baseline = "esguard_baseline.json"
    ratchet  = "esguard_ratchet.json"   # per-rule shrink-only counts
    exclude  = ["*_pb2.py", "build/*"]  # glob per file path / basename
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field


@dataclass
class EsguardConfig:
    enable: list[str] | None = None  # None -> all rules
    disable: list[str] = field(default_factory=list)
    baseline: str | None = None
    ratchet: str | None = None
    exclude: list[str] = field(default_factory=list)
    root: str = "."  # directory the config file lives in

    def baseline_path(self) -> str | None:
        if self.baseline is None:
            return None
        return os.path.join(self.root, self.baseline)

    def ratchet_path(self) -> str | None:
        if self.ratchet is None:
            return None
        return os.path.join(self.root, self.ratchet)

    def rule_ids(self, all_ids: list[str]) -> list[str]:
        ids = list(all_ids) if self.enable is None else [
            i for i in all_ids if i in self.enable]
        return [i for i in ids if i not in self.disable]


_SECTION_RE = re.compile(r"^\s*\[(?P<name>[^\]]+)\]\s*$")
_KV_RE = re.compile(r"^\s*(?P<key>[A-Za-z0-9_-]+)\s*=\s*(?P<val>.+)$")


def _strip_comment(line: str) -> str:
    out, in_str, quote = [], False, ""
    for ch in line:
        if in_str:
            out.append(ch)
            if ch == quote:
                in_str = False
        elif ch in ("'", '"'):
            in_str, quote = True, ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
    return "".join(out).strip()


def _parse_value(raw: str):
    raw = raw.strip()
    if raw.startswith("["):
        items = re.findall(r"""["']([^"']*)["']""", raw)
        return list(items)
    if raw in ("true", "false"):
        return raw == "true"
    if (raw.startswith('"') and raw.endswith('"')) or (
            raw.startswith("'") and raw.endswith("'")):
        return raw[1:-1]
    try:
        return int(raw)
    except ValueError:
        return raw


def parse_esguard_table(text: str) -> dict:
    """The `[tool.esguard]` table as a dict (TOML subset, see module doc)."""
    table: dict = {}
    in_section = False
    pending_key: str | None = None
    pending_val: list[str] = []
    for line in text.splitlines():
        stripped = _strip_comment(line)
        if not stripped:
            continue
        m = _SECTION_RE.match(stripped)
        if m:
            in_section = m.group("name").strip() == "tool.esguard"
            pending_key = None
            continue
        if not in_section:
            continue
        if pending_key is not None:
            pending_val.append(stripped)
            if stripped.endswith("]"):
                table[pending_key] = _parse_value(" ".join(pending_val))
                pending_key = None
            continue
        m = _KV_RE.match(stripped)
        if not m:
            continue
        key, val = m.group("key"), m.group("val").strip()
        if val.startswith("[") and not val.endswith("]"):
            pending_key, pending_val = key, [val]  # multi-line array
        else:
            table[key] = _parse_value(val)
    return table


def load_config(pyproject_path: str | None = None) -> EsguardConfig:
    """Read ``[tool.esguard]``; absent file or table -> defaults."""
    if pyproject_path is None:
        pyproject_path = "pyproject.toml"
    cfg = EsguardConfig(root=os.path.dirname(pyproject_path) or ".")
    if not os.path.exists(pyproject_path):
        return cfg
    with open(pyproject_path, encoding="utf-8") as fh:
        table = parse_esguard_table(fh.read())
    if "enable" in table:
        cfg.enable = list(table["enable"])
    if "disable" in table:
        cfg.disable = list(table["disable"])
    if "baseline" in table:
        cfg.baseline = str(table["baseline"])
    if "ratchet" in table:
        cfg.ratchet = str(table["ratchet"])
    if "exclude" in table:
        cfg.exclude = list(table["exclude"])
    return cfg
