"""Resilience rule: R08 swallowed-fault.

A recovery/retry path that catches an exception and does NOTHING — no
re-raise, no log, no counter — turns a fault into silence: the dead
worker whose slice is NaN every generation, the checkpoint that never
finalized, the retry that never happened, all invisible until someone
audits a finished run.  The resilience layer's contract
(docs/resilience.md) is that every swallowed fault leaves evidence: a
telemetry counter bump, a flight-recorder event, or a re-raise.

Flagged: ``except`` handlers whose body is ONLY ``pass``, outside two
legitimate shapes:

* **teardown** — ``__del__`` / ``__exit__`` / ``close`` / ``shutdown``
  bodies (and ``*_close`` helpers): the object is dying, there is no one
  to tell, and raising from ``__del__`` is its own hazard;
* **fall-through probes** — a ``try`` whose body exits the scope
  (``return`` / ``continue`` / ``break``): the pass-handler IS the
  dispatch to the next strategy on the following line — the R06-
  prescribed probe idiom (envs/rollout.py ``carry_init_takes_params``),
  not a swallow.

A handler that does anything real (assigns a flag consumed later, bumps
a counter, logs, raises) is clean — the rule asks for evidence, not a
specific API.
"""

from __future__ import annotations

import ast

from .context import ModuleContext
from .engine import (enclosing_defs, get_rule, iter_scopes, make_finding,
                     rule, scope_nodes, symbol_map, walk_tree)

_TEARDOWN_NAMES = {"__del__", "__exit__", "close", "shutdown"}


def _is_teardown(fn: ast.AST | None) -> bool:
    if fn is None:
        return False
    name = getattr(fn, "name", "")
    return (name in _TEARDOWN_NAMES or name.endswith("_close")
            or name.endswith("_shutdown"))


def _pass_only(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, ast.Pass) for s in handler.body)


def _falls_through(try_node: ast.Try) -> bool:
    """True when the try body's last statement exits the scope — the
    handler's ``pass`` then means "fall through to the next strategy"."""
    body = try_node.body
    return bool(body) and isinstance(body[-1],
                                     (ast.Return, ast.Continue, ast.Break))


@rule("R08", "swallowed-fault", "warning",
      "except handler swallows a fault with no re-raise, log, or counter")
def check_swallowed_fault(ctx: ModuleContext):
    r = get_rule("R08")
    parent_fn = enclosing_defs(ctx.tree)
    symbol_of = symbol_map(ctx)
    out = []
    for node in walk_tree(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        if _is_teardown(parent_fn.get(node)):
            continue
        if _falls_through(node):
            continue
        for handler in node.handlers:
            if not _pass_only(handler):
                continue
            out.append(make_finding(
                ctx, r, handler,
                "fault swallowed: this handler neither re-raises, logs, "
                "nor bumps a counter — the failure leaves no evidence",
                "record it (telemetry counter/event, logging, a flag the "
                "caller checks) or re-raise; pass-only is legitimate only "
                "in teardown (__del__/close) or fall-through probes",
                symbol_of.get(node, "<module>")))
    return out
