"""Measurement-honesty rules: R07 unfenced-device-timing, R09
nonmonotonic-span-clock, R12 gauge-shaped-latency, R14
jit-in-request-path.

JAX dispatch is asynchronous: a jitted call returns a future-like array
immediately and the device executes in the background.  So

    t0 = time.perf_counter()
    out = jitted_fn(x)
    dt = time.perf_counter() - t0        # measures DISPATCH, not compute

silently reports microseconds for seconds of device work — the classic
way a "10x speedup" enters a benchmark table and later evaporates.  The
fix is a fence between the dispatch and the second clock read:
``jax.block_until_ready(out)``, ``out.block_until_ready()``, or any
host materialization of the outputs (``np.asarray``, ``.item()``, ...).

R07 flags a ``perf_counter``/``time``/``monotonic`` delta whose window
contains a *provably jitted* call with no fence between that call and
the closing clock read.  "Provably jitted" is deliberately conservative
(the R02/R03 philosophy — silence over noise): the called name must be
bound from ``jax.jit(...)``/``shard_map(...)`` in this module (including
``self.<attr>`` assignments) or be a def the module traces.  Calling
``.lower()``/``.compile()`` ON a jitted object is synchronous AOT work,
not dispatch, and stays clean.
"""

from __future__ import annotations

import ast
import re

from .context import ModuleContext
from .engine import get_rule, iter_scopes, make_finding, rule, scope_nodes, walk_tree

_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic"}

# host materializations that force completion of pending device work.
# np.asarray & friends only fence the arrays THEY are given — but flagging
# any window with some materialization in it is the conservative choice
# (false silence beats false noise; the baseline handles true positives)
_FENCE_CALLS = {"jax.block_until_ready", "jax.device_get",
                "numpy.asarray", "numpy.array", "numpy.asanyarray"}
_FENCE_METHODS = {"block_until_ready", "item", "tolist", "numpy"}

# methods of a jitted object that do NOT dispatch it (AOT pipeline)
_NON_DISPATCH_ATTRS = {"lower", "compile", "trace", "eval_shape"}


def _is_clock_call(ctx: ModuleContext, node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and ctx.resolve(node.func) in _CLOCK_CALLS)


def _jit_binding_value(ctx: ModuleContext, value: ast.AST) -> bool:
    """Is this assigned value a jit/shard_map application (possibly
    wrapped, e.g. ``jax.jit(shard_map(...))``)?"""
    while isinstance(value, ast.Call):
        resolved = ctx.resolve(value.func)
        if resolved is not None and resolved.rsplit(".", 1)[-1] in (
                "jit", "pmap", "shard_map"):
            return True
        if not value.args:
            return False
        value = value.args[0]  # jax.jit(shard_map(body, ...)) nesting
    return False


def _jitted_names(ctx: ModuleContext) -> tuple[set[str], set[str]]:
    """Module-wide (plain names, attribute names) bound to jitted values:
    ``f = jax.jit(g)`` and ``self._step = jax.jit(...)``.  Attribute
    names are collected module-wide — cross-method ``self._step(...)``
    dispatch is the common engine idiom."""
    names: set[str] = set()
    attrs: set[str] = set()
    for node in walk_tree(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not _jit_binding_value(ctx, node.value):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                attrs.add(tgt.attr)
    # defs the module provably traces are dispatches too when called bare
    for fn in ctx.traced:
        name = getattr(fn, "name", None)
        if name:
            names.add(name)
    return names, attrs


def _call_kind(ctx: ModuleContext, node: ast.Call,
               jit_names: set[str], jit_attrs: set[str]) -> str | None:
    """"dispatch", "fence", or None for one Call node."""
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in _FENCE_METHODS and not node.args:
            return "fence"
        if ctx.resolve(func) in _FENCE_CALLS:
            return "fence"
        # self._generation_step(...) — dispatch; but .lower()/.compile()
        # ON a jitted attr is the synchronous AOT path
        if func.attr in jit_attrs and func.attr not in _NON_DISPATCH_ATTRS:
            return "dispatch"
        return None
    if isinstance(func, ast.Name):
        if ctx.resolve(func) in _FENCE_CALLS:
            return "fence"
        if func.id in jit_names:
            return "dispatch"
    return None


@rule("R07", "unfenced-device-timing", "warning",
      "wall-clock delta around a jitted call without a block_until_ready "
      "fence measures dispatch, not compute")
def check_unfenced_timing(ctx: ModuleContext):
    r = get_rule("R07")
    jit_names, jit_attrs = _jitted_names(ctx)
    out = []
    for symbol, scope in iter_scopes(ctx):
        starts: list[tuple[str, int]] = []  # (timer var, lineno)
        deltas: list[tuple[str, int, ast.AST]] = []  # (var, lineno, node)
        calls: list[tuple[str, int]] = []  # (kind, lineno)
        for node in scope_nodes(scope):
            if (isinstance(node, ast.Assign)
                    and _is_clock_call(ctx, node.value)):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        starts.append((tgt.id, node.lineno))
            elif (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and _is_clock_call(ctx, node.left)
                    and isinstance(node.right, ast.Name)):
                deltas.append((node.right.id, node.lineno, node))
            elif isinstance(node, ast.Call):
                kind = _call_kind(ctx, node, jit_names, jit_attrs)
                if kind is not None:
                    calls.append((kind, node.lineno))
        for var, d_line, d_node in deltas:
            t_lines = [ln for v, ln in starts if v == var and ln < d_line]
            if not t_lines:
                continue
            t_line = max(t_lines)  # nearest start of THIS window
            unfenced = None
            # same-line tie-break: dispatch before fence, so the idiom
            # `jitted(...).block_until_ready()` (fence wrapping dispatch
            # on one line) counts as fenced
            order = {"dispatch": 0, "fence": 1}
            for kind, c_line in sorted(
                    calls, key=lambda kc: (kc[1], order[kc[0]])):
                if not (t_line < c_line <= d_line):
                    continue
                if kind == "dispatch":
                    unfenced = c_line
                elif kind == "fence":
                    unfenced = None  # everything dispatched so far is fenced
            if unfenced is not None:
                out.append(make_finding(
                    ctx, r, d_node,
                    f"`{var}` delta spans a jitted dispatch (line "
                    f"{unfenced}) with no fence before the second clock "
                    "read — this measures async dispatch, not device "
                    "compute",
                    "call jax.block_until_ready(...) on the dispatched "
                    "outputs (or materialize them with np.asarray/.item()) "
                    "before taking the delta",
                    symbol))
    return out


# ---------------------------------------------------------------------
# R09: wall-clock (time.time) used for an elapsed-time measurement
# ---------------------------------------------------------------------
#
# ``time.time()`` is the WALL clock: NTP steps, leap smearing, and
# suspend/resume move it — backwards included.  Using it to time a span
# or age a within-process timestamp silently corrupts exactly the
# telemetry that perf gates and staleness watchdogs trust; the monotonic
# clocks (``time.perf_counter()``/``time.monotonic()``) exist for this.
#
# Wall time IS required when the timestamp crosses a process boundary
# (the heartbeat protocol: writer pid != reader pid, so no monotonic
# clock is shared — obs/recorder.py's ``age_s`` must stay wall-clock).
# The rule is therefore conservative: it only flags a delta whose BOTH
# ends are provably this module's own ``time.time()`` reads — a start
# bound from ``time.time()`` in the same scope (or a ``self.<attr>``
# assigned from it anywhere in the module) subtracted from a fresh
# ``time.time()`` call.  A start read from a file/dict (the heartbeat
# reader) is untyped and stays silent.

_WALL_CLOCK = "time.time"


def _is_wall_call(ctx: ModuleContext, node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and ctx.resolve(node.func) == _WALL_CLOCK)


@rule("R09", "nonmonotonic-span-clock", "warning",
      "time.time() delta measures elapsed time with the wall clock — "
      "NTP steps/suspend skew spans and ages; use time.perf_counter() "
      "or time.monotonic()")
def check_nonmonotonic_span_clock(ctx: ModuleContext):
    r = get_rule("R09")
    # self.<attr> = time.time() is collected module-wide: the serving/
    # supervisor idiom stamps the start in __init__ and takes the delta
    # in another method
    wall_attrs: set[str] = set()
    for node in walk_tree(ctx.tree):
        if isinstance(node, ast.Assign) and _is_wall_call(ctx, node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute):
                    wall_attrs.add(tgt.attr)
    out = []
    for symbol, scope in iter_scopes(ctx):
        wall_names: set[str] = set()
        deltas: list[ast.BinOp] = []
        for node in scope_nodes(scope):
            if isinstance(node, ast.Assign) and _is_wall_call(
                    ctx, node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        wall_names.add(tgt.id)
            elif (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and _is_wall_call(ctx, node.left)):
                deltas.append(node)
        for node in deltas:
            right = node.right
            start = None
            if isinstance(right, ast.Name) and right.id in wall_names:
                start = f"`{right.id}`"
            elif (isinstance(right, ast.Attribute)
                    and right.attr in wall_attrs):
                start = f"`self.{right.attr}`-style attribute"
            if start is not None:
                out.append(make_finding(
                    ctx, r, node,
                    f"elapsed time measured as time.time() minus {start} "
                    "(also bound from time.time()) — the wall clock can "
                    "step backwards under NTP/suspend, corrupting the "
                    "span/age",
                    "bind both ends to time.perf_counter() (spans) or "
                    "time.monotonic() (ages/deadlines); keep time.time() "
                    "only for timestamps that cross a process boundary",
                    symbol))
    return out


# ---------------------------------------------------------------------
# R12: a perf_counter/monotonic DURATION recorded through a gauge
# ---------------------------------------------------------------------
#
# A gauge is last-write-wins: ``hub.gauge("predict_ms", dt)`` keeps
# whichever batch happened to finish last, which is almost never the
# sample the tail lives in — a 5x slowdown on 1% of requests is
# invisible the moment the next normal batch overwrites it.  Durations
# belong in a streaming histogram (``hub.observe`` / ``hists.observe``,
# obs/hist.py), whose bucket counts keep every sample's contribution to
# p99.  The rule is conservative (the R02/R03 philosophy): it only
# flags a ``.gauge(...)`` call whose VALUE expression provably carries a
# monotonic-clock delta — the delta taken inline, or a name bound from
# ``time.perf_counter()/time.monotonic() - <start>`` in the same scope.
# Gauges of genuinely last-write facts (queue depth, ratios, sums
# re-derivable elsewhere) stay silent.

_MONO_CLOCK_CALLS = {"time.perf_counter", "time.monotonic"}


def _is_mono_clock_call(ctx: ModuleContext, node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and ctx.resolve(node.func) in _MONO_CLOCK_CALLS)


def _is_mono_delta(ctx: ModuleContext, node: ast.AST,
                   mono_names: set[str]) -> bool:
    """Is this expression a monotonic-clock delta (``clock() - x`` or
    ``now - t0`` with both sides clock-bound)?"""
    if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)):
        return False
    left_clock = (_is_mono_clock_call(ctx, node.left)
                  or (isinstance(node.left, ast.Name)
                      and node.left.id in mono_names))
    return left_clock


@rule("R12", "gauge-shaped-latency", "warning",
      "a perf_counter/monotonic duration recorded via a last-write-wins "
      "gauge destroys the tail — observe it into a histogram instead")
def check_gauge_shaped_latency(ctx: ModuleContext):
    r = get_rule("R12")
    out = []
    for symbol, scope in iter_scopes(ctx):
        mono_names: set[str] = set()   # t0 = time.perf_counter()
        delta_names: set[str] = set()  # dt = time.perf_counter() - t0
        gauges: list[ast.Call] = []
        for node in scope_nodes(scope):
            if isinstance(node, ast.Assign):
                if _is_mono_clock_call(ctx, node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            mono_names.add(tgt.id)
                elif _is_mono_delta(ctx, node.value, mono_names):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            delta_names.add(tgt.id)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "gauge"
                  and len(node.args) >= 2):
                gauges.append(node)
        for call in gauges:
            value = call.args[1]
            duration = None
            if _is_mono_delta(ctx, value, mono_names):
                duration = "an inline clock delta"
            else:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Name) and sub.id in delta_names:
                        duration = f"`{sub.id}` (a clock delta)"
                        break
                    if _is_mono_delta(ctx, sub, mono_names):
                        duration = "an inline clock delta"
                        break
            if duration is not None:
                out.append(make_finding(
                    ctx, r, call,
                    f"gauge value is {duration}: last-write-wins keeps "
                    "only the final sample, so the latency tail (the p99 "
                    "a shed or recompile ruins) is erased",
                    "record the duration with hists.observe(name, dt) "
                    "(obs/hist.py streaming histogram); keep gauges for "
                    "genuinely last-write facts like queue depth",
                    symbol))
    return out


# ---------------------------------------------------------------------
# R14: jax.jit constructed in a per-request/per-call scope
# ---------------------------------------------------------------------
#
# ``jax.jit(...)`` returns a WRAPPER whose compiled executables are
# cached ON THAT WRAPPER OBJECT.  Construct it once at load time and
# every call after the first reuses the executable; construct it inside
# a request handler or a dispatch loop and every single call traces and
# compiles from scratch — the serving-path recompile storm the warm-
# bundle machinery (serve/warm.py) exists to kill, re-introduced one
# innocent-looking line at a time.  The rule flags jit/pmap/shard_map
# APPLICATIONS (not calls of an already-jitted name) in the two shapes
# that are per-call by construction:
#
# * anywhere inside an HTTP handler method (``do_GET``/``do_POST``/…) —
#   stdlib http.server calls these once per request;
# * inside a ``for``/``while`` loop body, EXCEPT in recognized
#   load-time scopes where building a ladder of programs in a loop is
#   the legitimate idiom: module level, ``__init__``/``__post_init__``,
#   and builder-named functions (``build``/``init``/``setup``/``load``/
#   ``warm``/``compile``/``export``/``make`` in the name).
#
# Conservative by the R02/R03 philosophy: a jit constructed in a plain
# helper (called who-knows-how-often) stays silent — only provably
# per-request/per-iteration construction sites report.

_HANDLER_RE = re.compile(r"(^|\.)do_[A-Z]+$")
_SETUP_NAME_PARTS = ("build", "init", "setup", "load", "warm", "compile",
                     "export", "make")
_JIT_CTORS = ("jit", "pmap", "shard_map")


def _is_jit_ctor_call(ctx: ModuleContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = ctx.resolve(node.func)
    return (resolved is not None
            and resolved.rsplit(".", 1)[-1] in _JIT_CTORS)


def _loop_jit_calls(ctx: ModuleContext, loop: ast.AST):
    """jit-ctor calls inside one loop's per-iteration subtree, nested
    defs excluded (a def in a loop body is not executed per iteration's
    request).  A ``for``'s iterator/target evaluate ONCE, before the
    loop — `for f in (jax.jit(g),):` is construction, not per-iteration
    work — so only body/orelse are walked; a ``while``'s test re-runs
    every iteration and stays in scope."""
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        stack = list(loop.body) + list(loop.orelse)
    else:
        stack = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _is_jit_ctor_call(ctx, node):
            yield node
        stack.extend(ast.iter_child_nodes(node))


@rule("R14", "jit-in-request-path", "error",
      "jax.jit constructed inside a per-request/per-call scope recompiles "
      "on every call — hoist the jit to load time and reuse the wrapper")
def check_jit_in_request_path(ctx: ModuleContext):
    r = get_rule("R14")
    out = []
    seen: set[int] = set()

    def report(node: ast.AST, symbol: str, where: str) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        out.append(make_finding(
            ctx, r, node,
            f"jax.jit/pmap/shard_map constructed {where} — the compiled "
            "executable caches on the wrapper object, so constructing it "
            "per call means tracing + XLA-compiling per call",
            "construct the jitted callable once at load/init time (the "
            "server's engine build, __init__, a module-level builder) and "
            "call the stored wrapper here",
            symbol))

    for symbol, scope in iter_scopes(ctx):
        is_handler = bool(_HANDLER_RE.search(symbol))
        if is_handler:
            for node in scope_nodes(scope):
                if _is_jit_ctor_call(ctx, node):
                    report(node, symbol,
                           "inside an HTTP request handler (called once "
                           "per request)")
        name = symbol.rsplit(".", 1)[-1].lower()
        is_setup = (symbol == "<module>"
                    or name in ("__init__", "__post_init__")
                    or any(part in name for part in _SETUP_NAME_PARTS))
        if is_setup:
            continue
        for node in scope_nodes(scope):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                for call in _loop_jit_calls(ctx, node):
                    report(call, symbol,
                           "inside a loop body (recompile per iteration)")
    return out
