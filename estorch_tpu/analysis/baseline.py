"""Baseline (suppression) file: grandfathered findings, checked in.

The baseline lets the lint gate turn on strict TODAY while existing
findings are burned down deliberately: a finding whose identity
``(rule, file, symbol, snippet)`` appears in the baseline is suppressed;
a baseline entry matching nothing is reported STALE so fixed findings
cannot leave dead suppressions behind (the round-trip
``tests/test_analysis.py`` exercises exactly that cycle).

Every entry carries a human ``reason`` — a baseline is a justified debt
ledger, not a mute button.  Identity is line-number-free on purpose:
editing code above a grandfathered finding must not invalidate it.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .findings import Finding


def _norm(path: str) -> str:
    return os.path.normpath(path).replace(os.sep, "/")


@dataclass(frozen=True)
class BaselineEntry:
    rule: str
    file: str
    symbol: str
    snippet: str
    reason: str = ""

    def key(self) -> tuple[str, str, str, str]:
        return (self.rule, _norm(self.file), self.symbol, self.snippet)


@dataclass
class ApplyResult:
    unsuppressed: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale: list[BaselineEntry] = field(default_factory=list)


@dataclass
class Baseline:
    entries: list[BaselineEntry] = field(default_factory=list)

    def apply(self, findings: list[Finding]) -> ApplyResult:
        by_key = {e.key(): e for e in self.entries}
        res = ApplyResult()
        matched: set[tuple] = set()
        for f in findings:
            k = (f.rule, _norm(f.file), f.symbol, f.snippet)
            if k in by_key:
                matched.add(k)
                res.suppressed.append(f)
            else:
                res.unsuppressed.append(f)
        res.stale = [e for e in self.entries if e.key() not in matched]
        return res

    def unjustified(self) -> list[BaselineEntry]:
        return [e for e in self.entries if not e.reason.strip()]


def load_baseline(path: str) -> Baseline:
    """Missing file -> empty baseline (strict-by-default for new repos)."""
    if not os.path.exists(path):
        return Baseline()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    entries = []
    for raw in data.get("entries", []):
        entries.append(BaselineEntry(
            rule=raw["rule"], file=raw["file"], symbol=raw["symbol"],
            snippet=raw["snippet"], reason=raw.get("reason", "")))
    return Baseline(entries)


def save_baseline(path: str, findings: list[Finding],
                  reason: str = "") -> Baseline:
    """Write findings as baseline entries.  The default ``reason`` is
    EMPTY on purpose: auto-written entries report as UNJUSTIFIED until a
    human edits in why each one is allowed to stay."""
    entries = []
    seen: set[tuple] = set()
    for f in findings:
        k = (f.rule, _norm(f.file), f.symbol, f.snippet)
        if k in seen:
            continue
        seen.add(k)
        entries.append(BaselineEntry(
            rule=f.rule, file=_norm(f.file), symbol=f.symbol,
            snippet=f.snippet, reason=reason))
    payload = {
        "version": 1,
        "entries": [vars(e) for e in entries],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return Baseline(entries)
