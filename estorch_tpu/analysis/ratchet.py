"""Findings-count ratchet: per-rule debt that can only shrink.

The baseline answers "which EXACT findings are grandfathered"; the
ratchet answers a coarser question the lockset rules need: "how many
findings is each rule allowed, total?"  Identity-keyed baselining is
too brittle for race findings — refactoring a guarded region moves the
snippet and would force a baseline edit even when the debt is unchanged
— so CI pins a committed per-rule count instead:

* more findings than the recorded count -> regression, exit 1.  New
  race debt cannot land, full stop.
* fewer findings than the recorded count -> STALE, exit 2.  Whoever
  fixed a race must also lower the recorded count (``--write-ratchet``)
  so the improvement is locked in and cannot silently regress later.
* equal -> quiet.

The ratchet file is JSON, checked in next to the baseline::

    {"version": 1, "counts": {"R18": 0, "R19": 0, ...}}

Only rules listed in ``counts`` are ratcheted; other rules stay on the
identity baseline.  ``--changed`` runs skip the ratchet entirely — a
partial tree undercounts everything and would report every rule stale.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .findings import Finding


@dataclass
class RatchetResult:
    # (rule, recorded, actual) — actual > recorded: new debt, exit 1
    regressions: list[tuple[str, int, int]] = field(default_factory=list)
    # (rule, recorded, actual) — actual < recorded: lower the count
    stale: list[tuple[str, int, int]] = field(default_factory=list)

    def ok(self) -> bool:
        return not self.regressions and not self.stale


def count_findings(findings: list[Finding],
                   rule_ids: list[str]) -> dict[str, int]:
    counts = {rid: 0 for rid in rule_ids}
    for f in findings:
        if f.rule in counts:
            counts[f.rule] += 1
    return counts


def load_ratchet(path: str) -> dict[str, int]:
    """Missing file -> empty ratchet (nothing pinned, nothing checked)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("counts", {}).items()}


def save_ratchet(path: str, counts: dict[str, int]) -> None:
    payload = {"version": 1, "counts": dict(sorted(counts.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def check_ratchet(recorded: dict[str, int],
                  findings: list[Finding]) -> RatchetResult:
    """Compare actual per-rule totals against the recorded ceiling for
    every ratcheted rule.  Findings are counted whether or not the
    baseline suppressed them — the ratchet bounds TOTAL debt."""
    actual = count_findings(findings, list(recorded))
    res = RatchetResult()
    for rid in sorted(recorded):
        have, allow = actual[rid], recorded[rid]
        if have > allow:
            res.regressions.append((rid, allow, have))
        elif have < allow:
            res.stale.append((rid, allow, have))
    return res
