"""Per-module analysis context: AST, import aliases, traced functions.

Built once per file and shared by every rule, so each rule stays a small
visitor instead of re-deriving "is this call jax.random.split?" or "does
this function body get traced?" on its own.

Traced-function detection (the "hot path" of R02/R03) is deliberately
conservative: a function counts as traced only when the module gives
static evidence —

* decorated with ``jit``/``vmap``/``pmap``/``shard_map`` (bare,
  dotted, or wrapped in ``partial(jax.jit, ...)``), or
* its NAME is passed to a tracing entry point in the same module
  (``jax.jit(f)``, ``jax.vmap(f)``, ``jax.lax.scan(f, ...)``, ...), or
* it is lexically nested inside a traced function (a ``step_fn``
  defined inside a jitted body is traced with it).

Anything the analyzer cannot prove traced is treated as host code —
missed hazards are acceptable, false "host sync in hot path" noise on
plain Python is not.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

# call/decorator heads that trace their function argument
TRACING_ENTRY_POINTS = {
    "jit", "vmap", "pmap", "shard_map", "checkpoint", "remat",
    "scan", "while_loop", "fori_loop", "cond", "switch", "custom_jvp",
    "custom_vjp", "grad", "value_and_grad",
}
# of those, the ones that take the traced callable as FIRST positional arg
_CALLABLE_FIRST = TRACING_ENTRY_POINTS - {"fori_loop", "cond", "switch"}
# names distinctive enough that ANY dotted/imported source counts as
# tracing — this is what lets the analyzer see through local compat shims
# like utils/backend.py::shard_map.  Generic names (scan, cond, checkpoint,
# grad, ...) collide with ordinary host code and stay jax/flax/chex-only.
_DISTINCTIVE_TAILS = {"jit", "vmap", "pmap", "shard_map"}


def dotted_name(node: ast.AST) -> str | None:
    """``jax.lax.scan`` -> "jax.lax.scan"; None for non-name expressions."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class ModuleContext:
    path: str
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    # import alias -> canonical dotted path ("jr" -> "jax.random")
    aliases: dict[str, str] = field(default_factory=dict)
    # function name -> def nodes with that name (module-wide, by name)
    defs_by_name: dict[str, list[ast.AST]] = field(default_factory=dict)
    # def nodes whose bodies are traced (see module docstring)
    traced: set[ast.AST] = field(default_factory=set)
    # def node -> enclosing qualname ("Engine._step.body")
    qualnames: dict[ast.AST, str] = field(default_factory=dict)
    # every call-valued Assign with its nearest enclosing class name —
    # the lockset layer scans these for Lock()/RLock()/... factories
    # without re-walking the tree
    call_assigns: list[tuple[ast.Assign, str]] = field(default_factory=list)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # node -> resolved path: a dozen rules re-resolve the same call
    # heads, and the dotted-name walk is pure per-node work
    _resolve_cache: dict[ast.AST, str | None] = field(default_factory=dict)

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted path of a name/attribute expression, expanding
        the module's import aliases: with ``import jax.random as jr``,
        ``jr.split`` resolves to "jax.random.split"."""
        try:
            return self._resolve_cache[node]
        except KeyError:
            pass
        dotted = dotted_name(node)
        if dotted is None:
            out = None
        else:
            head, _, rest = dotted.partition(".")
            canon = self.aliases.get(head, head)
            out = canon + ("." + rest if rest else "")
        self._resolve_cache[node] = out
        return out

    def is_traced(self, fn: ast.AST) -> bool:
        return fn in self.traced


def _record_alias(node: ast.AST, aliases: dict[str, str]) -> None:
    if isinstance(node, ast.Import):
        for a in node.names:
            aliases[a.asname or a.name.partition(".")[0]] = (
                a.name if a.asname else a.name.partition(".")[0])
    elif isinstance(node, ast.ImportFrom):
        # relative imports keep their dots ("..utils.backend.shard_map")
        # — unresolvable to an absolute module, but enough for the
        # distinctive-tail rule to see through in-repo shims
        prefix = "." * node.level + (node.module or "")
        for a in node.names:
            aliases[a.asname or a.name] = (
                f"{prefix}.{a.name}" if prefix else a.name)


_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_tracing_head(ctx: ModuleContext, func: ast.AST) -> bool:
    resolved = ctx.resolve(func)
    if resolved is None:
        return False
    tail = resolved.rsplit(".", 1)[-1]
    if tail not in TRACING_ENTRY_POINTS:
        return False
    # provably jax/flax/chex: `from jax import jit` arrives here as
    # "jax.jit" via the alias map.  A BARE surviving name is a module-local
    # helper that happens to be called scan/cond/checkpoint — treating it
    # as tracing would flag pure host code (false R02/R03)
    head = resolved.split(".", 1)[0]
    if head in ("jax", "flax", "chex"):
        return True
    # distinctive tails (jit/vmap/pmap/shard_map) also count when they
    # arrive through ANY import or dotted attribute — version-compat shims
    # (`from ..utils.backend import shard_map`) must not blind the rules
    # to the hot bodies they wrap
    return tail in _DISTINCTIVE_TAILS and "." in resolved


def _decorator_traces(ctx: ModuleContext, dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @jax.jit(static_argnums=...)
        if _is_tracing_head(ctx, dec.func):
            return True
        head = ctx.resolve(dec.func)
        if head is not None and head.rsplit(".", 1)[-1] == "partial":
            return bool(dec.args) and _is_tracing_head(ctx, dec.args[0])
        return False
    return _is_tracing_head(ctx, dec)


def build_context(path: str, source: str) -> ModuleContext:
    tree = ast.parse(source, filename=path)
    ctx = ModuleContext(path=path, source=source, tree=tree,
                        lines=source.splitlines())

    # ---- single structural pass --------------------------------------
    # One recursive traversal collects import aliases, qualnames,
    # defs_by_name, the lexical-parent-function map, and every Call node
    # (tracing heads are filtered AFTER the walk, once aliases are
    # complete).  parent_fn matters twice: name references at a tracing
    # call site resolve against the call's enclosing scope chain, not
    # module-wide — an unrelated host function that happens to share a
    # closure name like `body`/`step_fn` must not become traced — and it
    # is the same map engine.enclosing_defs serves to the rules, so it
    # is cached on the tree here instead of being rebuilt there.
    parent_fn: dict[ast.AST, ast.AST | None] = {}
    calls: list[ast.Call] = []

    def walk(node: ast.AST, prefix: str, fn: ast.AST | None,
             cls: str) -> None:
        for child in ast.iter_child_nodes(node):
            parent_fn[child] = fn
            if isinstance(child, _FN_NODES):
                qn = f"{prefix}{child.name}"
                ctx.qualnames[child] = qn
                ctx.defs_by_name.setdefault(child.name, []).append(child)
                walk(child, qn + ".", child, cls)
                continue
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.", fn, child.name)
                continue
            if isinstance(child, ast.Call):
                calls.append(child)
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                _record_alias(child, ctx.aliases)
            elif isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call):
                ctx.call_assigns.append((child, cls))
            walk(child, prefix, fn, cls)

    walk(tree, "", None, "")
    tree._esguard_parent_fn = parent_fn

    def resolve_local_def(call: ast.Call, name: str) -> ast.AST | None:
        chain = []
        scope = parent_fn.get(call)
        while scope is not None:
            chain.append(scope)
            scope = parent_fn.get(scope)
        chain.append(None)  # module scope
        candidates = ctx.defs_by_name.get(name, [])
        for scope in chain:  # innermost enclosing scope wins
            for fn in candidates:
                if parent_fn.get(fn) is scope:
                    return fn
        return None

    for fn in ctx.qualnames:
        for dec in getattr(fn, "decorator_list", []):
            if _decorator_traces(ctx, dec):
                ctx.traced.add(fn)
    for node in calls:
        if _is_tracing_head(ctx, node.func):
            resolved = ctx.resolve(node.func) or ""
            if resolved.rsplit(".", 1)[-1] in _CALLABLE_FIRST:
                cand = node.args[:1]
            else:  # fori_loop/cond/switch: any callable argument
                cand = list(node.args)
            for arg in cand:
                if isinstance(arg, ast.Name):
                    fn = resolve_local_def(node, arg.id)
                    if fn is not None:
                        ctx.traced.add(fn)

    # ---- propagate into lexically nested defs ------------------------
    def mark_nested(fn: ast.AST) -> None:
        for child in ast.walk(fn):
            if child is not fn and isinstance(child, _FN_NODES):
                ctx.traced.add(child)

    for fn in list(ctx.traced):
        mark_nested(fn)
    return ctx
