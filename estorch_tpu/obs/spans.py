"""Span telemetry: low-overhead phase timers for the training loop.

A *span* is one timed phase of a generation — ``sample`` / ``eval`` /
``update`` on the host and pooled backends, ``dispatch`` / ``device`` /
``host_sync`` on the fused device path (whose single XLA program cannot
be split finer without de-fusing it; docs/observability.md has the full
taxonomy).  Spans nest: a phase entered inside another is recorded under
``parent/child`` (e.g. ``update/obsnorm_merge``), and the parent's time
includes its children — per-phase *share* therefore sums top-level names
only.

Device honesty: wall-clocking an async-dispatched jitted call measures
dispatch, not compute (esguard R07).  Every device span either contains
its own materialization (``np.asarray`` of an output) or passes
``fence=`` — a callable run before the clock stops, typically
``jax.block_until_ready`` on the program's outputs.

Overhead budget: a disabled Telemetry's ``phase()`` yields a cached
no-op context manager (two attribute loads); an enabled one costs two
``perf_counter`` calls + dict update per span.  Heartbeat/file work only
happens when a heartbeat path is configured (supervisors opt in via the
``ESTORCH_OBS_HEARTBEAT`` env var).  Measured A/B: default-on spans are
<2% of bench wall time (BENCHMARKS.md).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from .counters import Counters, NullCounters
from .hist import Histograms, NullHistograms
from .profile.ledger import CompileLedger, ledger_counters
from .recorder import HEARTBEAT_ENV, FlightRecorder, Heartbeat

OBS_DISABLE_ENV = "ESTORCH_OBS"  # "0" disables default-on telemetry

# shared stateless no-op context manager: the disabled path costs one
# attribute check + one return, no generator construction per span
_NULL_CM = contextlib.nullcontext()


class Telemetry:
    """Per-run telemetry hub: spans + counters + flight recorder + heartbeat.

    One instance rides each ``ES`` (``es.obs``); engines receive it as
    their ``telemetry`` attribute so sub-generation phases land in the
    same accumulator the train loop flushes into the generation record.
    """

    def __init__(self, enabled: bool = True,
                 heartbeat_path: str | None = None,
                 recorder_capacity: int = 512):
        self.enabled = bool(enabled)
        # disabled hubs swallow counter writes too — engines inc
        # unconditionally, and the shared NULL_TELEMETRY default must
        # never aggregate state across unrelated engines (see NullCounters)
        self.counters = Counters() if self.enabled else NullCounters()
        # streaming histograms (obs/hist.py): the distribution-shaped
        # facts — queue waits, per-phase durations, staleness — that
        # counters/gauges erase; same inert-when-disabled contract
        self.hists = Histograms() if self.enabled else NullHistograms()
        self.recorder = FlightRecorder(recorder_capacity)
        self.heartbeat = Heartbeat(heartbeat_path) if heartbeat_path else None
        self.generation = 0
        # span nesting is PER THREAD (the overlap scheduler runs the
        # engine's sample/eval/update spans from a background thread
        # while the main thread records host_sync/record — one shared
        # stack would interleave their pushes/pops into bogus names
        # like "async/dispatch/eval"); the accumulator is shared and
        # lock-guarded so both threads' spans land in the same record
        self._acc: dict[str, float] = {}
        self._acc_lock = threading.Lock()
        self._tls = threading.local()
        # performance-attribution facts (obs/profile/): the per-program
        # compile ledger and the run's analytic cost model — engines feed
        # the first, ES sets the second, `obs profile` joins them
        self.compile_ledger = CompileLedger()
        self.cost_model: dict | None = None

    # ------------------------------------------------------------- factory

    @classmethod
    def from_env(cls) -> "Telemetry":
        """Default-on construction honoring the env-var protocol:
        ``ESTORCH_OBS=0`` disables, ``ESTORCH_OBS_HEARTBEAT=<path>``
        (set by supervisors like bench.py stages) enables the heartbeat
        file."""
        enabled = os.environ.get(OBS_DISABLE_ENV, "1") != "0"
        hb = os.environ.get(HEARTBEAT_ENV) or None
        return cls(enabled=enabled, heartbeat_path=hb if enabled else None)

    # --------------------------------------------------------------- spans

    def phase(self, name: str, fence=None):
        """Time one phase; ``fence()`` (if given) runs before the clock
        stops — pass a ``block_until_ready`` closure for device work."""
        if not self.enabled:
            return _NULL_CM
        return self._phase_cm(name, fence)

    @property
    def _stack(self) -> list[str]:
        """This thread's span-nesting stack (see __init__)."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    @contextlib.contextmanager
    def _phase_cm(self, name: str, fence):
        stack = self._stack
        full = f"{stack[-1]}/{name}" if stack else name
        stack.append(full)
        if self.heartbeat is not None:
            # beat on ENTRY: a wedge inside this phase leaves its name —
            # not the previous phase's — as the last-known state
            self.heartbeat.beat(full, self.generation,
                                self.counters.snapshot(),
                                hists=self.hists.snapshot(compact=True))
        t0 = time.perf_counter()
        try:
            yield
            if fence is not None:
                fence()
        finally:
            dt = time.perf_counter() - t0
            stack.pop()
            with self._acc_lock:
                self._acc[full] = self._acc.get(full, 0.0) + dt
            # per-phase duration DISTRIBUTION, not just the sum: the
            # accumulator's per-generation total is what records carry,
            # the histogram is what `obs regress --tail` gates on
            self.hists.observe("phase/" + full, dt)
            trace = getattr(self._tls, "trace", None)
            if trace is not None:
                self.recorder.add("span", full, dur_s=dt,
                                  generation=self.generation, trace=trace)
            else:
                self.recorder.add("span", full, dur_s=dt,
                                  generation=self.generation)

    # ------------------------------------------------------------- traces

    @contextlib.contextmanager
    def trace_ctx(self, trace_id: str):
        """Causal identity for spans/events: everything recorded inside
        this context carries ``trace=trace_id`` into the flight recorder
        (serve request ids, async dispatch ids — docs/observability.md
        "Tails & traces").  Thread-local, like span nesting."""
        prev = getattr(self._tls, "trace", None)
        self._tls.trace = trace_id
        try:
            yield
        finally:
            self._tls.trace = prev

    def observe(self, name: str, value: float, n: int = 1,
                exemplar: str | None = None, **ladder) -> None:
        """Record ``n`` observations into the named streaming histogram
        (obs/hist.py; ladder kwargs apply on first observe only;
        ``exemplar`` attaches a trace id to the value's bucket)."""
        self.hists.observe(name, value, n, exemplar=exemplar, **ladder)

    def take_phases(self) -> dict[str, float]:
        """Flush this generation's span accumulator (merged into the
        generation record) and advance the generation counter."""
        if not self.enabled:
            return {}
        with self._acc_lock:
            out = {k: round(v, 6) for k, v in self._acc.items()}
            self._acc.clear()
        self.generation += 1
        self.counters.inc("generations")
        self.counters.sample_peak_rss()
        if self.heartbeat is not None:
            self.heartbeat.beat("between_generations", self.generation,
                                self.counters.snapshot(),
                                hists=self.hists.snapshot(compact=True))
        return out

    def discard_phases(self) -> None:
        """Drop accumulated spans without emitting them.  Train loops
        call this on entry: a generation that aborted mid-phase (dead
        env raising through the loop — the documented catch-and-resume
        contract) leaves partial spans behind, which must not be merged
        into the next successful generation's record.  The flight
        recorder keeps the aborted spans for post-mortems."""
        with self._acc_lock:
            self._acc.clear()

    def note(self, phase: str) -> None:
        """Heartbeat-only marker for long un-spanned stretches (backend
        init, XLA compile): a wedge there should still leave a last-known
        phase behind, without polluting the span accumulator."""
        if self.enabled and self.heartbeat is not None:
            self.heartbeat.beat(phase, self.generation,
                                self.counters.snapshot(),
                                hists=self.hists.snapshot(compact=True))

    # ------------------------------------------------- compile ledger

    def set_cost_model(self, model: dict | None) -> None:
        """Attach the run's analytic FLOPs/bytes model (obs/profile/
        costmodel.py); ES writes it into the generation-0 record so
        ``obs profile`` can turn phase seconds into achieved rates."""
        if self.enabled:
            self.cost_model = dict(model) if model else None

    def compile_event(self, program: str, dur_s: float, compiled=None,
                      count_recompiles: int = 1, **extra):
        """Record one program compile: ledger entry (+ XLA cost facts
        duck-typed off ``compiled`` when given), ``recompiles`` counter
        (``count_recompiles`` programs — 0 when the caller counts its
        own), per-program registry gauges for /metrics, and a flight-
        recorder event.  Thread-safe primitives only (the serving
        batcher records from its worker thread)."""
        if not self.enabled:
            return None
        from .profile.costmodel import compiled_cost_facts

        facts = compiled_cost_facts(compiled) if compiled is not None else {}
        entry = self.compile_ledger.record(
            program, dur_s, generation=self.generation, **facts, **extra)
        if count_recompiles:
            self.counters.inc("recompiles", count_recompiles)
        # cumulative compile seconds across the run's programs (gauge:
        # re-derivable from the ledger, last-write-wins by design)
        self.counters.gauge("compile_time_s", round(sum(
            e.get("compile_s", 0.0) for e in self.compile_ledger.entries()),
            6))
        for name, value in ledger_counters([entry]).items():
            self.counters.gauge(name, value)
        self.recorder.add("event", "compile", generation=self.generation,
                          program=program, dur_s=dur_s)
        return entry

    def take_compile_events(self) -> list[dict]:
        """Ledger entries recorded since the last flush — merged into the
        generation record as ``compile_events`` (obs profile / obs trace
        read them back)."""
        if not self.enabled:
            return []
        return self.compile_ledger.take_new()

    # -------------------------------------------------------------- events

    def event(self, name: str, **extra) -> None:
        """Record a non-span event (compile, retry, error) in the ring.
        The current :meth:`trace_ctx` id rides along unless the caller
        passed its own ``trace=``."""
        if self.enabled:
            trace = getattr(self._tls, "trace", None)
            if trace is not None and "trace" not in extra:
                extra["trace"] = trace
            self.recorder.add("event", name, generation=self.generation,
                              **extra)


class _NullTelemetry(Telemetry):
    """Shared disabled instance — the default ``telemetry`` attribute of
    every engine, so instrumented code never branches on None."""

    def __init__(self):
        super().__init__(enabled=False)


NULL_TELEMETRY = _NullTelemetry()


def resolve_telemetry(telemetry) -> Telemetry:
    """ES's ``telemetry=`` kwarg → a Telemetry: None → env-driven
    default-on, bool → forced on/off, instance → as-is."""
    if telemetry is None:
        return Telemetry.from_env()
    if isinstance(telemetry, Telemetry):
        return telemetry
    if telemetry is True:
        return Telemetry(enabled=True,
                         heartbeat_path=os.environ.get(HEARTBEAT_ENV) or None)
    if telemetry is False:
        return Telemetry(enabled=False)
    raise TypeError(
        f"telemetry must be None, a bool, or a Telemetry, got {telemetry!r}")
