"""Streaming histograms: the tail-latency truth the flat registry can't hold.

Counters sum and gauges overwrite — both erase the *distribution*, and
at serving/async scale the distribution IS the product: a shed or a
recompile ruins 1% of requests without moving any mean, and a gauge like
``batch_predict_ms_last`` (esguard R12 ``gauge-shaped-latency``) keeps
whichever value was written last, which is precisely the sample the tail
lives in.  This module is the stdlib answer:

* **fixed log-spaced bucket ladder** — buckets at ratio
  ``r = 10^(1/per_decade)`` from ``lo`` upward, plus an underflow bucket
  (≤ ``lo``) and a +Inf overflow bucket.  Two histograms built with the
  same parameters always share bucket edges, which is what makes them
  mergeable across threads, processes, and restarts without resampling;
* **exact small-N path** — the first ``exact_cap`` (default 256) raw
  observations are kept verbatim, so quantiles of a short run are
  *exact* (nearest-rank), not bucket-approximate.  Past the cap the raw
  list is dropped and quantiles come from the ladder;
* **documented error bound** — a bucket-path quantile is the geometric
  midpoint of its bucket, so for values inside ``[lo, hi]`` the relative
  error is at most ``sqrt(r) - 1`` (~10% at the default 12
  buckets/decade); ``quantile_error_bound()`` returns the conservative
  one-bucket bound ``r - 1`` that tests and the honesty gate use;
* **mergeable + serializable** — ``merge`` is associative and
  commutative on same-ladder histograms; ``to_dict``/``from_dict`` round
  trip through JSON (sparse counts), which is how histograms ride
  heartbeats and the sidecar's cross-restart ``counters.json``
  composition (:func:`merge_snapshots`);
* **inert when disabled** — :class:`NullHistograms` swallows observes,
  mirroring ``NullCounters``: engine code never branches on the hub's
  state, and the shared NULL_TELEMETRY default must not aggregate
  distributions across unrelated engines.

Deliberately stdlib-only and importable WITHOUT the package (the metrics
sidecar loads it by file path, like ``recorder.py``) — a wedged-jax host
must still be able to compose and serve histogram scrapes.
"""

from __future__ import annotations

import math
import threading

HIST_SCHEMA = 1

# default ladder: 10µs .. 10^3 s at 12 buckets/decade — spans queue
# waits (µs) through chaos-straggler stalls (minutes) with a ~10%
# geometric-midpoint quantile error (sqrt(10^(1/12)) - 1)
DEFAULT_LO = 1e-5
DEFAULT_DECADES = 8
DEFAULT_PER_DECADE = 12
DEFAULT_EXACT_CAP = 256
# per-bucket exemplar capacity: the last K trace ids observed into each
# bucket (docs/observability.md "Distributed tracing") — enough to name
# a tail sample, small enough to ride every snapshot
DEFAULT_EXEMPLAR_K = 4


class Histogram:
    """One thread-safe streaming histogram (see module docstring)."""

    def __init__(self, lo: float = DEFAULT_LO,
                 decades: int = DEFAULT_DECADES,
                 per_decade: int = DEFAULT_PER_DECADE,
                 exact_cap: int = DEFAULT_EXACT_CAP):
        if lo <= 0:
            raise ValueError(f"lo must be > 0, got {lo}")
        if decades < 1 or per_decade < 1:
            raise ValueError(
                f"decades/per_decade must be >= 1, got {decades}/"
                f"{per_decade}")
        self.lo = float(lo)
        self.per_decade = int(per_decade)
        self.n = int(decades) * int(per_decade)  # finite upper edges
        self.exact_cap = int(exact_cap)
        self._lock = threading.Lock()
        # counts[0] = underflow (<= lo); counts[i] = (bound[i-1], bound[i]]
        # for 1 <= i <= n; counts[n+1] = overflow (> bound[n-1], i.e. +Inf)
        self._counts = [0] * (self.n + 2)
        self._count = 0
        self._sum = 0.0
        self._exact: list[float] | None = []
        # bucket index → last K exemplar trace ids (newest last); only
        # buckets that ever saw an exemplar have a key
        self._exemplars: dict[int, list[str]] = {}

    # ------------------------------------------------------------ ladder

    def bound(self, i: int) -> float:
        """Upper edge of finite bucket ``i`` (0 = the underflow edge
        ``lo``; ``i`` in [0, n])."""
        return self.lo * 10.0 ** (i / self.per_decade)

    def _index(self, v: float) -> int:
        if v <= self.lo:
            return 0
        # ceil with a tiny epsilon so v == bound(k) lands in bucket k
        # (le semantics) despite float log noise
        e = math.log10(v / self.lo) * self.per_decade
        return min(self.n + 1, max(1, math.ceil(e - 1e-9)))

    def quantile_error_bound(self) -> float:
        """Conservative relative error of a bucket-path quantile for
        values inside the ladder: one full bucket ratio, ``r - 1``."""
        return 10.0 ** (1.0 / self.per_decade) - 1.0

    # ----------------------------------------------------------- observe

    def observe(self, value: float, n: int = 1,
                exemplar: str | None = None) -> None:
        """Record ``n`` observations of ``value`` (the weighted form
        serves per-batch costs shared by every coalesced request).
        ``exemplar`` attaches a trace id to the value's bucket — the
        last :data:`DEFAULT_EXEMPLAR_K` per bucket survive, so a tail
        bucket can NAME recent requests that landed in it."""
        v = float(value)
        if not math.isfinite(v) or n < 1:
            return
        i = self._index(v)
        with self._lock:
            self._counts[i] += n
            self._count += n
            self._sum += v * n
            if self._exact is not None:
                if self._count <= self.exact_cap:
                    self._exact.extend([v] * n)
                else:
                    self._exact = None  # past the cap: ladder-only
            if exemplar:
                ids = self._exemplars.setdefault(i, [])
                ids.append(str(exemplar))
                del ids[:-DEFAULT_EXEMPLAR_K]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    # ---------------------------------------------------------- quantile

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile: exact while the raw list survives
        (count ≤ exact_cap), else the geometric midpoint of the bucket
        holding the rank.  The overflow bucket has no upper edge, so a
        rank landing there returns the ladder's top edge — a documented
        UNDERestimate (size the ladder to the workload).  NaN when
        empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0:
                return float("nan")
            if self._exact is not None:
                s = sorted(self._exact)
                k = max(1, math.ceil(q * len(s)))
                return s[k - 1]
            k = max(1, math.ceil(q * self._count))
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= k:
                    break
            if i == 0:
                # underflow: midpoint half a bucket below lo
                return self.lo * 10.0 ** (-0.5 / self.per_decade)
            if i >= self.n + 1:
                return self.bound(self.n)
            return math.sqrt(self.bound(i - 1) * self.bound(i))

    # --------------------------------------------------------- exemplars

    def exemplars(self) -> dict[int, list[str]]:
        """Copy of the per-bucket exemplar ids (bucket index → newest
        last)."""
        with self._lock:
            return {i: list(ids) for i, ids in self._exemplars.items()
                    if ids}

    def slow_exemplars(self, q: float = 0.99) -> list[str]:
        """Exemplar trace ids from buckets AT OR ABOVE the bucket holding
        quantile ``q`` — slowest bucket first, newest first within a
        bucket, deduplicated.  How a p99 breach gets a NAME."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0 or not self._exemplars:
                return []
            k = max(1, math.ceil(q * self._count))
            cum = 0
            qi = len(self._counts) - 1
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= k:
                    qi = i
                    break
            out: list[str] = []
            for i in sorted(self._exemplars, reverse=True):
                if i < qi:
                    break
                for tid in reversed(self._exemplars[i]):
                    if tid not in out:
                        out.append(tid)
            return out

    # ------------------------------------------------------------- merge

    def _same_ladder(self, other: "Histogram") -> bool:
        return (self.lo == other.lo and self.per_decade == other.per_decade
                and self.n == other.n)

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into self (in place; returns self).  Raises on
        a ladder mismatch — bucket-wise addition across different edges
        would silently fabricate a distribution."""
        if not self._same_ladder(other):
            raise ValueError(
                f"ladder mismatch: (lo={self.lo}, per_decade="
                f"{self.per_decade}, n={self.n}) vs (lo={other.lo}, "
                f"per_decade={other.per_decade}, n={other.n})")
        with other._lock:
            o_counts = list(other._counts)
            o_count, o_sum = other._count, other._sum
            o_exact = None if other._exact is None else list(other._exact)
            o_ex = {i: list(ids) for i, ids in other._exemplars.items()}
        with self._lock:
            for i, c in enumerate(o_counts):
                self._counts[i] += c
            self._count += o_count
            self._sum += o_sum
            if (self._exact is not None and o_exact is not None
                    and self._count <= self.exact_cap):
                self._exact.extend(o_exact)
            else:
                self._exact = None
            for i, ids in o_ex.items():
                mine = self._exemplars.setdefault(i, [])
                mine.extend(ids)
                del mine[:-DEFAULT_EXEMPLAR_K]
        return self

    # --------------------------------------------------------- serialize

    def to_dict(self, compact: bool = False) -> dict:
        """JSON-able snapshot (sparse counts keyed by bucket index).

        ``compact`` drops the raw ``exact`` list — the shape heartbeats
        carry, where re-serializing up to ``exact_cap`` floats per hist
        on every beat would tax a hot path for a list only small-N
        quantile EXACTNESS (not correctness) needs; a compact snapshot
        round-trips as bucket-only, inside the documented bound."""
        with self._lock:
            return {
                "schema": HIST_SCHEMA,
                "lo": self.lo,
                "per_decade": self.per_decade,
                "n": self.n,
                "count": self._count,
                "sum": self._sum,
                "counts": {str(i): c for i, c in enumerate(self._counts)
                           if c},
                **({"exact": list(self._exact)}
                   if self._exact is not None and not compact else {}),
                # exemplars ride BOTH shapes: ≤ K short ids per touched
                # bucket is heartbeat-cheap, and the /traces scrape path
                # only ever sees compact snapshots
                **({"exemplars": {str(i): list(ids) for i, ids in
                                  self._exemplars.items() if ids}}
                   if self._exemplars else {}),
            }

    @classmethod
    def from_dict(cls, data: dict) -> "Histogram":
        if data.get("schema") != HIST_SCHEMA:
            raise ValueError(
                f"unknown histogram schema {data.get('schema')!r}")
        per_decade = int(data["per_decade"])
        n = int(data["n"])
        if n % per_decade:
            raise ValueError(f"n {n} not a multiple of per_decade "
                             f"{per_decade}")
        h = cls(lo=float(data["lo"]), decades=n // per_decade,
                per_decade=per_decade)
        for key, c in (data.get("counts") or {}).items():
            i = int(key)
            if not 0 <= i < len(h._counts):
                raise ValueError(f"bucket index {i} outside ladder")
            h._counts[i] = int(c)
        h._count = int(data.get("count", 0))
        h._sum = float(data.get("sum", 0.0))
        exact = data.get("exact")
        h._exact = ([float(x) for x in exact]
                    if isinstance(exact, list) else None)
        ex = data.get("exemplars")
        if isinstance(ex, dict):
            for key, ids in ex.items():
                try:
                    i = int(key)
                except (TypeError, ValueError):
                    continue
                if 0 <= i < len(h._counts) and isinstance(ids, list):
                    h._exemplars[i] = [str(x) for x in
                                       ids[-DEFAULT_EXEMPLAR_K:]]
        return h

    def to_export(self) -> dict:
        """The Prometheus-facing shape: CUMULATIVE ``(le, count)`` pairs
        (zero-delta interior edges elided; +Inf always present) + sum +
        count — what ``render_exposition(histograms=...)`` consumes."""
        with self._lock:
            buckets: list[tuple[float, int]] = []
            cum = 0
            for i, c in enumerate(self._counts):
                if i > self.n:
                    break
                cum += c
                if c:  # elide zero-delta edges: cumulative stays valid
                    buckets.append((self.bound(i), cum))
            buckets.append((math.inf, self._count))
            return {"buckets": buckets, "sum": self._sum,
                    "count": self._count}


class Histograms:
    """Name → :class:`Histogram` registry riding the telemetry hub.

    ``observe(name, value)`` creates the histogram on first use (ladder
    kwargs apply then only — later observes reuse the existing ladder);
    thread-safe like the counters registry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[str, Histogram] = {}

    def observe(self, name: str, value: float, n: int = 1,
                exemplar: str | None = None, **ladder) -> None:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram(**ladder))
        h.observe(value, n, exemplar=exemplar)

    def get(self, name: str) -> Histogram | None:
        return self._hists.get(name)

    def quantile(self, name: str, q: float) -> float | None:
        """Quantile of one histogram, or None when absent/empty."""
        h = self._hists.get(name)
        if h is None or h.count == 0:
            return None
        return h.quantile(q)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._hists)

    def snapshot(self, compact: bool = False) -> dict[str, dict]:
        """Point-in-time ``{name: to_dict()}`` — the heartbeat /
        cross-restart composition payload (``compact`` drops the exact
        lists; see :meth:`Histogram.to_dict`)."""
        with self._lock:
            hists = dict(self._hists)
        return {name: h.to_dict(compact=compact)
                for name, h in sorted(hists.items())}

    def export(self) -> dict[str, dict]:
        """``{name: to_export()}`` for the Prometheus encoder."""
        with self._lock:
            hists = dict(self._hists)
        return {name: h.to_export() for name, h in sorted(hists.items())}


class NullHistograms(Histograms):
    """Inert registry for disabled telemetry (the NullCounters rule:
    instrumented code observes unconditionally, a disabled hub
    swallows)."""

    def observe(self, name: str, value: float, n: int = 1,
                exemplar: str | None = None, **ladder) -> None:
        pass


# ---------------------------------------------------------------------
# snapshot-level helpers: the cross-restart composition primitives the
# sidecar and supervisor use on plain dicts (no live Histogram needed)
# ---------------------------------------------------------------------


def merge_snapshots(total: dict | None, snaps: dict | None) -> dict:
    """Bucket-wise fold of ``snaps`` (name → to_dict) into ``total``
    (same shape; returns a NEW dict).  A per-name ladder mismatch keeps
    whichever side carries more observations — cross-restart composition
    must degrade, never crash a scrape."""
    out = {name: dict(snap) for name, snap in (total or {}).items()}
    for name, snap in (snaps or {}).items():
        if not isinstance(snap, dict):
            continue
        if name not in out:
            out[name] = dict(snap)
            continue
        try:
            merged = Histogram.from_dict(out[name]).merge(
                Histogram.from_dict(snap))
            out[name] = merged.to_dict()
        except (ValueError, KeyError, TypeError):
            if int(snap.get("count", 0)) > int(out[name].get("count", 0)):
                out[name] = dict(snap)
    return out


def snapshot_from_export(series: dict,
                         lo: float = DEFAULT_LO,
                         per_decade: int = DEFAULT_PER_DECADE,
                         decades: int = DEFAULT_DECADES) -> dict | None:
    """Scraped Prometheus histogram series (``histogram_series`` shape:
    cumulative ``(le, count)`` pairs + sum + count) → a ``to_dict``
    snapshot on the given ladder, or None when the ``le`` edges are not
    this ladder's (a foreign histogram must not be resampled into a
    fabricated distribution — the fleet collector stores it as scalars
    only).  The inverse of :meth:`Histogram.to_export` modulo the elided
    zero-delta edges, which is what lets a collector that only ever saw
    the text exposition still merge windows with :func:`merge_snapshots`."""
    h = Histogram(lo=lo, decades=decades, per_decade=per_decade)
    buckets = series.get("buckets") or []
    prev_cum = 0
    for le, cum in buckets:
        if math.isinf(le):
            i = h.n + 1
        else:
            if le <= 0:
                return None
            e = math.log10(le / h.lo) * h.per_decade
            i = round(e)
            if not 0 <= i <= h.n or abs(h.bound(i) - le) > 1e-9 * le:
                return None  # not this ladder's edge
        delta = int(cum) - prev_cum
        if delta < 0:
            return None  # cumulative counts must not decrease
        prev_cum = int(cum)
        if delta:
            h._counts[i] += delta
    h._count = int(series.get("count") or prev_cum)
    h._sum = float(series.get("sum") or 0.0)
    h._exact = None  # the exposition never carries raw samples
    return h.to_dict()


def export_snapshots(snaps: dict | None) -> dict[str, dict]:
    """Snapshot dicts → Prometheus export shape; unparseable entries are
    skipped (a foreign/hand-edited file must not take the scrape down)."""
    out: dict[str, dict] = {}
    for name, snap in (snaps or {}).items():
        try:
            out[name] = Histogram.from_dict(snap).to_export()
        except (ValueError, KeyError, TypeError):
            continue
    return out


# ---------------------------------------------------------------------
# selfcheck: the run_lint.sh gate (`obs hist --selfcheck`)
# ---------------------------------------------------------------------


def selfcheck(render=None, parse=None) -> list[str]:
    """Prove the histogram math ([] = healthy):

    * exact small-N path: quantiles of ≤ exact_cap observations are
      nearest-rank EXACT;
    * known-distribution bucket path: p50/p95/p99 of a deterministic
      exponential sample within the documented ``r - 1`` error bound of
      the offline exact quantiles;
    * merge associativity + all-at-once equivalence (bucket counts,
      count, sum, quantiles);
    * cross-restart composition round trip: to_dict → JSON →
      merge_snapshots equals the directly-merged histogram;
    * (when the CLI passes the prometheus encoder/parser) export →
      render → parse round trip preserves the +Inf count.
    """
    import json as _json
    import random

    problems: list[str] = []

    # ---- exact small-N -------------------------------------------------
    rng = random.Random(0)
    small = [rng.uniform(1e-4, 1e-1) for _ in range(100)]
    h = Histogram()
    for v in small:
        h.observe(v)
    s = sorted(small)
    for q in (0.5, 0.95, 0.99):
        exact = s[max(1, math.ceil(q * len(s))) - 1]
        if h.quantile(q) != exact:
            problems.append(f"small-N p{q * 100:g} {h.quantile(q)} != "
                            f"exact {exact}")

    # ---- known distribution, bucket path ------------------------------
    big = [rng.expovariate(1 / 0.01) for _ in range(5000)]
    hb = Histogram()
    for v in big:
        hb.observe(v)
    if hb._exact is not None:
        problems.append("5000 observations did not overflow the exact cap")
    sb = sorted(big)
    bound = hb.quantile_error_bound()
    for q in (0.5, 0.95, 0.99):
        exact = sb[max(1, math.ceil(q * len(sb))) - 1]
        got = hb.quantile(q)
        rel = abs(got - exact) / exact
        if rel > bound:
            problems.append(
                f"bucket-path p{q * 100:g} off by {rel:.1%} "
                f"(> documented bound {bound:.1%}): {got} vs exact {exact}")

    # ---- merge associativity ------------------------------------------
    parts = [big[0::3], big[1::3], big[2::3]]
    hs = []
    for part in parts:
        hh = Histogram()
        for v in part:
            hh.observe(v)
        hs.append(hh)

    def build(vals):
        hh = Histogram()
        for v in vals:
            hh.observe(v)
        return hh

    left = build(parts[0]).merge(build(parts[1])).merge(build(parts[2]))
    right = build(parts[2]).merge(build(parts[1])).merge(build(parts[0]))
    if left._counts != right._counts or left.count != right.count:
        problems.append("merge is not associative/commutative on counts")
    if not math.isclose(left.sum, right.sum, rel_tol=1e-9):
        problems.append("merge is not associative on sums")
    if left._counts != hb._counts or left.count != hb.count:
        problems.append("merged thirds != all-at-once histogram")
    for q in (0.5, 0.99):
        if left.quantile(q) != hb.quantile(q):
            problems.append(f"merged p{q * 100:g} != all-at-once")

    # ---- cross-restart composition round trip -------------------------
    snap_a = {"lat": hs[0].to_dict()}
    snap_b = {"lat": hs[1].to_dict()}
    composed = merge_snapshots(_json.loads(_json.dumps(snap_a)),
                               _json.loads(_json.dumps(snap_b)))
    direct = build(parts[0]).merge(build(parts[1]))
    back = Histogram.from_dict(composed["lat"])
    if back._counts != direct._counts or back.count != direct.count:
        problems.append("cross-restart snapshot composition != direct "
                        "merge")
    if back.quantile(0.99) != direct.quantile(0.99):
        problems.append("composed snapshot p99 != direct merge p99")
    # ladder mismatch must degrade (keep the bigger side), not raise
    odd = {"lat": Histogram(lo=1e-3).to_dict()}
    try:
        kept = merge_snapshots(snap_a, odd)["lat"]
        if kept["count"] != snap_a["lat"]["count"]:
            problems.append("ladder-mismatch compose dropped the bigger "
                            "side")
    except ValueError:
        problems.append("ladder-mismatch compose raised instead of "
                        "degrading")

    # ---- exposition round trip (CLI passes the prometheus half) -------
    if render is not None and parse is not None:
        body = render({}, None, up=True,
                      histograms={"lat": hb.to_export()})
        try:
            samples = parse(body)
        except ValueError as e:
            problems.append(f"histogram exposition did not parse: {e}")
        else:
            inf_rows = [v for name, labels, v in samples
                        if name == "estorch_lat_bucket"
                        and labels.get("le") == "+Inf"]
            if inf_rows != [float(hb.count)]:
                problems.append(
                    f"+Inf bucket {inf_rows} != count {hb.count}")
            counts = [v for name, _l, v in samples
                      if name == "estorch_lat_count"]
            if counts != [float(hb.count)]:
                problems.append(f"_count sample {counts} != {hb.count}")
    return problems
