"""Cross-process trace assembly: fleet segments → one Perfetto timeline.

``python -m estorch_tpu.obs trace --fleet DIR...`` (or, on a wedged-jax
host, ``python estorch_tpu/obs/agg/traces.py``) is the assembly half of
distributed tracing (docs/observability.md "Distributed tracing";
``obs/tracing.py`` is the per-process half): every hop of a sampled
request — the router's ``route`` span and per-attempt ``upstream`` legs
(retries and BOTH hedge legs, the loser marked cancelled), the replica's
``request`` span with its ``queue_wait``/``coalesce``/``compute``/
``write`` children, the batcher's per-dispatch ``batch`` span — lands in
that process's ``traces.jsonl``, and this module joins them by trace id
into one timeline:

* per-process LANES (Perfetto process rows), one thread row per
  assembled trace, every segment an ``X`` duration event placed on the
  wall-clock axis (``ts`` is the cross-process alignment key — the
  per-process monotonic marks share no epoch);
* cross-process parent→child hand-offs drawn as FLOW ARROWS (``s``/``f``
  pairs): router leg → replica request, so a hedged request reads as one
  picture — two arrows leaving the router, the loser's lane ending in a
  cancelled leg;
* the output passes ``validate_trace`` (obs/export/traceevent.py), the
  same schema gate every other exporter answers to.

Inputs: ``--fleet`` takes run dirs (each holding a ``traces.jsonl``),
parent dirs of such dirs (a fleet workdir — every child dir is
scanned), or segment files directly; ``--store`` reads the
``traces-<target>.jsonl`` files the collector scraped off the fleet's
``/traces?since=`` endpoints — assembly from the store alone, no access
to the replicas' disks.  Foreign lines, torn tails, and trace ids that
never cross a process boundary degrade to smaller output, never a
crash.

``obs slow --store DIR [--quantile Q]`` is the exemplar join: the
stored request histograms carry per-bucket trace-id exemplars
(obs/hist.py), so the worst in-window traces are NAMED, assembled from
the store's scraped segments, and printed with a per-hop breakdown —
"p99 breached, and here is exactly where trace X spent it".

``--selfcheck`` proves the join on a synthetic three-process segment
set (run_lint.sh gate): hedged trace assembled across router + two
replicas with the win attributed and the loser cancelled, flow arrows
present, torn tail tolerated, foreign trace ids isolated, exported
JSON schema-clean.

Stdlib-only, jax-free, file-runnable — the sidecar discipline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import zlib

if __package__:
    from ..export.traceevent import validate_trace, write_trace
    from ..tracing import TRACES_FILENAME, valid_segment
    from .store import SeriesStore
else:  # file-run (wedged-jax host): load siblings without package init
    import importlib.util

    def _load(name: str, *rel: str):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            *rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _traceevent = _load("_estorch_obs_traceevent", os.pardir, "export",
                        "traceevent.py")
    _tracing = _load("_estorch_obs_tracing", os.pardir, "tracing.py")
    _store_mod = _load("_estorch_obs_agg_store", "store.py")
    validate_trace = _traceevent.validate_trace
    write_trace = _traceevent.write_trace
    TRACES_FILENAME = _tracing.TRACES_FILENAME
    valid_segment = _tracing.valid_segment
    SeriesStore = _store_mod.SeriesStore

# collector-scraped per-target segment files in a store root
TRACE_FILE_PREFIX = "traces-"
# metric names the exemplar join tries, in preference order: the
# router's end-to-end route histogram sees the whole hop chain; a
# router-less fleet still has the replicas' request histogram
SLOW_HIST_NAMES = ("estorch_router_route_s", "estorch_serve_request_s")
DEFAULT_SLOW_WINDOW_S = 900.0


def _us(seconds: float) -> float:
    return round(float(seconds) * 1e6, 3)


# ----------------------------------------------------------------- inputs

def trace_files(paths: list[str]) -> list[str]:
    """Segment files named by ``--fleet`` operands: a file is taken as
    is; a dir contributes its own ``traces.jsonl``, every child dir's
    ``traces.jsonl`` (the fleet-workdir case: ``router/``, ``r0/``, …),
    and any collector-scraped ``traces-*.jsonl`` at its top level."""
    out: list[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            continue
        own = os.path.join(p, TRACES_FILENAME)
        if os.path.isfile(own):
            out.append(own)
        try:
            children = sorted(os.listdir(p))
        except OSError:
            children = []
        for name in children:
            child = os.path.join(p, name)
            if (os.path.isfile(child) and name.startswith(TRACE_FILE_PREFIX)
                    and name.endswith(".jsonl")):
                out.append(child)
            elif os.path.isdir(child):
                sub = os.path.join(child, TRACES_FILENAME)
                if os.path.isfile(sub):
                    out.append(sub)
    # stable + deduped: the same file named twice must not double spans
    seen: set[str] = set()
    uniq = []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return uniq


def store_trace_files(store_dir: str) -> list[str]:
    """The collector's scraped segment files in a store root."""
    root = os.path.abspath(store_dir)
    try:
        names = sorted(n for n in os.listdir(root)
                       if n.startswith(TRACE_FILE_PREFIX)
                       and n.endswith(".jsonl"))
    except OSError:
        return []
    return [os.path.join(root, n) for n in names]


def load_segments(files: list[str]) -> list[dict]:
    """Valid segments across files, torn-tail / foreign-line tolerant,
    deduped on (trace_id, proc, span_id) — the same span scraped into
    two files (fleet dir AND store) must not render twice."""
    out: list[dict] = []
    seen: set[tuple] = set()
    for path in files:
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        for ln in text.splitlines():
            if not ln.strip():
                continue
            try:
                row = json.loads(ln)
            except ValueError:
                continue  # torn tail / foreign line
            if not valid_segment(row):
                continue
            key = (row["trace_id"], row["proc"], row["span_id"])
            if key in seen:
                continue
            seen.add(key)
            out.append(row)
    return out


# --------------------------------------------------------------- assembly

def assemble(segments: list[dict]) -> dict[str, dict]:
    """Join segments by trace id → ``{trace_id: trace}`` where a trace
    is ``{"trace_id", "segments" (ts order), "procs" (first-seen order),
    "t0", "dur_s", "sampled"}``.  ``dur_s`` spans the earliest start to
    the latest end across ALL processes (the wall-clock union — what the
    client experienced, retries and hedges included)."""
    by_id: dict[str, list[dict]] = {}
    for s in segments:
        by_id.setdefault(s["trace_id"], []).append(s)
    out: dict[str, dict] = {}
    for tid, segs in by_id.items():
        segs.sort(key=lambda s: (s["ts"], s.get("seq", 0)))
        procs: list[str] = []
        for s in segs:
            if s["proc"] not in procs:
                procs.append(s["proc"])
        t0 = min(s["ts"] for s in segs)
        t1 = max(s["ts"] + s["dur_s"] for s in segs)
        sampled = None
        for s in segs:
            r = (s.get("attrs") or {}).get("sampled")
            if isinstance(r, str):
                sampled = r
                break
        out[tid] = {"trace_id": tid, "segments": segs, "procs": procs,
                    "t0": t0, "dur_s": max(0.0, t1 - t0),
                    "sampled": sampled}
    return out


def _span_index(trace: dict) -> dict[str, dict]:
    return {s["span_id"]: s for s in trace["segments"]}


def cross_process_edges(trace: dict) -> list[tuple[dict, dict]]:
    """(parent, child) segment pairs whose hand-off crosses a process
    boundary — the edges rendered as flow arrows."""
    idx = _span_index(trace)
    edges = []
    for s in trace["segments"]:
        parent = idx.get(s.get("parent_span_id") or "")
        if parent is not None and parent["proc"] != s["proc"]:
            edges.append((parent, s))
    return edges


def export_fleet_trace(traces: list[dict], files: int = 0) -> dict:
    """Assembled traces → one Perfetto trace-event dict: per-process
    lanes (pid per proc), one thread row per trace, cross-process
    hand-offs as flow arrows (see module docstring)."""
    procs: list[str] = []
    for t in traces:
        for p in t["procs"]:
            if p not in procs:
                procs.append(p)
    pid_of = {p: 1000 + i for i, p in enumerate(procs)}
    events: list[dict] = []
    for p in procs:
        events.append({"ph": "M", "name": "process_name",
                       "pid": pid_of[p], "tid": 0, "args": {"name": p}})
    t_base = min((t["t0"] for t in traces), default=0.0)
    for k, t in enumerate(traces):
        tid = k + 1
        for p in t["procs"]:
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid_of[p], "tid": tid,
                           "args": {"name": f"trace {t['trace_id']}"}})
        for s in t["segments"]:
            args = {"trace_id": s["trace_id"], "span_id": s["span_id"],
                    **(s.get("attrs") or {})}
            if s.get("parent_span_id"):
                args["parent_span_id"] = s["parent_span_id"]
            events.append({
                "ph": "X", "name": s["name"], "cat": "trace",
                "ts": _us(max(0.0, s["ts"] - t_base)),
                "dur": _us(s["dur_s"]),
                "pid": pid_of[s["proc"]], "tid": tid, "args": args,
            })
        for parent, child in cross_process_edges(t):
            # one arrow per hand-off; Chrome binds flows on identical
            # (cat, id, name), and the id must be an int — derive it
            # from the child span (unique per edge by construction)
            fid = zlib.crc32(
                f"{t['trace_id']}/{child['span_id']}".encode()) & 0x7FFFFFFF
            for ph, seg in (("s", parent), ("f", child)):
                ev = {"ph": ph, "id": fid, "name": t["trace_id"],
                      "cat": "hop", "ts": _us(max(0.0, seg["ts"] - t_base)),
                      "pid": pid_of[seg["proc"]], "tid": tid}
                if ph == "f":
                    ev["bp"] = "e"
                events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "exporter": "estorch_tpu.obs trace --fleet",
            "traces": len(traces),
            "procs": procs,
            "files": files,
        },
    }


# ------------------------------------------------------------- formatting

_NOTE_KEYS = ("status", "replica", "attempt", "attempts", "hedge",
              "cancelled", "error", "bucket", "n", "sampled")


def _notes(attrs: dict) -> str:
    parts = []
    for k in _NOTE_KEYS:
        if k in (attrs or {}):
            v = attrs[k]
            if isinstance(v, bool):
                if v:
                    parts.append(k)
            else:
                parts.append(f"{k}={v}")
    return " ".join(parts)


def format_trace(trace: dict) -> str:
    """Human per-hop breakdown of one assembled trace: offset from the
    trace start, duration, process, span name, and the attrs that
    explain the hop (status, replica, hedge/cancelled, sampling
    reason)."""
    head = (f"trace {trace['trace_id']}  "
            f"{trace['dur_s'] * 1e3:.1f}ms  "
            f"procs={','.join(trace['procs'])}"
            + (f"  sampled={trace['sampled']}" if trace["sampled"]
               else ""))
    lines = [head]
    for s in trace["segments"]:
        off = (s["ts"] - trace["t0"]) * 1e3
        note = _notes(s.get("attrs") or {})
        lines.append(f"  +{off:8.1f}ms {s['dur_s'] * 1e3:9.1f}ms  "
                     f"{s['proc']:<16} {s['name']:<12}"
                     + (f"  {note}" if note else ""))
    return "\n".join(lines)


# ------------------------------------------------------------- slow join

def slowest_traces(store_dir: str, quantile: float = 0.99,
                   window_s: float = DEFAULT_SLOW_WINDOW_S,
                   limit: int = 5) -> dict:
    """The ``obs slow`` body: exemplar trace ids above the quantile from
    the STORED request histograms, joined against the store's scraped
    segments.  Returns ``{"metric", "quantile", "q_s", "ids",
    "traces" (assembled, worst first), "missing" (exemplar ids with no
    scraped segments)}`` — everything from the store alone."""
    store = SeriesStore(store_dir)
    # the store is written by another process: derive "now" from the
    # data, not the wall clock (a post-mortem store must still answer)
    now = 0.0
    for row in store._iter_rows(0.0):
        now = max(now, float(row["ts"]))
    metric, hist = None, None
    for name in SLOW_HIST_NAMES:
        h = store.hist_window(name, window_s=window_s, now=now)
        if h is not None and h.count > 0:
            metric, hist = name, h
            break
    if hist is None:
        return {"metric": None, "quantile": quantile, "q_s": None,
                "ids": [], "traces": [], "missing": []}
    ids = hist.slow_exemplars(q=quantile)
    assembled = assemble(load_segments(store_trace_files(store_dir)))
    traces, missing = [], []
    for tid in ids:
        t = assembled.get(tid)
        if t is not None:
            traces.append(t)
        else:
            missing.append(tid)
    traces.sort(key=lambda t: -t["dur_s"])
    return {"metric": metric, "quantile": quantile,
            "q_s": hist.quantile(quantile), "ids": ids[:limit],
            "traces": traces[:limit], "missing": missing}


# -------------------------------------------------------------- selfcheck

def _synth_segment(tid, span, parent, proc, name, ts, dur, **attrs):
    return {"trace_id": tid, "span_id": span, "parent_span_id": parent,
            "proc": proc, "name": name, "t0_mono": ts, "dur_s": dur,
            "ts": ts, "seq": 1, "attrs": attrs}


def selfcheck() -> list[str]:
    """Prove the assembly on a synthetic three-process fleet ([] =
    healthy; run_lint.sh gate): a hedged trace whose segments span
    router + two replicas must join into one trace with both upstream
    legs (loser cancelled, win attributed), export with cross-process
    flow arrows and a schema-clean validate, tolerate a torn tail, and
    keep a foreign trace id isolated in its own assembly."""
    import tempfile

    problems: list[str] = []
    base = 1_700_000_000.0
    hedge = [
        _synth_segment("t-hedge", "router.1", None, "router", "route",
                       base, 0.080, status=200, replica="r0", attempts=1,
                       sampled="hedge"),
        _synth_segment("t-hedge", "router.2", "router.1", "router",
                       "upstream", base + 0.001, 0.060, replica="r0",
                       attempt=0, hedge=False, status=200),
        _synth_segment("t-hedge", "router.3", "router.1", "router",
                       "upstream", base + 0.030, 0.045, replica="r1",
                       attempt=0, hedge=True, cancelled=True,
                       error="cancelled"),
        _synth_segment("t-hedge", "server-a.1", "router.2", "server-a",
                       "request", base + 0.004, 0.050, status=200),
        _synth_segment("t-hedge", "server-a.2", "server-a.1", "server-a",
                       "compute", base + 0.010, 0.030, bucket=2, n=1),
        _synth_segment("t-hedge", "server-b.1", "router.3", "server-b",
                       "request", base + 0.033, 0.020, status=200),
    ]
    baseline = [
        _synth_segment("t-base", "router.4", None, "router", "route",
                       base + 1.0, 0.010, status=200, sampled="head"),
        _synth_segment("t-base", "router.5", "router.4", "router",
                       "upstream", base + 1.001, 0.008, replica="r0",
                       attempt=0, status=200),
        _synth_segment("t-base", "server-a.3", "router.5", "server-a",
                       "request", base + 1.002, 0.006, status=200),
    ]
    foreign = [
        _synth_segment("t-foreign", "server-b.9", None, "server-b",
                       "request", base + 2.0, 0.004, status=200),
    ]
    with tempfile.TemporaryDirectory() as d:
        by_proc = {"router": [], "r0": [], "r1": []}
        for s in hedge + baseline:
            by_proc[{"router": "router", "server-a": "r0",
                     "server-b": "r1"}[s["proc"]]].append(s)
        by_proc["r1"].extend(foreign)
        for name, segs in by_proc.items():
            os.makedirs(os.path.join(d, name))
            with open(os.path.join(d, name, TRACES_FILENAME), "w") as f:
                for s in segs:
                    f.write(json.dumps(s) + "\n")
        # torn tail + foreign line on one file: a crash artifact and a
        # stray log line must degrade, never crash the join
        with open(os.path.join(d, "r0", TRACES_FILENAME), "a") as f:
            f.write("not json at all\n")
            f.write('{"trace_id": "t-torn", "span_id": "x", "pr')

        files = trace_files([d])
        if len(files) != 3:
            problems.append(f"expected 3 segment files under the fleet "
                            f"dir, found {len(files)}: {files}")
        assembled = assemble(load_segments(files))
        th = assembled.get("t-hedge")
        if th is None:
            return problems + ["hedged trace did not assemble"]
        if th["procs"] != ["router", "server-a", "server-b"]:
            problems.append(f"hedged trace procs wrong: {th['procs']}")
        legs = [s for s in th["segments"] if s["name"] == "upstream"]
        if len(legs) != 2:
            problems.append(f"expected both hedge legs, got {len(legs)}")
        else:
            cancelled = [s for s in legs
                         if (s["attrs"] or {}).get("cancelled")]
            winners = [s for s in legs
                       if (s["attrs"] or {}).get("status") == 200]
            if len(cancelled) != 1 or len(winners) != 1:
                problems.append(
                    f"win attribution wrong: {len(winners)} winner(s), "
                    f"{len(cancelled)} cancelled")
        if th["sampled"] != "hedge":
            problems.append(f"sampling reason lost: {th['sampled']!r}")
        if "t-foreign" not in assembled:
            problems.append("foreign trace id vanished entirely")
        elif assembled["t-foreign"]["procs"] != ["server-b"]:
            problems.append("foreign trace leaked across processes")
        if "t-torn" in assembled:
            problems.append("torn tail line assembled as a segment")

        ordered = sorted(assembled.values(), key=lambda t: t["t0"])
        trace = export_fleet_trace(ordered, files=len(files))
        schema = validate_trace(trace)
        if schema:
            problems.append(f"exported trace fails validate_trace: "
                            f"{schema[:3]}")
        flows = [e for e in trace["traceEvents"] if e["ph"] in ("s", "f")]
        pids = {e["pid"] for e in flows}
        # hedge: router→server-a and router→server-b; baseline:
        # router→server-a — three edges = six flow events, across ≥2 pids
        if len(flows) != 6:
            problems.append(f"expected 6 flow events (3 cross-process "
                            f"edges), got {len(flows)}")
        if len(pids) < 2:
            problems.append("flow arrows do not cross process lanes")
        out = os.path.join(d, "fleet_trace.json")
        write_trace(trace, out)
        try:
            with open(out) as f:
                json.load(f)
        except ValueError as e:
            problems.append(f"written trace is not valid JSON: {e}")
        text = format_trace(th)
        if "cancelled" not in text or "server-b" not in text:
            problems.append("per-hop breakdown loses the cancelled "
                            "hedge leg")
    return problems


# ------------------------------------------------------------------- CLI

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs trace",
        description="assemble fleet trace segments into one Perfetto "
                    "timeline (docs/observability.md, 'Distributed "
                    "tracing')")
    p.add_argument("--fleet", nargs="*", metavar="DIR",
                   help="run dirs / fleet workdirs / segment files "
                        "holding traces.jsonl")
    p.add_argument("--store", metavar="DIR",
                   help="collector store root: assemble from the scraped "
                        "traces-<target>.jsonl files instead")
    p.add_argument("--trace-id", action="append", default=None,
                   metavar="ID", help="assemble only these trace ids "
                                      "(repeatable; default: all)")
    p.add_argument("-o", "--out", default=None, metavar="PATH",
                   help="output path (default: fleet_trace.json beside "
                        "the first input)")
    p.add_argument("--print", action="store_true", dest="do_print",
                   help="also print each assembled trace's per-hop "
                        "breakdown")
    p.add_argument("--selfcheck", action="store_true",
                   help="prove the assembly on a synthetic 3-process "
                        "segment set and exit")
    return p


def build_slow_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs slow",
        description="worst stored traces via histogram exemplars "
                    "(docs/observability.md, 'Distributed tracing')")
    p.add_argument("--store", required=True, metavar="DIR",
                   help="collector store root")
    p.add_argument("--quantile", type=float, default=0.99, metavar="Q",
                   help="exemplars at/above this stored quantile "
                        "(default 0.99)")
    p.add_argument("--window", type=float, default=DEFAULT_SLOW_WINDOW_S,
                   metavar="S", help="stored-history window in seconds")
    p.add_argument("--limit", type=int, default=5, metavar="N",
                   help="show at most N traces (default 5)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable result on stdout")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.selfcheck:
        problems = selfcheck()
        if problems:
            for pr in problems:
                print(f"trace selfcheck: {pr}", file=sys.stderr)
            return 1
        print("obs trace selfcheck: OK (3-process hedged trace assembled "
              "with both legs and the win attributed, cross-process flow "
              "arrows validate, torn tail tolerated, foreign trace ids "
              "isolated)")
        return 0
    if bool(args.fleet) == bool(args.store):
        print("trace assembly needs exactly one of --fleet DIR... / "
              "--store DIR (or --selfcheck)", file=sys.stderr)
        return 3
    files = (trace_files(args.fleet) if args.fleet
             else store_trace_files(args.store))
    if not files:
        print("trace: no segment files found (nothing sampled yet, or "
              "wrong dir?)", file=sys.stderr)
        return 2
    assembled = assemble(load_segments(files))
    if args.trace_id:
        missing = [t for t in args.trace_id if t not in assembled]
        for t in missing:
            print(f"note: trace id {t!r} not in the segment files",
                  file=sys.stderr)
        assembled = {k: v for k, v in assembled.items()
                     if k in set(args.trace_id)}
    if not assembled:
        print("trace: no assembled traces", file=sys.stderr)
        return 1
    ordered = sorted(assembled.values(), key=lambda t: t["t0"])
    trace = export_fleet_trace(ordered, files=len(files))
    problems = validate_trace(trace)
    if problems:  # exporter bug, not user error — still fail loudly
        for pr in problems:
            print(f"trace: invalid output: {pr}", file=sys.stderr)
        return 1
    first = args.fleet[0] if args.fleet else args.store
    out = args.out or os.path.join(
        first if os.path.isdir(first)
        else os.path.dirname(os.path.abspath(first)), "fleet_trace.json")
    write_trace(trace, out)
    cross = sum(len(cross_process_edges(t)) for t in ordered)
    print(f"trace: {len(ordered)} trace(s) across "
          f"{len(trace['otherData']['procs'])} process(es), "
          f"{cross} cross-process hop(s), {len(files)} file(s) -> {out}")
    if args.do_print:
        for t in ordered:
            print(format_trace(t))
    return 0


def main_slow(argv: list[str] | None = None) -> int:
    args = build_slow_parser().parse_args(argv)
    if not 0.5 <= args.quantile < 1.0:
        print("slow: --quantile must be in [0.5, 1)", file=sys.stderr)
        return 3
    result = slowest_traces(args.store, quantile=args.quantile,
                            window_s=args.window, limit=args.limit)
    if args.as_json:
        print(json.dumps({**result,
                          "traces": [{k: v for k, v in t.items()}
                                     for t in result["traces"]]},
                         default=float))
        return 0 if result["traces"] else 1
    if result["metric"] is None:
        print("slow: no stored request histogram in the window (is the "
              "collector running against this store?)", file=sys.stderr)
        return 1
    q_ms = (result["q_s"] or 0.0) * 1e3
    print(f"slow: {result['metric']} p{args.quantile * 100:g} = "
          f"{q_ms:.1f}ms, {len(result['ids'])} exemplar(s) above it")
    for t in result["traces"]:
        print(format_trace(t))
    for tid in result["missing"]:
        print(f"  {tid}: exemplar known, but no scraped segments in the "
              "store (dropped by the source sampler, or scrape lag)")
    if not result["traces"] and not result["missing"]:
        print("  (no exemplars recorded yet)")
    return 0 if result["traces"] else 1


if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv[:1] == ["slow"]:
        sys.exit(main_slow(argv[1:]))
    sys.exit(main(argv))
