"""Fleet metrics collector: scrape N targets, store history, evaluate SLOs.

``python -m estorch_tpu.obs collect --targets targets.json --store D``
(or, on a wedged-jax host, ``python estorch_tpu/obs/agg/collector.py``)
runs the loop every per-process telemetry surface presupposed but nobody
owned: each tick it scrapes every configured target — serve servers'
``/metrics``, run-dir sidecars, bare run directories — through the SAME
validating parser the doctor uses (``obs/export/prometheus.py``), lands
every sample in the local time-series store (``store.py``) tagged with a
``target`` label, and evaluates the declarative SLO rules
(``rules.py``), appending firing/resolved transitions to the alerts
ledger.

Targets file (``targets.json``)::

    {"schema": 1, "interval_s": 2.0, "targets": [
      {"name": "serve-a", "url": "http://127.0.0.1:8321/metrics",
       "timeout_s": 2.0},
      {"name": "run-1", "run_dir": "runs/r1"}
    ]}

``url`` targets are Prometheus text-exposition endpoints; ``run_dir``
targets are scraped in-process through the sidecar's composition rules
(heartbeat + supervisor-published ``counters.json``), so a training run
is a first-class fleet member without running a sidecar at all.

Fault containment (the reason this is a daemon, not a cron of curls):

* every scrape runs in its own thread with a PER-TARGET timeout — a
  dead, slow, or garbage-spewing target costs its own slot, never the
  tick (a target whose scrape is still in flight at the next tick is
  skipped, not doubled);
* a failed scrape bumps the target's consecutive-failure count and
  synthesizes ``estorch_up{target=...} 0`` into the store, so the
  absence rule and the dash see the SAME down verdict the scrape saw —
  no separate bookkeeping to drift;
* a garbage body is a parse ERROR (the validating parser refuses it),
  counted like a refused connection — blessing garbage would be the
  false health check the parser exists to prevent.

The collector is itself a fleet citizen: its own ``/metrics`` exposes
tick/sample/error counters plus per-target up/failure/latency gauges,
``/alerts`` serves the active alert set + recent ledger transitions as
JSON, and ``/healthz`` answers collector liveness.

Stdlib-only; importable and runnable WITHOUT the package (file-run mode
loads its siblings by path, the sidecar discipline) — the fleet plane
must keep answering while jax is wedged.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

if __package__:
    from ..export.prometheus import (histogram_series, metric_name,
                                     parse_exposition, render_exposition)
    from ..export.sidecar import (compose_hists, compose_totals,
                                  read_published_counters)
    from ..hist import export_snapshots, snapshot_from_export
    from ..recorder import STALE_AFTER_S, read_heartbeat
    from ..tracing import valid_segment
    from .rules import (LEDGER_FILENAME, RulesEngine, load_rules,
                        read_ledger)
    from .store import SeriesStore
else:  # file-run (wedged-jax host): load siblings without any package init
    import importlib.util

    def _load(name: str, *rel: str):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            *rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _prom = _load("_estorch_obs_prometheus", os.pardir, "export",
                  "prometheus.py")
    _sidecar = _load("_estorch_obs_sidecar", os.pardir, "export",
                     "sidecar.py")
    _hist = _load("_estorch_obs_hist", os.pardir, "hist.py")
    _recorder = _load("_estorch_obs_recorder", os.pardir, "recorder.py")
    _tracing = _load("_estorch_obs_tracing", os.pardir, "tracing.py")
    _store = _load("_estorch_obs_agg_store", "store.py")
    _rules = _load("_estorch_obs_agg_rules", "rules.py")
    histogram_series = _prom.histogram_series
    metric_name = _prom.metric_name
    parse_exposition = _prom.parse_exposition
    render_exposition = _prom.render_exposition
    compose_hists = _sidecar.compose_hists
    compose_totals = _sidecar.compose_totals
    read_published_counters = _sidecar.read_published_counters
    export_snapshots = _hist.export_snapshots
    snapshot_from_export = _hist.snapshot_from_export
    STALE_AFTER_S = _recorder.STALE_AFTER_S
    read_heartbeat = _recorder.read_heartbeat
    valid_segment = _tracing.valid_segment
    SeriesStore = _store.SeriesStore
    RulesEngine = _rules.RulesEngine
    load_rules = _rules.load_rules
    read_ledger = _rules.read_ledger
    LEDGER_FILENAME = _rules.LEDGER_FILENAME

TARGETS_SCHEMA = 1
DEFAULT_INTERVAL_S = 2.0
DEFAULT_TIMEOUT_S = 2.0
# collector-side per-target trace segment files (store root):
# traces-<target>.jsonl, joined by `obs trace --store` / `obs slow`
TRACE_FILE_PREFIX = "traces-"
TRACE_FILE_MAX_LINES = 20_000


def trace_file_path(store_root: str, target: str) -> str:
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", target)
    return os.path.join(store_root, f"{TRACE_FILE_PREFIX}{safe}.jsonl")


def append_segments(path: str, segments: list[dict],
                    max_lines: int = TRACE_FILE_MAX_LINES) -> int:
    """Append valid segments to a per-target trace file, atomically
    (tmp + replace), capped to the newest ``max_lines`` — a reader mid-
    scrape sees the old file or the new one, never a torn middle.
    Returns how many segments were kept."""
    rows = [json.dumps(s, sort_keys=True) for s in segments
            if valid_segment(s)]
    if not rows:
        return 0
    try:
        with open(path) as f:
            old = f.read().splitlines()
    except OSError:
        old = []
    keep = (old + rows)[-max_lines:]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(keep) + "\n")
    os.replace(tmp, path)
    return len(rows)


def traces_url(metrics_url: str) -> str:
    """The fleet convention: a target exposing ``/metrics`` exposes its
    sampled trace segments at ``/traces`` on the same listener."""
    parts = urllib.parse.urlsplit(metrics_url)
    return urllib.parse.urlunsplit(
        (parts.scheme, parts.netloc, "/traces", "", ""))


def scrape_traces(metrics_url: str, since: int,
                  timeout_s: float = DEFAULT_TIMEOUT_S) -> dict:
    """One ``/traces?since=<cursor>`` scrape → the payload dict
    (``obs/tracing.py`` :func:`traces_payload` shape).  Raises on any
    failure — the CALLER decides that traces are best-effort."""
    url = f"{traces_url(metrics_url)}?since={int(since)}"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        payload = json.loads(resp.read().decode(errors="replace"))
    if not isinstance(payload, dict) \
            or not isinstance(payload.get("segments"), list):
        raise ValueError("malformed /traces payload")
    return payload


class Target:
    """One scrape target (see module docstring for the JSON shape)."""

    __slots__ = ("name", "kind", "url", "run_dir", "timeout_s",
                 "stale_after_s")

    def __init__(self, name: str, *, url: str | None = None,
                 run_dir: str | None = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 stale_after_s: float = STALE_AFTER_S):
        if bool(url) == bool(run_dir):
            raise ValueError(
                f"target {name!r} needs exactly one of url / run_dir")
        self.name = str(name)
        self.kind = "prometheus" if url else "run_dir"
        self.url = url
        self.run_dir = run_dir
        self.timeout_s = float(timeout_s)
        self.stale_after_s = float(stale_after_s)


def validate_targets(obj) -> list[str]:
    """Structural problems of a parsed targets file ([] when clean)."""
    problems: list[str] = []
    if not isinstance(obj, dict) or obj.get("schema") != TARGETS_SCHEMA:
        return [f"targets file must be an object with "
                f"schema={TARGETS_SCHEMA}"]
    targets = obj.get("targets")
    if not isinstance(targets, list) or not targets:
        return ["targets must be a non-empty list"]
    seen: set[str] = set()
    for i, t in enumerate(targets):
        where = f"targets[{i}]"
        if not isinstance(t, dict):
            problems.append(f"{where}: not an object")
            continue
        name = t.get("name")
        if not name or not isinstance(name, str):
            problems.append(f"{where}: missing name")
        elif name in seen:
            problems.append(f"{where}: duplicate name {name!r}")
        else:
            seen.add(name)
        if bool(t.get("url")) == bool(t.get("run_dir")):
            problems.append(f"{where}: exactly one of url / run_dir")
    return problems


def load_targets(path: str) -> tuple[list[Target], float]:
    """Parse + validate a targets file → (targets, interval_s)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"{path}: unreadable targets file: {e}") from e
    problems = validate_targets(obj)
    if problems:
        raise ValueError(f"{path}: " + "; ".join(problems))
    base = os.path.dirname(os.path.abspath(path))
    targets = []
    for t in obj["targets"]:
        run_dir = t.get("run_dir")
        if run_dir and not os.path.isabs(run_dir):
            run_dir = os.path.join(base, run_dir)
        targets.append(Target(
            t["name"], url=t.get("url"), run_dir=run_dir,
            timeout_s=float(t.get("timeout_s", DEFAULT_TIMEOUT_S)),
            stale_after_s=float(t.get("stale_after_s", STALE_AFTER_S))))
    return targets, float(obj.get("interval_s", DEFAULT_INTERVAL_S))


# ---------------------------------------------------------------- scrape

def samples_from_exposition(text: str, target: str) -> list[dict]:
    """Parsed exposition → store samples tagged ``target``.

    Scalar samples store as values; histogram series (``_bucket`` /
    ``_sum`` / ``_count``) collapse into ONE snapshot sample per base
    (``obs/hist.py`` to_dict shape via :func:`snapshot_from_export`) so
    stored windows merge bucket-wise instead of being resampled.  A
    histogram on a foreign bucket ladder degrades to nothing (its
    ``_count`` survives as a scalar) rather than fabricating a
    distribution.  Raises ValueError on a malformed body — garbage is a
    scrape FAILURE, not data."""
    samples = parse_exposition(text)  # ValueError on malformed lines
    hist_bases = set(histogram_series(samples))
    out: list[dict] = []
    for name, labels, value in samples:
        base = None
        for suffix in ("_bucket", "_sum"):
            if name.endswith(suffix) and name[: -len(suffix)] in hist_bases:
                base = name[: -len(suffix)]
        if base is not None:
            continue  # folded into the snapshot below (counts kept)
        out.append({"name": name,
                    "labels": {"target": target, **labels},
                    "value": value})
    for base, series in histogram_series(samples).items():
        snap = snapshot_from_export(series)
        if snap is not None:
            out.append({"name": base, "labels": {"target": target},
                        "hist": snap})
    return out


def scrape_prometheus(url: str, target: str,
                      timeout_s: float = DEFAULT_TIMEOUT_S) -> list[dict]:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        body = resp.read().decode(errors="replace")
    return samples_from_exposition(body, target)


def scrape_run_dir(run_dir: str, target: str,
                   stale_after_s: float = STALE_AFTER_S) -> list[dict]:
    """Scrape a run directory in-process through the sidecar composition
    rules, rendered + re-parsed so BOTH target kinds flow through the
    one validating parser (a composition bug fails the scrape here, not
    silently downstream)."""
    hb = read_heartbeat(os.path.join(run_dir, "heartbeat.json"))
    published = read_published_counters(run_dir)
    if hb is None and published is None:
        raise ValueError(f"no heartbeat.json or counters.json in "
                         f"{run_dir!r}")
    totals = compose_totals(published, hb)
    hists = compose_hists(published, hb)
    body = render_exposition(totals, hb, stale_after_s=stale_after_s,
                             histograms=export_snapshots(hists) or None)
    return samples_from_exposition(body, target)


class _TargetState:
    __slots__ = ("consecutive_failures", "last_error", "last_scrape_s",
                 "last_ok_ts", "inflight", "trace_cursor")

    def __init__(self):
        self.consecutive_failures = 0
        self.last_error: str | None = None
        self.last_scrape_s: float | None = None
        self.last_ok_ts: float | None = None
        self.inflight = False
        # /traces?since= high-water mark; reset to 0 when the target's
        # cursor goes BACKWARD (process restart — seq starts over)
        self.trace_cursor = 0


class Collector:
    """The scrape/store/evaluate loop plus its own HTTP plane."""

    def __init__(self, targets: list[Target], store: SeriesStore,
                 rules: RulesEngine | None = None, *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 host: str = "127.0.0.1", port: int = 0,
                 serve_http: bool = True, scrape_traces: bool = True):
        self.targets = list(targets)
        self.store = store
        self.rules = rules
        self.interval_s = float(interval_s)
        self.scrape_traces = bool(scrape_traces)
        self.counters: dict[str, float] = {
            "agg_ticks_total": 0, "agg_samples_stored_total": 0,
            "agg_scrape_errors_total": 0, "agg_alert_transitions_total": 0,
            "agg_trace_segments_total": 0,
            "agg_trace_scrape_errors_total": 0,
        }
        self._states = {t.name: _TargetState() for t in self.targets}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd = None
        if serve_http:
            self._httpd = _AggHttpd((host, int(port)), _make_handler(self))
            self.host, self.port = self._httpd.server_address[:2]

    # ------------------------------------------------------------- tick

    def _scrape_one(self, t: Target) -> list[dict]:
        if t.kind == "prometheus":
            return scrape_prometheus(t.url, t.name, timeout_s=t.timeout_s)
        return scrape_run_dir(t.run_dir, t.name,
                              stale_after_s=t.stale_after_s)

    def _land_traces(self, t: Target, state: _TargetState,
                     r: dict) -> int:
        """Land one successful scrape's /traces payload: segments append
        to the store root's ``traces-<target>.jsonl`` (what ``obs trace
        --store`` / ``obs slow`` assemble), bucket exemplars are grafted
        onto this tick's stored histogram snapshots (Prometheus text
        cannot carry them), and the cursor advances — backward movement
        means the target restarted, so restart from 0.  Returns how many
        segments landed."""
        if r.get("trace_error"):
            self.counters["agg_trace_scrape_errors_total"] += 1
            return 0
        payload = r.get("traces")
        if not payload:
            return 0
        kept = 0
        segs = payload.get("segments") or []
        if segs:
            kept = append_segments(
                trace_file_path(self.store.root, t.name), segs)
            self.counters["agg_trace_segments_total"] += kept
        cursor = int(payload.get("cursor") or 0)
        state.trace_cursor = cursor if cursor >= state.trace_cursor else 0
        exemplars = payload.get("exemplars") or {}
        if isinstance(exemplars, dict):
            by_metric = {metric_name(name): ex
                         for name, ex in exemplars.items()
                         if isinstance(ex, dict)}
            for sample in r["samples"]:
                ex = by_metric.get(sample["name"])
                if ex is not None and isinstance(sample.get("hist"), dict):
                    sample["hist"]["exemplars"] = ex
        return kept

    def tick(self, now: float | None = None) -> dict:
        """One collection round: scrape every target (bounded, parallel),
        store the samples, evaluate the rules.  Returns a summary dict
        (per-target ok/error + transitions) for callers that drive ticks
        themselves (tests, the doctor probe)."""
        now = time.time() if now is None else float(now)
        results: dict[str, dict] = {}
        res_lock = threading.Lock()

        def scrape(t: Target, state: _TargetState) -> None:
            t0 = time.perf_counter()
            try:
                samples = self._scrape_one(t)
                err = None
            except Exception as e:  # noqa: BLE001 — any failure mode
                # (refused, timeout, garbage, missing files) is the same
                # verdict: this target did not produce a scrape
                samples, err = None, f"{type(e).__name__}: {e}"
            traces, terr = None, None
            if (err is None and self.scrape_traces
                    and t.kind == "prometheus"):
                # best-effort second fetch on the same listener: trace
                # segments + histogram exemplars ride /traces?since= —
                # a missing endpoint degrades tracing, never the scrape
                try:
                    traces = scrape_traces(t.url, state.trace_cursor,
                                           timeout_s=t.timeout_s)
                except Exception as e:  # noqa: BLE001 — same envelope
                    terr = f"{type(e).__name__}: {e}"
            dt = time.perf_counter() - t0
            with res_lock:
                results[t.name] = {"samples": samples, "error": err,
                                   "traces": traces, "trace_error": terr,
                                   "elapsed_s": dt}
            # handshake with the next tick's skip-if-stuck check — the
            # collector lock orders this against tick's read+set
            with self._lock:
                state.inflight = False

        threads = []
        budget = max((t.timeout_s for t in self.targets),
                     default=DEFAULT_TIMEOUT_S) + 1.0
        for t in self.targets:
            state = self._states[t.name]
            # test-and-set under the collector lock: a zombie scrape
            # clearing the flag concurrently must not double-spawn
            with self._lock:
                stuck, state.inflight = state.inflight, True
            if stuck:
                # previous scrape still stuck past its own timeout: skip
                # this round rather than stacking threads on a zombie
                with res_lock:
                    results[t.name] = {"samples": None, "elapsed_s": 0.0,
                                       "error": "previous scrape still "
                                                "in flight"}
                continue
            th = threading.Thread(target=scrape, args=(t, state),
                                  name=f"agg-scrape-{t.name}", daemon=True)
            th.start()
            threads.append(th)
        deadline = time.perf_counter() + budget
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.perf_counter()))

        batch: list[dict] = []
        for t in self.targets:
            state = self._states[t.name]
            r = results.get(t.name)
            if r is None or r.get("samples") is None:
                err = (r or {}).get("error") or "scrape timed out"
                state.consecutive_failures += 1
                state.last_error = err
                self.counters["agg_scrape_errors_total"] += 1
                # the down verdict lands in the SAME store the rules and
                # dash read — one source of truth for "this replica died"
                batch.append({"name": "estorch_up",
                              "labels": {"target": t.name}, "value": 0.0})
                results[t.name] = {"ok": False, "error": err}
            else:
                state.consecutive_failures = 0
                state.last_error = None
                state.last_ok_ts = now
                state.last_scrape_s = r["elapsed_s"]
                segs = self._land_traces(t, state, r)
                batch.extend(r["samples"])
                results[t.name] = {"ok": True,
                                   "samples": len(r["samples"]),
                                   "segments": segs,
                                   "elapsed_s": round(r["elapsed_s"], 4)}
        with self._lock:
            self.store.append(batch, ts=now)
        self.counters["agg_ticks_total"] += 1
        self.counters["agg_samples_stored_total"] += len(batch)
        transitions: list[dict] = []
        if self.rules is not None:
            transitions = self.rules.evaluate(
                self.store, [t.name for t in self.targets], now)
            self.counters["agg_alert_transitions_total"] += len(transitions)
        return {"ts": now, "targets": results, "stored": len(batch),
                "transitions": transitions}

    def run(self, max_ticks: int | None = None) -> int:
        """The daemon loop: tick, then sleep the interval remainder.
        Stops after ``max_ticks`` (None = until :meth:`stop`)."""
        done = 0
        while not self._stop.is_set():
            t0 = time.perf_counter()
            self.tick()
            done += 1
            if max_ticks is not None and done >= max_ticks:
                break
            remaining = self.interval_s - (time.perf_counter() - t0)
            if remaining > 0 and self._stop.wait(remaining):
                break
        return done

    def stop(self) -> None:
        self._stop.set()

    # -------------------------------------------------------- HTTP plane

    def metrics(self) -> str:
        """The collector's own exposition: flat counters via the shared
        encoder, then per-target labeled gauges (one TYPE block each)."""
        body = render_exposition(dict(self.counters), None, up=True)
        lines = [body.rstrip("\n")]

        def esc(v: str) -> str:
            return (str(v).replace("\\", r"\\").replace("\n", r"\n")
                    .replace('"', r'\"'))

        gauges = (
            ("agg_target_up", "1 while the last scrape of the target "
                              "succeeded",
             lambda st: 0.0 if st.consecutive_failures else 1.0),
            ("agg_target_consecutive_failures", "scrapes failed in a row",
             lambda st: float(st.consecutive_failures)),
            ("agg_target_scrape_seconds", "duration of the last "
                                          "successful scrape",
             lambda st: float(st.last_scrape_s or 0.0)),
        )
        for name, help_, get in gauges:
            metric = metric_name(name)
            lines.append(f"# HELP {metric} {help_}")
            lines.append(f"# TYPE {metric} gauge")
            for t in self.targets:
                st = self._states[t.name]
                lines.append(f'{metric}{{target="{esc(t.name)}"}} '
                             f"{get(st):g}")
        return "\n".join(lines) + "\n"

    def alerts(self) -> dict:
        ledger_path = (self.rules.ledger_path
                       if self.rules is not None else None)
        return {
            "active": self.rules.active() if self.rules is not None else [],
            "transitions": (read_ledger(ledger_path, tail=50)
                            if ledger_path else []),
        }

    def health(self) -> dict:
        return {
            "ok": True,
            "targets": {
                t.name: {
                    "kind": t.kind,
                    "up": self._states[t.name].consecutive_failures == 0
                          and self._states[t.name].last_ok_ts is not None,
                    "consecutive_failures":
                        self._states[t.name].consecutive_failures,
                    **({"error": self._states[t.name].last_error}
                       if self._states[t.name].last_error else {}),
                } for t in self.targets
            },
            "ticks": int(self.counters["agg_ticks_total"]),
        }

    def start_background(self) -> threading.Thread | None:
        if self._httpd is None:
            return None
        self._serving = True
        th = threading.Thread(target=self._httpd.serve_forever,
                              kwargs={"poll_interval": 0.1},
                              name="agg-http", daemon=True)
        th.start()
        return th

    def close(self) -> None:
        self.stop()
        if self._httpd is not None:
            if getattr(self, "_serving", False):
                self._httpd.shutdown()
            self._httpd.server_close()


class _AggHttpd(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


def _make_handler(collector: Collector):
    class AggHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def _reply(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                self._reply(200, collector.metrics().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/alerts":
                self._reply(200, json.dumps(collector.alerts(),
                                            default=float).encode(),
                            "application/json")
            elif self.path == "/healthz":
                self._reply(200, json.dumps(collector.health()).encode(),
                            "application/json")
            else:
                self._reply(404, json.dumps(
                    {"error": f"no route {self.path!r}"}).encode(),
                    "application/json")

    return AggHandler


# ------------------------------------------------------------- selfcheck

def selfcheck() -> list[str]:
    """End-to-end proof on synthetic targets ([] = healthy): a healthy
    exposition target, a garbage target, and a dead port under one
    collector — every tick survives the dead/garbage targets, samples
    land in the store, the absence rule fires for the dead pair, an
    injected latency spike breaches the burn-rate rule NAMING the
    target, stored quantiles match the source histogram within the
    documented ladder bound, and the collector's own /metrics and
    /alerts parse.  Stdlib only, ~seconds."""
    import socket
    import tempfile

    if __package__:
        from ..hist import Histogram
    else:
        Histogram = _hist.Histogram

    problems: list[str] = []
    hist = Histogram()
    counters = {"requests_total": 0}

    class Fake(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            body = render_exposition(
                dict(counters), None, up=True,
                extra_gauges={"queue_depth": 1.0},
                histograms={"serve/request_s": hist.to_export()}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class Garbage(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"<html>definitely not an exposition</html>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    fake = ThreadingHTTPServer(("127.0.0.1", 0), Fake)
    junk = ThreadingHTTPServer(("127.0.0.1", 0), Garbage)
    for srv in (fake, junk):
        threading.Thread(target=srv.serve_forever, daemon=True).start()
    # bound-but-not-listening: connects get RST for the whole selfcheck
    # (closing it would let the allocator hand the port to the collector
    # itself, and the "dead" target would scrape something alive)
    dead_sock = socket.socket()
    dead_sock.bind(("127.0.0.1", 0))
    dead_port = dead_sock.getsockname()[1]

    with tempfile.TemporaryDirectory() as d:
        col = None
        try:
            store = SeriesStore(os.path.join(d, "store"), max_segments=4)
            rules = RulesEngine([
                {"name": "replica-down", "kind": "absence",
                 "metric": "estorch_up", "for_s": 0, "window_s": 30},
                {"name": "queue-deep", "kind": "threshold",
                 "metric": "estorch_queue_depth", "op": ">", "value": 100,
                 "for_s": 0, "window_s": 30},
                {"name": "p99-slo", "kind": "burn_rate",
                 "metric": "estorch_serve_request_s", "quantile": 0.99,
                 "slo_s": 0.05,
                 "windows": [{"window_s": 60}, {"window_s": 10}]},
            ], ledger_path=os.path.join(d, LEDGER_FILENAME))
            targets = [
                Target("good",
                       url=f"http://127.0.0.1:{fake.server_address[1]}"
                           "/metrics", timeout_s=2.0),
                Target("garbage",
                       url=f"http://127.0.0.1:{junk.server_address[1]}"
                           "/metrics", timeout_s=2.0),
                Target("dead", url=f"http://127.0.0.1:{dead_port}/metrics",
                       timeout_s=0.5),
            ]
            col = Collector(targets, store, rules, interval_s=0.1, port=0)
            col.start_background()

            for v in (0.010, 0.012, 0.011, 0.013):
                hist.observe(v)
            counters["requests_total"] = 4
            t0 = time.perf_counter()
            now = time.time()
            r1 = col.tick(now)
            tick_s = time.perf_counter() - t0
            if tick_s > 5.0:
                problems.append(f"tick stalled on dead/garbage targets: "
                                f"{tick_s:.1f}s")
            if not r1["targets"]["good"]["ok"]:
                problems.append(f"healthy target failed: {r1}")
            for bad in ("garbage", "dead"):
                if r1["targets"][bad].get("ok"):
                    problems.append(f"{bad} target scraped OK?!")
            fired = {(t["rule"], t["target"])
                     for t in r1["transitions"] if t["event"] == "firing"}
            for bad in ("garbage", "dead"):
                if ("replica-down", bad) not in fired:
                    problems.append(
                        f"absence rule did not fire for {bad!r}: {fired}")
            if ("replica-down", "good") in fired:
                problems.append("absence rule fired for the healthy "
                                "target")
            if ("p99-slo", "good") in fired:
                problems.append("burn-rate fired on healthy latency")

            # inject the latency spike, scrape again: burn-rate must fire
            # naming the target, and the stored quantile must match the
            # source histogram within the documented ladder bound
            for _ in range(400):
                hist.observe(0.250)
            counters["requests_total"] = 404
            r2 = col.tick(now + 1.0)
            burn = [t for t in r2["transitions"]
                    if t["rule"] == "p99-slo" and t["event"] == "firing"]
            if not burn or burn[0]["target"] != "good":
                problems.append(f"burn-rate did not fire naming the "
                                f"target: {r2['transitions']}")
            elif "estorch_serve_request_s" not in burn[0]["detail"]:
                problems.append(f"burn-rate detail does not name the "
                                f"metric: {burn[0]}")
            got = store.quantile("estorch_serve_request_s", 0.99,
                                 {"target": "good"}, window_s=60,
                                 now=now + 1.0)
            want = hist.quantile(0.99)
            bound = hist.quantile_error_bound()
            if got is None or abs(got - want) > want * bound + 1e-12:
                problems.append(f"stored p99 {got} vs source {want} "
                                f"outside ladder bound {bound:.1%}")
            up = store.latest("estorch_up", {"target": "dead"},
                              window_s=60, now=now + 1.0)
            if not up or list(up.values())[-1][2] != 0.0:
                problems.append(f"dead target's estorch_up not stored "
                                f"as 0: {up}")

            # the collector's own plane must parse/serve
            with urllib.request.urlopen(
                    f"http://{col.host}:{col.port}/metrics",
                    timeout=10) as resp:
                own = resp.read().decode()
            try:
                parse_exposition(own)
            except ValueError as e:
                problems.append(f"collector /metrics does not parse: {e}")
            if 'estorch_agg_target_up{target="dead"} 0' not in own:
                problems.append("per-target up gauge missing from "
                                "collector /metrics")
            with urllib.request.urlopen(
                    f"http://{col.host}:{col.port}/alerts",
                    timeout=10) as resp:
                alerts = json.loads(resp.read().decode())
            active = {(a["rule"], a["target"]) for a in alerts["active"]}
            if ("p99-slo", "good") not in active \
                    or ("replica-down", "dead") not in active:
                problems.append(f"/alerts active set wrong: {active}")
            if not any(t["event"] == "firing"
                       for t in alerts["transitions"]):
                problems.append("/alerts carries no ledger transitions")

            # junk rules/targets files must be refused with a diagnosis
            bad = os.path.join(d, "bad.json")
            with open(bad, "w") as f:
                json.dump({"schema": 1, "rules": [{"kind": "nope"}]}, f)
            refused = False
            try:
                load_rules(bad)
            except ValueError:
                refused = True
            if not refused:
                problems.append("junk rules file accepted")
            with open(bad, "w") as f:
                json.dump({"schema": 1, "targets": [{"name": "x"}]}, f)
            refused = False
            try:
                load_targets(bad)
            except ValueError:
                refused = True
            if not refused:
                problems.append("junk targets file accepted")
        except Exception as e:  # noqa: BLE001 — the lint gate's
            # contract is one problem line + exit 1, never a traceback
            problems.append(f"unexpected selfcheck failure: {e!r}")
        finally:
            if col is not None:
                col.close()
            dead_sock.close()
            fake.shutdown(), fake.server_close()
            junk.shutdown(), junk.server_close()
    return problems


# ------------------------------------------------------------------ CLI

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs collect",
        description="fleet metrics collector (docs/observability.md, "
                    "'Fleet aggregation')")
    p.add_argument("--targets", metavar="PATH",
                   help="targets.json (required unless --selfcheck)")
    p.add_argument("--store", metavar="DIR",
                   help="time-series store root (required unless "
                        "--selfcheck)")
    p.add_argument("--rules", default=None, metavar="PATH",
                   help="rules.json — SLO/alert rules evaluated each tick")
    p.add_argument("--interval", type=float, default=None,
                   help="collection interval seconds (default: the "
                        "targets file's interval_s, else "
                        f"{DEFAULT_INTERVAL_S})")
    p.add_argument("--ticks", type=int, default=None, metavar="N",
                   help="stop after N ticks (default: run until SIGTERM)")
    p.add_argument("--once", action="store_true",
                   help="one tick, then exit (alias for --ticks 1)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="collector's own /metrics //alerts port "
                        "(0 = ephemeral)")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="atomically write {host,port,pid} JSON once bound")
    p.add_argument("--selfcheck", action="store_true",
                   help="prove the scrape/store/rules loop on synthetic "
                        "targets and exit")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.selfcheck:
        problems = selfcheck()
        if problems:
            for pr in problems:
                print(f"collect selfcheck: {pr}", file=sys.stderr)
            return 1
        print("obs collect selfcheck: OK (dead/garbage targets tolerated "
              "per tick, absence + burn-rate rules fire naming the "
              "target, stored quantiles within the ladder bound, "
              "/metrics and /alerts parse)")
        return 0
    if not args.targets or not args.store:
        print("collect needs --targets and --store (or --selfcheck)",
              file=sys.stderr)
        return 3
    try:
        targets, file_interval = load_targets(args.targets)
    except ValueError as e:
        print(f"collect: {e}", file=sys.stderr)
        return 2
    store = SeriesStore(args.store)
    rules = None
    if args.rules:
        try:
            rules = load_rules(args.rules)
        except ValueError as e:
            print(f"collect: {e}", file=sys.stderr)
            return 2
        rules.ledger_path = os.path.join(os.path.abspath(args.store),
                                         LEDGER_FILENAME)
        os.makedirs(args.store, exist_ok=True)
        # adopt still-firing alerts from a previous collector's ledger so
        # a restart emits the missing resolved (or keeps firing) instead
        # of forgetting — /alerts and the dash must agree after restarts
        rules.seed_from_ledger()
    interval = args.interval if args.interval is not None else file_interval
    col = Collector(targets, store, rules, interval_s=interval,
                    host=args.host, port=args.port)
    col.start_background()
    print(json.dumps({"ready": True,
                      "url": f"http://{col.host}:{col.port}",
                      "targets": [t.name for t in col.targets],
                      "store": store.root, "pid": os.getpid()}),
          flush=True)
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": col.host, "port": col.port,
                       "pid": os.getpid()}, f)
        os.replace(tmp, args.port_file)
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: col.stop())
    ticks = 1 if args.once else args.ticks
    done = col.run(max_ticks=ticks)
    col.close()
    print(json.dumps({"done": True, "ticks": done}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
