"""``obs dash`` — the fleet on one terminal screen.

Renders the collector's STORED history (never a live endpoint — the
whole point of the store is that the dash works while a replica is dead)
as a per-target table:

    target     up   gen    req p50/p99 ms   disp p99 ms   queue   recomp   alerts
    serve-a    UP    -        1.2 / 4.8          -            0        2    -
    serve-b    DOWN  -          -  /  -          -            -        -    replica-down
    run-1      UP    41         -  /  -         3.1           -        0    -

Columns come from the stored metric names every surface already exports:
``estorch_up`` (liveness), ``estorch_heartbeat_generation`` (training
progress), ``estorch_serve_request_s`` (request-latency histogram →
p50/p99), ``estorch_async_fold_latency_s`` (dispatch-to-fold p99 for
training runs), ``estorch_queue_depth``, ``estorch_recompiles``
(windowed increase, reset-aware), plus the active alerts from the
ledger.  Missing metrics render as ``-`` — a training run has no
request latencies and a serve replica has no generations, and the dash
must say so rather than fabricate.  The ``slowest`` column names the
worst in-window trace id from the latency histogram's bucket exemplars
(docs/observability.md "Distributed tracing") — paste it into ``obs
slow --store`` for the per-hop breakdown; targets exporting no
exemplars render ``-``.

Autoscaled router targets (obs/agg/autoscale.py) get two more columns,
derived from the store + the append-only decision log alone: ``scale``
is desired-vs-actual replicas (``3→5`` while the fleet converges, a
bare count once it has), ``scale age`` is seconds since the last
decision event in ``autoscale_decisions.jsonl``.  Non-autoscaled
targets render ``-`` in both.

``--once`` prints one frame (scriptable, CI-friendly); ``--watch N``
redraws every N seconds until interrupted.

Stdlib-only, file-runnable (``python estorch_tpu/obs/agg/dash.py``) —
the wedged-host discipline shared with the sidecar and collector.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __package__:
    from .autoscale import DECISIONS_FILENAME, read_decisions
    from .rules import (LEDGER_FILENAME, LEDGER_MAX_TRANSITIONS,
                        read_ledger)
    from .store import SeriesStore
else:  # file-run: load siblings without any package init
    import importlib.util

    def _load(name: str, fname: str):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            fname)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _store = _load("_estorch_obs_agg_store", "store.py")
    _rules = _load("_estorch_obs_agg_rules", "rules.py")
    _autoscale = _load("_estorch_obs_agg_autoscale", "autoscale.py")
    SeriesStore = _store.SeriesStore
    read_ledger = _rules.read_ledger
    LEDGER_FILENAME = _rules.LEDGER_FILENAME
    LEDGER_MAX_TRANSITIONS = _rules.LEDGER_MAX_TRANSITIONS
    DECISIONS_FILENAME = _autoscale.DECISIONS_FILENAME
    read_decisions = _autoscale.read_decisions

REQUEST_HIST = "estorch_serve_request_s"
DISPATCH_HIST = "estorch_async_fold_latency_s"
ROUTE_HIST = "estorch_router_route_s"


def _fmt_ms(v: float | None) -> str:
    return f"{v * 1e3:.1f}" if v is not None else "-"


def _slowest_trace(store, metric: str, labels: dict, window_s: float,
                   now: float) -> str | None:
    """Worst exemplar trace id at/above the stored p99 bucket, or None
    when the window's histogram carries no exemplars."""
    h = store.hist_window(metric, labels, window_s, now)
    if h is None or h.count == 0:
        return None
    ids = h.slow_exemplars(q=0.99)
    return ids[0] if ids else None


def _fmt_num(v: float | None) -> str:
    if v is None:
        return "-"
    return f"{int(v)}" if float(v) == int(v) else f"{v:g}"


def fleet_snapshot(store_root: str, *, window_s: float = 60.0,
                   now: float | None = None,
                   store: "SeriesStore | None" = None) -> dict:
    """The dash's data model: per-target rows + active alerts, all from
    the store + ledger (machine-readable half of :func:`render`).

    Pass ``store`` to reuse one :class:`SeriesStore` across frames —
    watch mode does, so sealed segments stay memoized instead of being
    re-parsed on every redraw."""
    now = time.time() if now is None else float(now)
    store = SeriesStore(store_root) if store is None else store
    targets = store.label_values("estorch_up", "target", window_s, now)
    # active = fired and not since resolved, reconstructed from the
    # ledger so the dash needs no live collector to agree with /alerts;
    # the tail matches the ledger's own compaction bound — a shorter
    # read could drop an old still-firing transition and show resolved
    active: dict[tuple[str, str], dict] = {}
    for t in read_ledger(os.path.join(store_root, LEDGER_FILENAME),
                         tail=LEDGER_MAX_TRANSITIONS):
        key = (str(t.get("rule")), str(t.get("target")))
        if t.get("event") == "firing":
            active[key] = t
        elif t.get("event") == "resolved":
            active.pop(key, None)
    # autoscaler state: last decision event per target from the
    # append-only log — the dash needs no live autoscaler, the log +
    # store ARE the source of truth (obs/agg/autoscale.py)
    last_decision: dict[str, dict] = {}
    for ev in read_decisions(os.path.join(store_root,
                                          DECISIONS_FILENAME)):
        last_decision[str(ev.get("target"))] = ev
    rows = []
    for name in targets:
        labels = {"target": name}

        def latest(metric: str) -> float | None:
            got = store.latest(metric, labels, window_s, now)
            if not got:
                return None
            return max(got.values(), key=lambda x: x[0])[2]

        up = latest("estorch_up")
        # front-router targets (serve/router.py) export per-replica
        # labeled gauges; their presence IS the router detection, and
        # the columns come from the store alone like everything else
        replica_up = store.latest("estorch_router_replica_up", labels,
                                  window_s, now)
        router = None
        if replica_up:
            breaker = store.latest("estorch_router_breaker_state",
                                   labels, window_s, now)
            p99 = store.latest("estorch_router_upstream_p99_s", labels,
                               window_s, now)
            replicas = {}
            for _ts, lab, v in replica_up.values():
                replicas[str(lab.get("replica"))] = {"up": v == 1.0}
            for _ts, lab, v in (breaker or {}).values():
                replicas.setdefault(str(lab.get("replica")), {})[
                    "breaker"] = int(v)
            for _ts, lab, v in (p99 or {}).values():
                name_r = str(lab.get("replica"))
                if v == v:  # NaN = no samples yet
                    replicas.setdefault(name_r, {})["p99_s"] = v
            router = {
                "replicas": replicas,
                # only OPEN (2) alarms: half-open (1) is the normal
                # readmission probe, not a down replica
                "breakers_open": sum(
                    1 for r in replicas.values()
                    if r.get("breaker", 0) == 2),
                "retries": store.increase("estorch_router_retries_total",
                                          labels, window_s, now),
                "hedge_wins": store.increase(
                    "estorch_router_hedge_wins_total", labels, window_s,
                    now),
                "worst_p99_s": max(
                    (r["p99_s"] for r in replicas.values()
                     if "p99_s" in r), default=None),
            }
        # autoscale columns: desired from the router's exported gauge
        # (falls back to the last decision's verdict), actual from the
        # replica_up gauges, age from the decision log — None for
        # non-autoscaled targets so render shows '-'
        autoscale = None
        dec = last_decision.get(name)
        desired_g = latest("estorch_router_desired_replicas")
        if router is not None and (dec is not None
                                   or desired_g is not None):
            desired = (int(desired_g) if desired_g is not None
                       else (dec.get("verdict") or {}).get("desired"))
            autoscale = {
                "desired": desired,
                "actual": sum(1 for _ts, _lab, v in replica_up.values()
                              if v == 1.0),
                "last_decision_ts": dec["ts"] if dec else None,
                "decision_age_s": (round(now - float(dec["ts"]), 3)
                                   if dec else None),
                "last_action": ((dec.get("verdict") or {}).get("action")
                                if dec else None),
            }
        rows.append({
            "target": name,
            "up": bool(up == 1.0),
            "generation": latest("estorch_heartbeat_generation"),
            # cold-start health (serve replicas publish startup_s /
            # compiles_at_load gauges; a training run honestly has none)
            "startup_s": latest("estorch_startup_s"),
            "compiles_at_load": latest("estorch_compiles_at_load"),
            # a router target's client-facing latency is its route_s
            # histogram; a replica's is serve_request_s — same column
            "req_p50_s": store.quantile(
                ROUTE_HIST if router else REQUEST_HIST, 0.50, labels,
                window_s, now),
            "req_p99_s": store.quantile(
                ROUTE_HIST if router else REQUEST_HIST, 0.99, labels,
                window_s, now),
            "dispatch_p99_s": store.quantile(DISPATCH_HIST, 0.99, labels,
                                             window_s, now),
            # the worst in-window trace id from the latency histogram's
            # bucket exemplars (obs/hist.py) — `obs slow --store` turns
            # it into a per-hop breakdown; None when the target exports
            # no exemplars (old process, tracing off)
            "slowest_trace": _slowest_trace(
                store, ROUTE_HIST if router else REQUEST_HIST, labels,
                window_s, now),
            "queue_depth": latest("estorch_queue_depth"),
            "recompiles": store.increase("estorch_recompiles", labels,
                                         window_s, now),
            # elastic multi-host coordinators (docs/multihost.md) export
            # membership + per-host fold-latency gauges; training runs
            # without a fleet — and every serve target — honestly lack
            # them and render '-'
            "elastic_hosts": latest("estorch_elastic_hosts"),
            "host_fold_p99_s": latest("estorch_elastic_fold_p99_worst_s"),
            "hosts_lost": store.increase("estorch_hosts_lost", labels,
                                         window_s, now),
            "router": router,
            "autoscale": autoscale,
            "alerts": sorted(rule for (rule, tgt) in active
                             if tgt == name),
        })
    return {"ts": now, "window_s": float(window_s), "targets": rows,
            "active_alerts": [
                {"rule": rule, "target": tgt,
                 "detail": ev.get("detail", "")}
                for (rule, tgt), ev in sorted(active.items())]}


def render(store_root: str, *, window_s: float = 60.0,
           now: float | None = None,
           store: "SeriesStore | None" = None) -> str:
    """One human frame of the fleet (see module docstring)."""
    snap = fleet_snapshot(store_root, window_s=window_s, now=now,
                          store=store)
    header = ("target", "up", "gen", "cold", "req p50/p99 ms",
              "disp p99 ms", "hosts", "host p99 ms", "queue", "recomp",
              "brk", "retry", "hedge", "repl p99", "scale", "scale age",
              "slowest", "alerts")
    table = [header]
    for row in snap["targets"]:
        # cold: startup seconds, suffixed ! when the replica paid fresh
        # XLA builds at load (a warm bundle would have made it 0); -1 is
        # the server's "no monitoring stream, warmth unproven" sentinel —
        # rendered ? so unproven never reads as proven-clean
        cold = "-"
        if row.get("startup_s") is not None:
            cold = f"{row['startup_s']:.1f}s"
            compiles = row.get("compiles_at_load")
            if compiles is not None and compiles > 0:
                cold += f"!{int(compiles)}"
            elif compiles is not None and compiles < 0:
                cold += "?"
        # router columns (serve/router.py targets): open-breaker count
        # over replica total (suffixed ! when any is open), windowed
        # retry / hedge-win increases, and the worst per-replica p99 —
        # non-router targets honestly render '-'
        ro = row.get("router")
        if ro:
            n_open = ro["breakers_open"]
            brk = f"{n_open}/{len(ro['replicas'])}"
            if n_open:
                brk += "!"
            retry = _fmt_num(ro["retries"])
            hedge = _fmt_num(ro["hedge_wins"])
            repl_p99 = _fmt_ms(ro["worst_p99_s"])
        else:
            brk = retry = hedge = repl_p99 = "-"
        # scale: desired vs actual replicas — `3→5` while converging, a
        # bare count once converged; scale age: seconds since the last
        # autoscaler decision — non-autoscaled targets honestly show '-'
        az = row.get("autoscale")
        scale = scale_age = "-"
        if az and az.get("desired") is not None:
            scale = (f"{az['actual']}" if az["actual"] == az["desired"]
                     else f"{az['actual']}→{az['desired']}")
        if az and az.get("decision_age_s") is not None:
            scale_age = f"{az['decision_age_s']:.0f}s"
        # hosts: elastic membership count, suffixed !N when N host
        # deaths landed inside the window (a shrinking fleet should
        # jump out of the table the way open breakers do)
        hosts = "-"
        if row.get("elastic_hosts") is not None:
            hosts = _fmt_num(row["elastic_hosts"])
            lost = row.get("hosts_lost")
            if lost:
                hosts += f"!{int(lost)}"
        table.append((
            row["target"],
            "UP" if row["up"] else "DOWN",
            _fmt_num(row["generation"]),
            cold,
            f"{_fmt_ms(row['req_p50_s'])} / {_fmt_ms(row['req_p99_s'])}",
            _fmt_ms(row["dispatch_p99_s"]),
            hosts,
            _fmt_ms(row["host_fold_p99_s"]),
            _fmt_num(row["queue_depth"]),
            _fmt_num(row["recompiles"]),
            brk, retry, hedge, repl_p99, scale, scale_age,
            # worst in-window trace id — feed it to `obs slow --store`
            # / `obs trace --store --trace-id` for the per-hop story;
            # '-' for targets exporting no exemplars
            row.get("slowest_trace") or "-",
            ",".join(row["alerts"]) or "-",
        ))
    widths = [max(len(str(r[i])) for r in table)
              for i in range(len(header))]
    lines = [f"fleet @ {time.strftime('%H:%M:%S', time.localtime(snap['ts']))}"
             f" (window {snap['window_s']:g}s, {len(snap['targets'])} "
             f"target(s), {len(snap['active_alerts'])} active alert(s))"]
    for j, r in enumerate(table):
        lines.append("  ".join(str(c).ljust(w)
                               for c, w in zip(r, widths)).rstrip())
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    for a in snap["active_alerts"]:
        lines.append(f"ALERT {a['rule']} [{a['target']}]: {a['detail']}")
    if not snap["targets"]:
        lines.append("(no targets in window — is the collector running "
                     "against this store?)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m estorch_tpu.obs dash",
        description="terminal fleet console over a collector store "
                    "(docs/observability.md, 'Fleet aggregation')")
    p.add_argument("--store", required=True, metavar="DIR",
                   help="the collector's --store directory")
    p.add_argument("--window", type=float, default=60.0,
                   help="history window in seconds (default 60)")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (default)")
    p.add_argument("--watch", type=float, default=None, metavar="SECS",
                   help="redraw every SECS seconds until interrupted")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable snapshot instead of the table")
    args = p.parse_args(argv)
    if not os.path.isdir(args.store):
        print(f"dash: no such store dir {args.store!r}", file=sys.stderr)
        return 2

    # ONE store across frames: watch mode redraws every few seconds and
    # the store's sealed-segment memo cache only pays off if it survives
    # the frame loop
    store = SeriesStore(args.store)

    def frame() -> str:
        if args.as_json:
            return json.dumps(fleet_snapshot(args.store,
                                             window_s=args.window,
                                             store=store),
                              default=float)
        return render(args.store, window_s=args.window, store=store)

    if args.watch is None or args.once:
        print(frame())
        return 0
    try:
        while True:
            # ANSI home+clear keeps the frame in place without pulling in
            # curses; harmless when redirected to a file
            sys.stdout.write("\x1b[H\x1b[2J" + frame() + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.watch))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
